"""minilua: a small Lua interpreter for script filters.

The reference's lua subplugin embeds liblua to run user scripts as stream
filters (ext/nnstreamer/tensor_filter/tensor_filter_lua.cc, 591 LoC; the
fixture scripts are tests/test_models/models/passthrough.lua and
scaler.lua).  This image has no Lua runtime, so the TPU framework ships
its own interpreter for the Lua subset those filters use — written from
the Lua 5.x reference manual, not from any Lua implementation:

statements   assignment (incl. table fields), local, function defs,
             numeric for, generic for over pairs/ipairs, while,
             repeat/until, if/elseif/else, return, break, calls
expressions  precedence-climbing: or/and, comparisons, .., + -, * / %,
             unary - not #, ^, calls, colon method calls (strings
             dispatch via the string library), table constructors,
             field/index
values       numbers (int/float), strings, booleans, nil, 1-based
             tables; multiple return values with Lua's expression-list
             adjustment (non-final results truncate to one value, the
             final one expands; conditions take the first value)
stdlib       math.floor/ceil/abs/min/max/sqrt/huge · string.format/sub/
             len/upper/lower/rep/reverse/byte/char/find/match/gmatch/
             gsub with REAL Lua patterns (§6.4.1: classes %a %d %s %w
             %l %u %p %c %x + complements, [sets] with ranges and ^,
             * + - ? quantifiers, ^ $ anchors, captures incl. position
             captures and %1-%9 back-references, %b balanced, %f
             frontier; gsub takes string/function/table replacements
             with %0-%9 escapes and returns (result, count)) ·
             table.insert/remove/concat · tostring · tonumber · # ·
             print · setmetatable/getmetatable/rawget/rawset/type with
             __index (table or function, chained), __newindex, __call,
             AND the operator metamethods __add/__sub/__mul/__div/
             __mod/__pow/__unm/__eq/__lt/__le/__concat (first operand's
             metatable, then the second's, manual §2.8); closures
             capture lexical scope and MUTATE upvalues (the counter
             idiom works).  Not implemented: per-iteration
             loop-variable scoping, coroutines, goto — scripts touching
             those fail with a named LuaError (or behave as documented
             in Env for loop captures).

Execution compiles the AST to Python closures once (scripts run a
nested-loop body per frame — ~1M interpreted ops for the reference's
640×480 scaler — so a tree-walk per eval would be too slow).  Host
integration: callers inject globals (e.g. ``input_tensor``) and read
globals back (``inputTensorsInfo``); numpy-backed objects implementing
``__getitem__``/``__setitem__`` work as 1-based tensor proxies.
"""

from __future__ import annotations

import math
import re
from typing import Any, Callable, Dict, List, Optional, Tuple


class LuaError(RuntimeError):
    pass


# ---------------------------------------------------------------------------
# lexer
# ---------------------------------------------------------------------------

_KEYWORDS = {"and", "break", "do", "else", "elseif", "end", "false", "for",
             "function", "if", "in", "local", "nil", "not", "or", "repeat",
             "return", "then", "true", "until", "while"}

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<comment>--[^\n]*)
  | (?P<num>0[xX][0-9a-fA-F]+
          |(?:\d+\.\d*|\.\d+|\d+)(?:[eE][+-]?\d+)?)
  | (?P<name>[A-Za-z_]\w*)
  | (?P<str>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
  | (?P<op>\.\.|==|~=|<=|>=|[-+*/%^#<>=(){}\[\],;.:])
""", re.VERBOSE)


def _lex(src: str) -> List[Tuple[str, Any]]:
    toks: List[Tuple[str, Any]] = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            raise LuaError(f"lua: bad character {src[pos]!r} at {pos}")
        pos = m.end()
        kind = m.lastgroup
        text = m.group()
        if kind in ("ws", "comment"):
            continue
        if kind == "num":
            if text[:2] in ("0x", "0X"):
                toks.append(("num", int(text, 16)))
            elif "." in text or "e" in text or "E" in text:
                toks.append(("num", float(text)))
            else:
                toks.append(("num", int(text)))
        elif kind == "name":
            toks.append((text, text) if text in _KEYWORDS
                        else ("name", text))
        elif kind == "str":
            body = text[1:-1]
            toks.append(("str", re.sub(
                r"\\(.)",
                lambda m: {"n": "\n", "t": "\t", "r": "\r",
                           "a": "\a", "0": "\0"}.get(m.group(1),
                                                       m.group(1)),
                body)))
        else:
            toks.append((text, text))
    toks.append(("<eof>", None))
    return toks


# ---------------------------------------------------------------------------
# runtime values
# ---------------------------------------------------------------------------

class LuaTable:
    """1-based table: array part + hash part in one dict; optional
    metatable (``__index``/``__newindex``/``__call`` plus the operator
    metamethods ``__add``/``__sub``/``__mul``/``__div``/``__mod``/
    ``__pow``/``__unm``/``__eq``/``__lt``/``__le``/``__concat`` are
    honored — see _BINFN and the unary/power parsers)."""

    __slots__ = ("data", "metatable")

    def __init__(self, data: Optional[Dict[Any, Any]] = None):
        self.data = data or {}
        self.metatable: Optional["LuaTable"] = None

    def get(self, key):
        if isinstance(key, float) and key.is_integer():
            key = int(key)
        return self.data.get(key)

    def set(self, key, value):
        if isinstance(key, float) and key.is_integer():
            key = int(key)
        if value is None:
            # Lua: assigning nil DELETES the entry (pairs/# never see it)
            self.data.pop(key, None)
        else:
            self.data[key] = value

    def length(self) -> int:
        n = 0
        while (n + 1) in self.data:
            n += 1
        return n


class Env:
    """Variable scope: per-call locals chained to the DEFINING scope
    (lexical upvalues), over the shared globals table.

    Lua semantics: reads fall through locals → enclosing function
    locals → globals; PLAIN assignment writes the nearest existing
    binding in that chain (closures MUTATE captured upvalues — the
    counter idiom works), else the GLOBAL; ``local`` and loop control
    variables write the current frame explicitly.  The top-level chunk
    uses the globals table as its locals.  Subset note: a closure
    created inside a loop captures the frame, not a per-iteration
    binding (real Lua scopes loop variables per iteration)."""

    __slots__ = ("locals", "globals", "parent")

    def __init__(self, locals_: Dict[str, Any], globals_: Dict[str, Any],
                 parent: Optional["Env"] = None):
        self.locals = locals_
        self.globals = globals_
        self.parent = parent

    def get(self, name: str):
        e = self
        while e is not None:
            if name in e.locals:
                return e.locals[name]
            e = e.parent
        return self.globals.get(name)

    def set(self, name: str, value) -> None:
        e = self
        while e is not None:
            if name in e.locals:
                e.locals[name] = value
                return
            e = e.parent
        self.globals[name] = value

    def set_local(self, name: str, value) -> None:
        self.locals[name] = value


class _Break(Exception):
    pass


class _Return(Exception):
    def __init__(self, value):
        self.value = value


def _truthy(v) -> bool:
    if isinstance(v, tuple):              # a condition takes the FIRST
        v = v[0] if v else None           # of a multi-value result
    return v is not None and v is not False


def _index(obj, key, _depth=0):
    if _depth > 100:
        raise LuaError("lua: __index chain too deep")
    if isinstance(obj, LuaTable):
        v = obj.get(key)
        if v is None and obj.metatable is not None:
            handler = obj.metatable.get("__index")
            if isinstance(handler, LuaTable):
                return _index(handler, key, _depth + 1)
            if callable(handler):
                return _first(handler(obj, key))
        return v
    if isinstance(obj, str):
        # Lua strings carry a metatable with __index = the string
        # library (lstrlib.c createmetatable): ("x").rep and s:rep(2)
        # BOTH resolve here (mcall routes through _index); any other
        # key — numeric indexing included — is nil, never a Python
        # str.__getitem__ (which leaked a TypeError on string keys,
        # fuzz-found).  Divergence note: the method table is a shared
        # singleton, so a script REPLACING string.fn in its own
        # globals changes neither path (liblua points its string
        # metatable at the state's own string table, so there a
        # replacement affects both).
        if isinstance(key, str):
            return _string_lib().get(key)
        return None
    if hasattr(obj, "__getitem__"):
        if isinstance(key, float) and key.is_integer():
            key = int(key)
        return obj[key]
    raise LuaError(f"lua: cannot index {type(obj).__name__}")


def _first(v):
    """Single-value adjustment: a multi-value result (Python tuple) in a
    scalar position — operator operand, index key/object, parenthesized
    expression, keyed constructor value — takes its FIRST value
    (manual §3.4)."""
    if isinstance(v, tuple):
        return v[0] if v else None
    return v


def _adjust_values(vals: List[Any], n: int) -> List[Any]:
    """Lua multiple-value adjustment for an evaluated expression list:
    a non-final multi-value result (Python tuple) truncates to its first
    value, the FINAL one expands; the list is then padded with nil / cut
    to ``n`` (manual §3.4: expression-list adjustment)."""
    out = _expand_args(vals)
    return [out[i] if i < len(out) else None for i in range(n)]


def _expand_args(vals: List[Any]) -> List[Any]:
    """Call-argument adjustment: final multi-value expands, earlier ones
    truncate to their first value."""
    out: List[Any] = []
    for i, v in enumerate(vals):
        if isinstance(v, tuple):
            if i == len(vals) - 1:
                out.extend(v)
            else:
                out.append(v[0] if v else None)
        else:
            out.append(v)
    return out


def _setindex(obj, key, value, _depth=0):
    if _depth > 100:
        raise LuaError("lua: __newindex chain too deep")
    if isinstance(obj, LuaTable):
        # __newindex fires only for keys ABSENT from the table (manual
        # §2.4); existing keys raw-assign
        if obj.get(key) is None and obj.metatable is not None:
            handler = obj.metatable.get("__newindex")
            if isinstance(handler, LuaTable):
                return _setindex(handler, key, value, _depth + 1)
            if callable(handler):
                handler(obj, key, value)
                return
        obj.set(key, value)
        return
    if hasattr(obj, "__setitem__"):
        if isinstance(key, float) and key.is_integer():
            key = int(key)
        obj[key] = value
        return
    raise LuaError(f"lua: cannot index-assign {type(obj).__name__}")


def _call_value(f, args):
    """Invoke a Lua value: function, or table with a ``__call``
    metamethod (the callable-object pattern)."""
    if callable(f):
        return f(*args)
    if isinstance(f, LuaTable) and f.metatable is not None:
        handler = f.metatable.get("__call")
        if callable(handler):
            return handler(f, *args)
    if f is None:
        raise LuaError("lua: call of nil")
    raise LuaError(f"lua: cannot call a {type(f).__name__} value")


# ---------------------------------------------------------------------------
# parser + closure compiler
# ---------------------------------------------------------------------------

class _Parser:
    """Recursive-descent parser emitting Python closures.

    Compiled expressions are ``fn(env) -> value``; statements are
    ``fn(env) -> None``; ``env`` is the variable scope (function calls
    get a fresh child scope falling back to globals — sufficient for the
    script-filter subset, which uses globals + loop locals)."""

    def __init__(self, toks: List[Tuple[str, Any]]):
        self.toks = toks
        self.i = 0

    # -- token helpers -------------------------------------------------------
    def peek(self) -> str:
        return self.toks[self.i][0]

    def next(self) -> Tuple[str, Any]:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, kind: str) -> Any:
        k, v = self.next()
        if k != kind:
            raise LuaError(f"lua: expected {kind!r}, got {k!r}")
        return v

    def accept(self, kind: str) -> bool:
        if self.peek() == kind:
            self.i += 1
            return True
        return False

    # -- chunk / block -------------------------------------------------------
    def parse_chunk(self) -> Callable[[Dict], None]:
        body = self.block(("<eof>",))
        self.expect("<eof>")
        return body

    def block(self, stops: Tuple[str, ...]) -> Callable[[Dict], None]:
        stmts: List[Callable] = []
        while self.peek() not in stops:
            st = self.statement()
            if st is not None:
                stmts.append(st)

        def run(env):
            for st in stmts:
                st(env)
        return run

    # -- statements ----------------------------------------------------------
    def statement(self) -> Optional[Callable]:
        k = self.peek()
        if k == ";":
            self.next()
            return None
        if k == "local":
            self.next()
            names = [self.expect("name")]
            while self.accept(","):
                names.append(self.expect("name"))
            exprs = []
            if self.accept("="):
                exprs = self.exprlist()

            def local_stmt(env, names=names, exprs=exprs):
                vals = _adjust_values([e(env) for e in exprs], len(names))
                for n, v in zip(names, vals):
                    env.set_local(n, v)
            return local_stmt
        if k == "function":
            self.next()
            name = self.expect("name")
            if self.peek() in (".", ":"):
                # function t.m(...) / function t:m(self-style) — define
                # into a table field; colon form prepends the implicit
                # `self` parameter (Lua manual §3.4.11)
                sep = self.next()[0]
                field = self.expect("name")
                fn = self.function_body(implicit_self=(sep == ":"))

                def mdef(env, name=name, field=field, fn=fn):
                    obj = env.get(name)
                    if obj is None:
                        raise LuaError(
                            f"lua: function {name}.{field}: {name!r} "
                            "is nil")
                    # same assignment rule as `obj.field = fn` (tables
                    # AND host __setitem__ proxies)
                    _setindex(obj, field, fn(env))
                return mdef
            fn = self.function_body()

            def fndef(env, name=name, fn=fn):
                env.set(name, fn(env))
            return fndef
        if k == "for":
            return self.for_stmt()
        if k == "while":
            self.next()
            cond = self.expr()
            self.expect("do")
            body = self.block(("end",))
            self.expect("end")

            def while_stmt(env, cond=cond, body=body):
                while _truthy(cond(env)):
                    try:
                        body(env)
                    except _Break:
                        break
            return while_stmt
        if k == "repeat":
            self.next()
            body = self.block(("until",))
            self.expect("until")
            cond = self.expr()

            def repeat_stmt(env, body=body, cond=cond):
                # body locals stay visible to the until-condition (same
                # env object runs both, per the Lua scoping rule)
                while True:
                    try:
                        body(env)
                    except _Break:
                        break
                    if _truthy(cond(env)):
                        break
            return repeat_stmt
        if k == "if":
            return self.if_stmt()
        if k == "return":
            self.next()
            exprs: List[Callable] = []
            if self.peek() not in ("end", "else", "elseif", "until",
                                   "<eof>", ";"):
                exprs = self.exprlist()

            def ret(env, exprs=tuple(exprs)):
                if not exprs:
                    raise _Return(None)
                if len(exprs) == 1:
                    # single expr: pass through (incl. a callee's own
                    # multi-value tuple — chained returns)
                    raise _Return(exprs[0](env))
                vals = _expand_args([e(env) for e in exprs])
                raise _Return(tuple(vals))
            return ret
        if k == "break":
            self.next()

            def brk(env):
                raise _Break()
            return brk
        return self.expr_or_assign()

    def for_stmt(self) -> Callable:
        self.next()
        var = self.expect("name")
        if self.peek() in (",", "in"):
            return self.generic_for(var)
        self.expect("=")
        start = self.expr()
        self.expect(",")
        stop = self.expr()
        step = None
        if self.accept(","):
            step = self.expr()
        self.expect("do")
        body = self.block(("end",))
        self.expect("end")

        _MISSING = object()

        def run(env, var=var, start=start, stop=stop, step=step,
                body=body, _MISSING=_MISSING):
            i = _first(start(env))
            limit = _first(stop(env))
            inc = _first(step(env)) if step else 1
            if inc == 0:
                raise LuaError("lua: for step is zero")
            saved = env.locals.get(var, _MISSING)
            try:
                while (i <= limit) if inc > 0 else (i >= limit):
                    env.set_local(var, i)
                    try:
                        body(env)
                    except _Break:
                        break
                    i += inc
            finally:
                # the control variable is a fresh local scoped to the
                # loop (Lua manual §3.3.5) — restore the outer binding
                if saved is _MISSING:
                    env.locals.pop(var, None)
                else:
                    env.locals[var] = saved
        return run

    def generic_for(self, first_var: str) -> Callable:
        """``for k, v in pairs(t) do … end`` — the Lua generic-for
        protocol: the in-list evaluates to (iterator, state, control);
        each round calls ``iterator(state, control)`` and stops when the
        first result is nil (manual §3.3.5)."""
        names = [first_var]
        while self.accept(","):
            names.append(self.expect("name"))
        self.expect("in")
        exprs = self.exprlist()
        self.expect("do")
        body = self.block(("end",))
        self.expect("end")

        _MISSING = object()

        def run(env, names=tuple(names), exprs=tuple(exprs), body=body,
                _MISSING=_MISSING):
            # standard expression-list adjustment to the (iterator,
            # state, control) triple — pairs/ipairs expand from one expr
            it, state, ctrl = _adjust_values([e(env) for e in exprs], 3)
            if not callable(it):
                raise LuaError("lua: generic for needs an iterator "
                               "function (pairs/ipairs)")
            saved = {n: env.locals.get(n, _MISSING) for n in names}
            try:
                while True:
                    res = it(state, ctrl)
                    if isinstance(res, tuple):
                        first = res[0] if res else None
                    else:
                        res = (res,)
                        first = res[0]
                    if first is None:
                        break
                    ctrl = first
                    for i, n in enumerate(names):
                        env.set_local(n, res[i] if i < len(res) else None)
                    try:
                        body(env)
                    except _Break:
                        break
            finally:
                for n, s in saved.items():
                    if s is _MISSING:
                        env.locals.pop(n, None)
                    else:
                        env.locals[n] = s
        return run

    def if_stmt(self) -> Callable:
        self.next()
        arms: List[Tuple[Optional[Callable], Callable]] = []
        cond = self.expr()
        self.expect("then")
        arms.append((cond, self.block(("elseif", "else", "end"))))
        while self.peek() == "elseif":
            self.next()
            c = self.expr()
            self.expect("then")
            arms.append((c, self.block(("elseif", "else", "end"))))
        if self.accept("else"):
            arms.append((None, self.block(("end",))))
        self.expect("end")

        def run(env, arms=arms):
            for cond, body in arms:
                if cond is None or _truthy(cond(env)):
                    body(env)
                    return
        return run

    def expr_or_assign(self) -> Callable:
        target = self.suffixed()
        if self.peek() in ("=", ","):
            targets = [target]
            while self.accept(","):
                targets.append(self.suffixed())
            self.expect("=")
            exprs = self.exprlist()
            setters = []
            for t in targets:
                if t[0] == "name":
                    setters.append(("name", t[1]))
                elif t[0] == "index":
                    setters.append(("index", t[1], t[2]))
                else:
                    raise LuaError("lua: cannot assign to expression")

            def assign(env, setters=setters, exprs=exprs):
                vals = _adjust_values([e(env) for e in exprs],
                                      len(setters))
                for s, v in zip(setters, vals):
                    if s[0] == "name":
                        env.set(s[1], v)
                    else:
                        _setindex(_first(s[1](env)), _first(s[2](env)), v)
            return assign
        # bare expression statement (function call)
        fn = self.finish_expr_from_suffixed(target)

        def run(env, fn=fn):
            fn(env)
        return run

    # -- functions -----------------------------------------------------------
    def function_body(self, implicit_self: bool = False) -> Callable:
        self.expect("(")
        params: List[str] = ["self"] if implicit_self else []
        if self.peek() != ")":
            params.append(self.expect("name"))
            while self.accept(","):
                params.append(self.expect("name"))
        self.expect(")")
        body = self.block(("end",))
        self.expect("end")

        def make(defenv, params=params, body=body):
            g = defenv.globals
            # the chunk-level env aliases globals as its locals; chaining
            # to it would only duplicate the globals fallback
            parent = defenv if defenv.locals is not g else None

            def call(*args):
                env = Env({}, g, parent)
                for i, p in enumerate(params):
                    env.set_local(p, args[i] if i < len(args) else None)
                try:
                    body(env)
                except _Return as r:
                    return r.value
                return None
            return call
        return make

    # -- expressions (precedence climbing) -----------------------------------
    #: precedence levels, loosest first; or/and get short-circuit
    #: handling inline in expr(), everything else dispatches via _BINFN
    _BINOPS = [
        {"or"}, {"and"},
        {"<", ">", "<=", ">=", "==", "~="},
        {".."}, {"+", "-"}, {"*", "/", "%"},
    ]

    def exprlist(self) -> List[Callable]:
        out = [self.expr()]
        while self.accept(","):
            out.append(self.expr())
        return out

    def expr(self, level: int = 0) -> Callable:
        if level >= len(self._BINOPS):
            return self.unary()
        ops = self._BINOPS[level]
        left = self.expr(level + 1)
        while self.peek() in ops:
            op = self.next()[0]
            right = self.expr(level + 1)
            if op == "or":
                left = (lambda a, b: lambda env:
                        (lambda v: v if _truthy(v) else _first(b(env)))
                        (_first(a(env))))(left, right)
            elif op == "and":
                left = (lambda a, b: lambda env:
                        (lambda v: _first(b(env)) if _truthy(v) else v)
                        (_first(a(env))))(left, right)
            else:
                fn = _BINFN[op]
                left = (lambda a, b, fn=fn: lambda env:
                        fn(_first(a(env)), _first(b(env))))(left, right)
        return left

    def unary(self) -> Callable:
        if self.accept("-"):
            operand = self.unary()

            def neg(env):
                v = _first(operand(env))
                if isinstance(v, (int, float)):
                    return -v
                h = _metamethod(v, "__unm")
                if h is not None:
                    return _first(_call_value(h, (v, v)))
                raise LuaError("lua: arithmetic (unary -) on non-number "
                               "(no __unm metamethod)")
            return neg
        if self.accept("not"):
            operand = self.unary()
            return lambda env: not _truthy(operand(env))
        if self.accept("#"):
            operand = self.unary()

            def length(env):
                v = _first(operand(env))
                if isinstance(v, LuaTable):
                    return v.length()
                if isinstance(v, str):
                    return len(v)
                try:
                    return len(v)
                except TypeError:
                    raise LuaError("lua: # of non-table")
            return length
        return self.power()

    def power(self) -> Callable:
        base = self.finish_expr_from_suffixed(self.suffixed())
        if self.accept("^"):
            exp = self.unary()       # right associative, binds over unary

            def powr(env):
                a, b = _first(base(env)), _first(exp(env))
                if isinstance(a, (int, float)) and isinstance(b,
                                                              (int, float)):
                    return _lua_rawpow(a, b)
                h = _meta_bin(a, b, "__pow")
                if h is not None:
                    return h()
                raise LuaError("lua: arithmetic (^) on non-number "
                               "(no __pow metamethod)")
            return powr
        return base

    # -- primary/suffixed expressions ---------------------------------------
    def suffixed(self):
        """Parse primary + suffixes, returning a tagged node so assignment
        targets can be distinguished: ('name', n) | ('index', objfn,
        keyfn) | ('expr', fn)."""
        k, v = self.next()
        if k == "num" or k == "str":
            node = ("expr", lambda env, v=v: v)
        elif k == "true":
            node = ("expr", lambda env: True)
        elif k == "false":
            node = ("expr", lambda env: False)
        elif k == "nil":
            node = ("expr", lambda env: None)
        elif k == "name":
            node = ("name", v)
        elif k == "(":
            inner = self.expr()
            self.expect(")")
            node = ("expr", lambda env, inner=inner: _first(inner(env)))
        elif k == "{":
            node = ("expr", self.table_constructor())
        elif k == "function":
            fn = self.function_body()
            node = ("expr", lambda env, fn=fn: fn(env))
        else:
            raise LuaError(f"lua: unexpected token {k!r}")

        while True:
            p = self.peek()
            if p == ".":
                self.next()
                field = self.expect("name")
                objfn = self.node_value(node)
                node = ("index", objfn, lambda env, f=field: f)
            elif p == "[":
                self.next()
                key = self.expr()
                self.expect("]")
                node = ("index", self.node_value(node), key)
            elif p == "(":
                self.next()
                args: List[Callable] = []
                if self.peek() != ")":
                    args = self.exprlist()
                self.expect(")")
                fnv = self.node_value(node)

                def call(env, fnv=fnv, args=tuple(args)):
                    f = _first(fnv(env))
                    return _call_value(
                        f, _expand_args([a(env) for a in args]))
                node = ("expr", call)
            elif p == ":":
                # method-call sugar: obj:m(a) == obj.m(obj, a); strings
                # dispatch through the `string` library table (the role
                # of Lua's string metatable)
                self.next()
                method = self.expect("name")
                self.expect("(")
                margs: List[Callable] = []
                if self.peek() != ")":
                    margs = self.exprlist()
                self.expect(")")
                objfn = self.node_value(node)

                def mcall(env, objfn=objfn, method=method,
                          margs=tuple(margs)):
                    obj = _first(objfn(env))
                    # strings resolve via _index's shared string-lib
                    # singleton — the SAME table dot access uses, so
                    # s:rep(2) and ('x').rep can never diverge
                    f = _index(obj, method)
                    if f is None:
                        raise LuaError(
                            f"lua: no method {method!r} on "
                            f"{_lua_str(obj)[:40]!r}")
                    return _call_value(
                        f, [obj] + _expand_args([a(env) for a in margs]))
                node = ("expr", mcall)
            else:
                return node

    def node_value(self, node) -> Callable:
        if node[0] == "name":
            name = node[1]

            def load(env, name=name):
                return env.get(name)
            return load
        if node[0] == "index":
            objfn, keyfn = node[1], node[2]
            return lambda env: _index(_first(objfn(env)),
                                      _first(keyfn(env)))
        return node[1]

    def finish_expr_from_suffixed(self, node) -> Callable:
        return self.node_value(node)

    def table_constructor(self) -> Callable:
        items: List[Tuple[Optional[Any], Callable]] = []
        while not self.accept("}"):
            if self.peek() == "name" and self.toks[self.i + 1][0] == "=":
                key = self.expect("name")
                self.expect("=")
                items.append((key, self.expr()))
            elif self.accept("["):
                key_expr = self.expr()
                self.expect("]")
                self.expect("=")
                items.append((key_expr, self.expr()))
            else:
                items.append((None, self.expr()))
            if not (self.accept(",") or self.accept(";")):
                self.expect("}")
                break

        def build(env, items=items):
            t = LuaTable()
            n = 0
            for i, (key, vexpr) in enumerate(items):
                v = vexpr(env)
                if key is None:
                    if isinstance(v, tuple):
                        # multi-value adjustment in constructors: the
                        # FINAL positional item expands, earlier ones
                        # truncate to their first value
                        if i == len(items) - 1:
                            for vv in v:
                                n += 1
                                t.set(n, vv)
                            continue
                        v = v[0] if v else None
                    n += 1
                    t.set(n, v)
                elif callable(key):
                    t.set(_first(key(env)), _first(v))
                else:
                    t.set(key, _first(v))
            return t
        return build


def _lua_str(v) -> str:
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    if v is True:
        return "true"
    if v is False:
        return "false"
    if v is None:
        return "nil"
    return str(v)


def _lua_setmetatable(t, mt):
    if not isinstance(t, LuaTable):
        raise LuaError("lua: setmetatable on non-table")
    if mt is not None and not isinstance(mt, LuaTable):
        raise LuaError("lua: metatable must be a table or nil")
    t.metatable = mt
    return t


def _lua_getmetatable(t):
    return t.metatable if isinstance(t, LuaTable) else None


def _lua_rawget(t, k):
    if not isinstance(t, LuaTable):
        raise LuaError("lua: rawget on non-table")
    return t.get(k)


def _lua_rawset(t, k, v):
    if not isinstance(t, LuaTable):
        raise LuaError("lua: rawset on non-table")
    t.set(k, v)
    return t


def _lua_type(v):
    if v is None:
        return "nil"
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, (int, float)):
        return "number"
    if isinstance(v, str):
        return "string"
    if isinstance(v, LuaTable):
        return "table"
    if callable(v):
        return "function"
    return "userdata"


def _lua_pairs(t):
    """Iterator triple over ALL entries (snapshot of keys at call time)."""
    if not isinstance(t, LuaTable):
        raise LuaError("lua: pairs expects a table")
    keys = list(t.data.keys())
    succ: Dict[Any, Any] = {}
    prev: Any = None
    for key in keys:
        succ[prev] = key
        prev = key

    def nxt(state, ctrl):
        k = succ.get(ctrl)
        # skip keys deleted mid-traversal (Lua allows removing fields
        # during pairs; next never yields a removed key)
        while k is not None and k not in t.data:
            k = succ.get(k)
        if k is None:
            return None
        return (k, t.get(k))

    return (nxt, t, None)


def _lua_ipairs(t):
    """Iterator triple over the 1..n array part, stopping at the first
    nil (the Lua ipairs contract)."""
    if not isinstance(t, LuaTable):
        raise LuaError("lua: ipairs expects a table")

    def nxt(state, ctrl):
        i = 1 if ctrl is None else int(ctrl) + 1
        v = state.get(i)
        if v is None:
            return None
        return (i, v)

    return (nxt, t, None)


def _lua_tonumber(v, base=None):
    if isinstance(v, bool):
        return None                 # Lua: booleans are not numbers
    if base is not None:
        try:
            return float(int(str(v).strip(), int(base)))
        except ValueError:
            return None
    if isinstance(v, (int, float)):
        return v
    try:
        s = str(v).strip()
        return int(s, 16) if s[:2].lower() == "0x" else (
            int(s) if s.lstrip("+-").isdigit() else float(s))
    except (ValueError, IndexError):
        return None


def _metamethod(v, event):
    if isinstance(v, LuaTable) and v.metatable is not None:
        return v.metatable.get(event)
    return None


def _meta_bin(a, b, event):
    """First operand's metamethod, then the second's (manual §2.8 order);
    None when neither has one."""
    h = _metamethod(a, event) or _metamethod(b, event)
    if h is None:
        return None
    return lambda: _first(_call_value(h, (a, b)))


def _arith(name, fn, event):
    def op(a, b):
        if isinstance(a, (int, float)) and isinstance(b, (int, float)):
            return fn(a, b)
        h = _meta_bin(a, b, event)
        if h is not None:
            return h()
        raise LuaError(f"lua: arithmetic ({name}) on non-number "
                       f"(no {event} metamethod)")
    return op


def _lua_lt(a, b):
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return a < b
    if isinstance(a, str) and isinstance(b, str):
        return a < b
    h = _meta_bin(a, b, "__lt")
    if h is not None:
        return _truthy(h())
    raise LuaError("lua: attempt to compare incompatible values "
                   "(no __lt metamethod)")


def _lua_le(a, b):
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return a <= b
    if isinstance(a, str) and isinstance(b, str):
        return a <= b
    h = _meta_bin(a, b, "__le")
    if h is not None:
        return _truthy(h())
    raise LuaError("lua: attempt to compare incompatible values "
                   "(no __le metamethod)")


def _lua_eq(a, b):
    if a is b:
        return True
    if isinstance(a, bool) != isinstance(b, bool):
        return False       # Lua: different types are never equal
                           # (Python would unify True == 1)
    if isinstance(a, LuaTable) and isinstance(b, LuaTable):
        # __eq fires only when neither raw-equal nor identical (§2.8)
        h = _meta_bin(a, b, "__eq")
        if h is not None:
            return _truthy(h())
        return False
    if isinstance(a, LuaTable) or isinstance(b, LuaTable):
        return False
    return a == b


def _lua_concat(a, b):
    if isinstance(a, (str, int, float)) and isinstance(b, (str, int, float)):
        return _lua_str(a) + _lua_str(b)
    h = _meta_bin(a, b, "__concat")
    if h is not None:
        return h()
    bad = a if not isinstance(a, (str, int, float)) else b
    raise LuaError(f"lua: attempt to concatenate a {_lua_type(bad)} "
                   "value (no __concat metamethod)")


def _lua_rawdiv(a, b):
    """Lua numbers are C doubles: 1/0 is inf, -1/0 is -inf, 0/0 is nan
    (Python raises ZeroDivisionError instead, which leaked)."""
    a, b = float(a), float(b)
    if b == 0.0:
        if a == 0.0 or math.isnan(a):
            return math.nan
        same_sign = (a > 0) == (math.copysign(1.0, b) > 0)
        return math.inf if same_sign else -math.inf
    return a / b


def _lua_rawmod(a, b):
    """a % b == a - floor(a/b)*b (manual §2.5.1); x%0 and inf%x are
    nan per C fmod, and floor() of an infinite quotient must not raise
    OverflowError."""
    a, b = float(a), float(b)
    if b == 0.0 or not math.isfinite(a):
        return math.nan
    if math.isinf(b):
        # C-Lua luai_nummod: m = fmod(a, b) (= a for finite a), then
        # m += b when m*b < 0 — so an opposite-sign infinite divisor
        # yields b itself: -5 % inf = inf, 5 % -inf = -inf.  Same-sign
        # (and ±0 numerators: 0*inf is nan, not < 0) keep a.
        return b if a != 0.0 and (a < 0.0) != (b < 0.0) else a
    q = a / b
    if not math.isfinite(q):
        return math.nan
    fl = math.floor(q)
    if fl == 0:
        return a                  # 5 % inf = 5 (0*inf would be nan)
    return a - fl * b


def _lua_rawpow(a, b):
    """C pow semantics: 0^-1 is inf, overflow saturates to inf, and a
    negative base with a non-integer exponent is nan (Python would
    raise or go complex)."""
    a, b = float(a), float(b)
    if a < 0 and not b.is_integer():
        return math.nan
    try:
        r = a ** b
    except (ZeroDivisionError, OverflowError):
        neg = a < 0 and b.is_integer() and int(b) % 2 == 1
        return -math.inf if neg else math.inf
    return r


_BINFN: Dict[str, Callable] = {
    "+": _arith("+", lambda a, b: a + b, "__add"),
    "-": _arith("-", lambda a, b: a - b, "__sub"),
    "*": _arith("*", lambda a, b: a * b, "__mul"),
    "/": _arith("/", _lua_rawdiv, "__div"),
    "%": _arith("%", _lua_rawmod, "__mod"),
    "<": _lua_lt, ">": lambda a, b: _lua_lt(b, a),
    "<=": _lua_le, ">=": lambda a, b: _lua_le(b, a),
    "==": _lua_eq, "~=": lambda a, b: not _lua_eq(a, b),
    "..": _lua_concat,
}


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def _protect(name: str, fn):
    """Stdlib/builtin boundary guard: a bad argument to a library
    function — string.gsub(nil, ...), string.sub(s, 'o'), bare
    ipairs() — must surface as the named LuaError liblua raises ("bad
    argument #n to 'gsub'"), never as a leaked Python
    TypeError/ValueError (fuzz-found).  LuaError raised inside a
    function (its own argument checks) passes through untouched."""
    def wrapped(*args):
        try:
            return fn(*args)
        except LuaError:
            raise
        except (TypeError, ValueError, AttributeError, IndexError,
                KeyError, OverflowError) as exc:
            raise LuaError(
                f"lua: bad argument to '{name}' ({exc})") from exc
    return wrapped


def _protected_lib(entries: Dict[str, Any]) -> LuaTable:
    return LuaTable({k: (_protect(k, v) if callable(v) else v)
                     for k, v in entries.items()})


_STRING_LIB: Optional[LuaTable] = None


def _string_lib() -> LuaTable:
    """Shared string library for Lua's string-metatable __index (what
    makes ``s:rep(2)`` / ``("x").sub`` resolve)."""
    global _STRING_LIB
    if _STRING_LIB is None:
        _STRING_LIB = _make_string()
    return _STRING_LIB


def _make_math() -> LuaTable:
    return _protected_lib({
        "floor": lambda x: float(math.floor(x)),
        "ceil": lambda x: float(math.ceil(x)),
        "abs": abs, "sqrt": math.sqrt,
        "min": min, "max": max, "huge": math.inf,
    })


_FMT_RE = re.compile(r"%[-+ #0]*\d*(?:\.\d+)?[diouxXeEfgGqsc%]")


def _lua_format(fmt: str, *args) -> str:
    """string.format per the Lua manual's C-printf subset (%q quotes).
    Every '%' must start a valid directive — an invalid one raises
    wherever it sits (Lua: "invalid conversion")."""
    out: List[str] = []
    pos = 0
    ai = 0
    while True:
        i = fmt.find("%", pos)
        if i < 0:
            out.append(fmt[pos:])
            break
        out.append(fmt[pos:i])
        m = _FMT_RE.match(fmt, i)
        if m is None:
            raise LuaError("lua: string.format: invalid conversion "
                           f"{fmt[i:i + 2]!r}")
        pos = m.end()
        spec = m.group()
        conv = spec[-1]
        if conv == "%":
            out.append("%")
            continue
        if ai >= len(args):
            raise LuaError(f"lua: string.format: no argument #{ai + 1} "
                           f"for {spec!r}")
        a = args[ai]
        ai += 1
        if conv == "q":
            s = _lua_str(a).replace("\\", "\\\\").replace('"', '\\"')
            out.append('"' + s.replace("\n", "\\n") + '"')
        elif conv in "diouxX":
            out.append(spec % int(a))
        elif conv in "eEfgG":
            out.append(spec % float(a))
        elif conv == "c":
            out.append(chr(int(a)))
        else:                                   # s
            out.append(spec % _lua_str(a))
    return "".join(out)


def _str_range(s: str, i, j=None):
    """Lua 1-based, negative-from-end [i, j] → Python slice bounds."""
    n = len(s)
    i = int(i)
    j = n if j is None else int(j)
    if i < 0:
        i = max(n + i + 1, 1)
    elif i == 0:
        i = 1
    if j < 0:
        j = n + j + 1
    elif j > n:
        j = n
    return i - 1, j


# ---------------------------------------------------------------------------
# Lua pattern matching (manual §6.4.1), written from the manual's
# specification: character classes, sets, quantifiers (* + - ?),
# anchors, captures (incl. position captures and %1-%9 back-references),
# %b balanced match, %f frontier.  Recursive matcher with explicit
# backtracking — the same observable semantics as liblua's lstrlib, from
# a fresh implementation.
# ---------------------------------------------------------------------------

_HEXDIGITS = "0123456789abcdefABCDEF"


def _cls_match(ch: str, cl: str) -> bool:
    """Single class character (the letter after %%) against one char."""
    low = cl.lower()
    if low == "a":
        res = ch.isalpha()
    elif low == "c":
        res = ord(ch) < 32 or ord(ch) == 127
    elif low == "d":
        res = ch.isdigit()
    elif low == "l":
        res = ch.islower()
    elif low == "p":
        res = ch.isprintable() and not ch.isalnum() and not ch.isspace()
    elif low == "s":
        res = ch in " \t\n\r\f\v"
    elif low == "u":
        res = ch.isupper()
    elif low == "w":
        res = ch.isalnum()
    elif low == "x":
        res = ch in _HEXDIGITS
    else:
        return ch == cl                    # %. %% %( … : literal escape
    return (not res) if cl.isupper() else res


class _MatchState:
    __slots__ = ("src", "pat", "caps")

    def __init__(self, src: str, pat: str):
        self.src = src
        self.pat = pat
        self.caps: List[List[Any]] = []    # [start, len] ; len -1 = open,
        #                                    "pos" = position capture


def _class_end(ms: _MatchState, pi: int) -> int:
    """Index just past the single-item class starting at pat[pi]."""
    p = ms.pat
    c = p[pi]
    pi += 1
    if c == "%":
        if pi >= len(p):
            raise LuaError("lua pattern: malformed (ends with '%')")
        return pi + 1
    if c == "[":
        if pi < len(p) and p[pi] == "^":
            pi += 1
        first = True                        # ']' as first char is literal
        while True:
            if pi >= len(p):
                raise LuaError("lua pattern: malformed (missing ']')")
            if p[pi] == "]" and not first:
                return pi + 1
            if p[pi] == "%":
                pi += 1
                if pi >= len(p):
                    raise LuaError("lua pattern: malformed (ends with '%')")
            pi += 1
            first = False
    return pi


def _set_match(ms: _MatchState, ch: str, pi: int, ep: int) -> bool:
    """Char vs a [set] spanning pat[pi:ep] (pi at '[', ep past ']')."""
    p = ms.pat
    i = pi + 1
    neg = False
    if i < ep - 1 and p[i] == "^":
        neg = True
        i += 1
    res = False
    while i < ep - 1:
        if p[i] == "%" and i + 1 < ep - 1:
            if _cls_match(ch, p[i + 1]):
                res = True
            i += 2
        elif i + 2 < ep - 1 and p[i + 1] == "-":
            if p[i] <= ch <= p[i + 2]:
                res = True
            i += 3
        else:
            if p[i] == ch:
                res = True
            i += 1
    return res != neg


def _single_match(ms: _MatchState, si: int, pi: int, ep: int) -> bool:
    if si >= len(ms.src):
        return False
    ch = ms.src[si]
    c = ms.pat[pi]
    if c == ".":
        return True
    if c == "%":
        return _cls_match(ch, ms.pat[pi + 1])
    if c == "[":
        return _set_match(ms, ch, pi, ep)
    return ch == c


def _max_expand(ms: _MatchState, si: int, pi: int, ep: int):
    i = 0
    while _single_match(ms, si + i, pi, ep):
        i += 1
    while i >= 0:
        r = _pm(ms, si + i, ep + 1)
        if r is not None:
            return r
        i -= 1
    return None


def _min_expand(ms: _MatchState, si: int, pi: int, ep: int):
    while True:
        r = _pm(ms, si, ep + 1)
        if r is not None:
            return r
        if _single_match(ms, si, pi, ep):
            si += 1
        else:
            return None


def _pm(ms: _MatchState, si: int, pi: int):
    """Match pat[pi:] at src[si:]; returns the end index or None."""
    p, s = ms.pat, ms.src
    while True:
        if pi >= len(p):
            return si
        c = p[pi]
        if c == "(":
            if pi + 1 < len(p) and p[pi + 1] == ")":   # position capture
                ms.caps.append([si, "pos"])
                r = _pm(ms, si, pi + 2)
                if r is None:
                    ms.caps.pop()
                return r
            ms.caps.append([si, -1])
            r = _pm(ms, si, pi + 1)
            if r is None:
                ms.caps.pop()
            return r
        if c == ")":
            for cap in reversed(ms.caps):
                if cap[1] == -1:
                    cap[1] = si - cap[0]
                    r = _pm(ms, si, pi + 1)
                    if r is None:
                        cap[1] = -1
                    return r
            raise LuaError("lua pattern: unmatched ')'")
        if c == "$" and pi + 1 == len(p):
            return si if si == len(s) else None
        if c == "%" and pi + 1 < len(p):
            nx = p[pi + 1]
            if nx == "b":
                if pi + 3 >= len(p):
                    raise LuaError("lua pattern: malformed %b "
                                   "(needs two chars)")
                x, y = p[pi + 2], p[pi + 3]
                if si >= len(s) or s[si] != x:
                    return None
                bal, j = 1, si + 1
                while j < len(s):
                    if s[j] == y:
                        bal -= 1
                        if bal == 0:
                            r = _pm(ms, j + 1, pi + 4)
                            if r is not None:
                                return r
                            break
                    elif s[j] == x:
                        bal += 1
                    j += 1
                return None
            if nx == "f":
                if pi + 2 >= len(p) or p[pi + 2] != "[":
                    raise LuaError("lua pattern: missing '[' after %f")
                ep = _class_end(ms, pi + 2)
                prev = s[si - 1] if si > 0 else "\0"
                cur = s[si] if si < len(s) else "\0"
                if (not _set_match(ms, prev, pi + 2, ep)
                        and _set_match(ms, cur, pi + 2, ep)):
                    pi = ep
                    continue
                return None
            if nx.isdigit():                      # back-reference
                idx = int(nx) - 1
                if (nx == "0" or idx >= len(ms.caps)
                        or ms.caps[idx][1] in (-1, "pos")):
                    raise LuaError(f"lua pattern: invalid capture %{nx}")
                st, ln = ms.caps[idx]
                cap = s[st:st + ln]
                if s.startswith(cap, si):
                    si += len(cap)
                    pi += 2
                    continue
                return None
        ep = _class_end(ms, pi)
        q = p[ep] if ep < len(p) else ""
        if q == "?":
            if _single_match(ms, si, pi, ep):
                r = _pm(ms, si + 1, ep + 1)
                if r is not None:
                    return r
            pi = ep + 1
            continue
        if q == "+":
            if not _single_match(ms, si, pi, ep):
                return None
            return _max_expand(ms, si + 1, pi, ep)
        if q == "*":
            return _max_expand(ms, si, pi, ep)
        if q == "-":
            return _min_expand(ms, si, pi, ep)
        if not _single_match(ms, si, pi, ep):
            return None
        si += 1
        pi = ep


def _captures(ms: _MatchState, si: int, ei: int) -> List[Any]:
    """Captured values (whole match when no captures)."""
    if not ms.caps:
        return [ms.src[si:ei]]
    out = []
    for start, ln in ms.caps:
        if ln == "pos":
            out.append(float(start + 1))          # 1-based position
        elif ln == -1:
            raise LuaError("lua pattern: unfinished capture")
        else:
            out.append(ms.src[start:start + ln])
    return out


def _has_captures(pat: str) -> bool:
    i = 0
    while i < len(pat):
        c = pat[i]
        if c == "%":
            i += 2
        elif c == "[":
            # skip the whole [set] — '(' inside it is literal
            i += 1
            if i < len(pat) and pat[i] == "^":
                i += 1
            first = True
            while i < len(pat) and (pat[i] != "]" or first):
                if pat[i] == "%":
                    i += 1
                i += 1
                first = False
            i += 1
        elif c == "(":
            return True
        else:
            i += 1
    return False


def _pat_search(s: str, pat: str, init: int = 0):
    """Find the first match of `pat` in `s` at/after byte `init`.
    Returns (start, end, captures) with 0-based [start, end), or None."""
    anchor = pat.startswith("^")
    p0 = 1 if anchor else 0
    si = init
    while True:
        ms = _MatchState(s, pat)
        e = _pm(ms, si, p0)
        if e is not None:
            return si, e, _captures(ms, si, e)
        si += 1
        if anchor or si > len(s):
            return None


def _make_string() -> LuaTable:
    def sub(s, i, j=None):
        a, b = _str_range(s, i, j)
        return s[a:b] if a < b else ""

    def find(s, pat, init=1, plain=None):
        a, _ = _str_range(s, init)
        a = min(a, len(s))          # Lua 5.1 clamps init to #s+1
        if _truthy(plain):
            idx = s.find(pat, a)
            if idx < 0:
                return None
            return (float(idx + 1), float(idx + len(pat)))
        hit = _pat_search(s, pat, a)
        if hit is None:
            return None
        st, en, ms_caps = hit
        caps = () if not _has_captures(pat) else tuple(ms_caps)
        return (float(st + 1), float(en)) + caps

    def match(s, pat, init=1):
        a, _ = _str_range(s, init)
        a = min(a, len(s))          # Lua 5.1 clamps init to #s+1
        hit = _pat_search(s, pat, a)
        if hit is None:
            return None
        caps = hit[2]
        return caps[0] if len(caps) == 1 else tuple(caps)

    def gmatch(s, pat):
        state = {"pos": 0}

        def it(*_ignored):
            while state["pos"] <= len(s):
                hit = _pat_search(s, pat, state["pos"])
                if hit is None:
                    return None
                st, en, caps = hit
                state["pos"] = en + 1 if en == st else en  # empty-match step
                return caps[0] if len(caps) == 1 else tuple(caps)
            return None
        return it

    def _expand_repl(repl: str, caps: List[Any], whole: str) -> str:
        out: List[str] = []
        i = 0
        while i < len(repl):
            ch = repl[i]
            if ch == "%" and i + 1 < len(repl):
                nx = repl[i + 1]
                if nx == "%":
                    out.append("%")
                elif nx == "0":
                    out.append(whole)
                elif nx.isdigit():
                    idx = int(nx) - 1
                    if idx >= len(caps):
                        raise LuaError(
                            f"lua: string.gsub: invalid capture %{nx} "
                            "in replacement")
                    out.append(_lua_str(caps[idx]))
                else:
                    raise LuaError(
                        f"lua: string.gsub: invalid use of '%' in "
                        f"replacement ('%{nx}')")
                i += 2
            else:
                out.append(ch)
                i += 1
        return "".join(out)

    def gsub(s, pat, repl, n=None):
        limit = math.inf if n is None else int(n)
        out: List[str] = []
        pos = 0
        count = 0
        anchor = pat.startswith("^")
        while count < limit and pos <= len(s):
            hit = _pat_search(s, pat, pos)
            if hit is None:
                break
            st, en, caps = hit
            out.append(s[pos:st])
            whole = s[st:en]
            if isinstance(repl, str):
                rep = _expand_repl(repl, caps, whole)
            elif isinstance(repl, LuaTable):
                rep = repl.get(caps[0])
            elif callable(repl):
                rep = _first(repl(*caps))
            else:
                raise LuaError("lua: string.gsub: replacement must be a "
                               "string, table, or function")
            if rep is None or rep is False:     # nil/false: keep the match
                rep = whole
            elif not isinstance(rep, str):
                if isinstance(rep, (int, float)):
                    rep = _lua_str(rep)
                else:
                    raise LuaError("lua: string.gsub: replacement value "
                                   f"must be a string (got {_lua_type(rep)})")
            out.append(rep)
            count += 1
            if en == st:                         # empty match: emit + step
                if st < len(s):
                    out.append(s[st])
                pos = st + 1
            else:
                pos = en
            if anchor:
                break
        out.append(s[pos:])
        return ("".join(out), float(count))

    def byte(s, i=1):
        a, _ = _str_range(s, i)
        return float(ord(s[a])) if a < len(s) else None

    return _protected_lib({
        "format": _lua_format,
        "sub": sub, "len": lambda s: len(s),
        "upper": lambda s: s.upper(), "lower": lambda s: s.lower(),
        "rep": lambda s, n, sep=None: (
            (_lua_str(sep or "")).join([s] * int(n)) if int(n) > 0 else ""),
        "reverse": lambda s: s[::-1],
        "byte": byte,
        "char": lambda *cs: "".join(chr(int(c)) for c in cs),
        "find": find, "match": match, "gmatch": gmatch, "gsub": gsub,
    })


def _make_table() -> LuaTable:
    def insert(t: LuaTable, a, b=None):
        if b is None:
            t.set(t.length() + 1, a)
            return
        pos = int(a)
        n = t.length()
        if pos < 1 or pos > n + 1:
            raise LuaError(
                f"lua: table.insert: position {pos} out of bounds "
                f"(table length {n})")
        for k in range(n, pos - 1, -1):
            t.set(k + 1, t.get(k))
        t.set(pos, b)

    def remove(t: LuaTable, pos=None):
        n = t.length()
        if n == 0:
            return None
        p = n if pos is None else int(pos)
        v = t.get(p)
        for k in range(p, n):
            t.set(k, t.get(k + 1))
        t.data.pop(n, None)
        return v

    def concat(t: LuaTable, sep=""):
        return _lua_str(sep).join(
            _lua_str(t.get(k)) for k in range(1, t.length() + 1))

    return _protected_lib({"insert": insert, "remove": remove,
                           "concat": concat})


class LuaState:
    """A loaded script: globals table + compiled chunk."""

    def __init__(self, source: str,
                 host_globals: Optional[Dict[str, Any]] = None):
        # builtins go through the same _protect boundary as the stdlib
        # tables: bare ipairs() is a LuaError, not a Python TypeError
        self.globals: Dict[str, Any] = {
            "math": _make_math(),
            "string": _make_string(),
            "table": _make_table(),
        }
        self.globals.update({
            name: _protect(name, fn) for name, fn in {
                "tostring": _lua_str,
                "tonumber": _lua_tonumber,
                "pairs": _lua_pairs,
                "ipairs": _lua_ipairs,
                "print": lambda *a: print(
                    "[lua]", *[_lua_str(x) for x in a]),
                "setmetatable": _lua_setmetatable,
                "getmetatable": _lua_getmetatable,
                "rawget": _lua_rawget,
                "rawset": _lua_rawset,
                "type": _lua_type,
            }.items()})
        if host_globals:
            self.globals.update(host_globals)
        chunk = _Parser(_lex(source)).parse_chunk()
        try:
            # the top-level chunk's locals ARE the globals table
            chunk(Env(self.globals, self.globals))
        except _Return:
            pass                      # chunks may end with `return`
        except _Break:
            raise LuaError("lua: break outside a loop")

    def get(self, name: str):
        return self.globals.get(name)

    def set(self, name: str, value) -> None:
        self.globals[name] = value

    def call(self, name: str, *args):
        fn = self.globals.get(name)
        if fn is None:
            raise LuaError(f"lua: no function {name!r}")
        return fn(*args)
