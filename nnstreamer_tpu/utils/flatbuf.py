"""Minimal dependency-free FlatBuffers runtime (reader + builder).

The reference links the official flatbuffers C++ runtime for its .tflite
loader and flatbuf codec (ext/nnstreamer/tensor_filter/
tensor_filter_tensorflow_lite.cc, ext/nnstreamer/tensor_decoder/
tensordec-flatbuf.cc).  That library is not in this image, so this module
implements the FlatBuffers wire format directly — enough to (a) parse
.tflite model files and (b) encode/decode the tensor-stream flatbuf schema
(reference ext/nnstreamer/include/nnstreamer.fbs).

Wire format (little-endian throughout):

- file: ``int32`` relative offset to the root table (optionally followed by
  a 4-byte file identifier).
- table: at its position holds an ``int32`` *backwards* offset to its
  vtable; the vtable is ``uint16 vtable_bytes, uint16 table_bytes`` then one
  ``uint16`` per field slot (0 = absent → default).
- scalars are stored inline in the table; strings/vectors/subtables are
  stored via a ``uint32`` forward offset.
- string: ``uint32 len`` + bytes (+NUL); vector: ``uint32 len`` + elements.
"""

from __future__ import annotations

import struct
from typing import Any, List, Optional, Sequence, Tuple


def _u8(buf: bytes, pos: int) -> int:
    return buf[pos]


def _u16(buf: bytes, pos: int) -> int:
    return struct.unpack_from("<H", buf, pos)[0]


def _i32(buf: bytes, pos: int) -> int:
    return struct.unpack_from("<i", buf, pos)[0]


def _u32(buf: bytes, pos: int) -> int:
    return struct.unpack_from("<I", buf, pos)[0]


_SCALAR_FMT = {
    "bool": ("<?", 1), "int8": ("<b", 1), "uint8": ("<B", 1),
    "int16": ("<h", 2), "uint16": ("<H", 2),
    "int32": ("<i", 4), "uint32": ("<I", 4),
    "int64": ("<q", 8), "uint64": ("<Q", 8),
    "float32": ("<f", 4), "float64": ("<d", 8),
}


class Table:
    """Read-cursor over one flatbuffer table."""

    __slots__ = ("buf", "pos", "_vt", "_vt_size")

    def __init__(self, buf: bytes, pos: int) -> None:
        self.buf = buf
        self.pos = pos
        self._vt = pos - _i32(buf, pos)
        self._vt_size = _u16(buf, self._vt)

    def _field_pos(self, field_id: int) -> int:
        """Absolute position of field's inline data, or 0 when absent."""
        vt_off = 4 + 2 * field_id
        if vt_off >= self._vt_size:
            return 0
        rel = _u16(self.buf, self._vt + vt_off)
        return self.pos + rel if rel else 0

    def has(self, field_id: int) -> bool:
        return self._field_pos(field_id) != 0

    # -- scalars -------------------------------------------------------------
    def scalar(self, field_id: int, kind: str, default: Any = 0) -> Any:
        p = self._field_pos(field_id)
        if not p:
            return default
        fmt, _ = _SCALAR_FMT[kind]
        return struct.unpack_from(fmt, self.buf, p)[0]

    # -- offset objects ------------------------------------------------------
    def _indirect(self, field_id: int) -> int:
        p = self._field_pos(field_id)
        if not p:
            return 0
        return p + _u32(self.buf, p)

    def string(self, field_id: int) -> Optional[str]:
        p = self._indirect(field_id)
        if not p:
            return None
        n = _u32(self.buf, p)
        return self.buf[p + 4:p + 4 + n].decode("utf-8", "replace")

    def table(self, field_id: int) -> Optional["Table"]:
        p = self._indirect(field_id)
        return Table(self.buf, p) if p else None

    def struct(self, field_id: int, fmt: str) -> Optional[Tuple[Any, ...]]:
        """Inline struct field unpacked with ``fmt`` (e.g. ``"<ii"``)."""
        p = self._field_pos(field_id)
        if not p:
            return None
        return struct.unpack_from(fmt, self.buf, p)

    # -- vectors -------------------------------------------------------------
    def _vector(self, field_id: int) -> Tuple[int, int]:
        """(element-0 position, length); (0, 0) when absent."""
        p = self._indirect(field_id)
        if not p:
            return 0, 0
        return p + 4, _u32(self.buf, p)

    def vector_len(self, field_id: int) -> int:
        return self._vector(field_id)[1]

    def scalar_vector(self, field_id: int, kind: str) -> List[Any]:
        p, n = self._vector(field_id)
        if not n:
            return []
        fmt, size = _SCALAR_FMT[kind]
        return [struct.unpack_from(fmt, self.buf, p + i * size)[0]
                for i in range(n)]

    def bytes_vector(self, field_id: int) -> memoryview:
        """[ubyte] vector as a zero-copy memoryview (np.frombuffer-ready);
        large weight buffers must not be copied at model load."""
        p, n = self._vector(field_id)
        return memoryview(self.buf)[p:p + n]

    def table_vector(self, field_id: int) -> List["Table"]:
        p, n = self._vector(field_id)
        out = []
        for i in range(n):
            ep = p + i * 4
            out.append(Table(self.buf, ep + _u32(self.buf, ep)))
        return out

    def string_vector(self, field_id: int) -> List[str]:
        p, n = self._vector(field_id)
        out = []
        for i in range(n):
            ep = p + i * 4
            sp = ep + _u32(self.buf, ep)
            sl = _u32(self.buf, sp)
            out.append(self.buf[sp + 4:sp + 4 + sl].decode("utf-8", "replace"))
        return out


def root(buf: bytes, expect_identifier: Optional[str] = None) -> Table:
    """Root table of a finished flatbuffer."""
    if len(buf) < 8:
        raise ValueError("flatbuffer too short")
    if expect_identifier is not None:
        ident = buf[4:8].decode("ascii", "replace")
        if ident != expect_identifier:
            raise ValueError(
                f"flatbuffer identifier {ident!r} != {expect_identifier!r}")
    return Table(buf, _u32(buf, 0))


# ---------------------------------------------------------------------------
# builder
# ---------------------------------------------------------------------------

class Builder:
    """Minimal flatbuffer builder (bottom-up, like the official runtime).

    Supports scalars, strings, scalar vectors, byte vectors, vectors of
    offsets, and nested tables — the surface the tensor flatbuf schema
    needs.  The buffer is built back-to-front; offsets returned by
    ``end_table``/``string``/vector methods count from the *end* of the
    final buffer (larger offset = earlier file position), the same
    convention as the official runtimes.
    """

    def __init__(self) -> None:
        self._buf = bytearray()   # reversed: index 0 = LAST byte of file
        self._minalign = 4
        self._vtables: List[Tuple[Tuple[int, ...], int]] = []
        self._current: Optional[List[Tuple[int, int, Any, str]]] = None

    def _offset(self) -> int:
        return len(self._buf)

    def _push(self, data: bytes) -> None:
        self._buf.extend(reversed(data))

    def _prep(self, size: int, additional: int) -> None:
        """Pad so the object about to be pushed (``additional`` bytes of
        header/payload) ends up ``size``-aligned in the final buffer."""
        self._minalign = max(self._minalign, size)
        while (len(self._buf) + additional) % size:
            self._buf.append(0)

    def _push_u32_rel(self, target_off: int) -> None:
        """Push a uint32 forward offset to an object at ``target_off``."""
        self._prep(4, 4)
        rel = self._offset() + 4 - target_off
        if rel <= 0:
            raise ValueError("flatbuffer forward offset must be positive")
        self._push(struct.pack("<I", rel))

    # -- leaf objects --------------------------------------------------------
    def string(self, s: str) -> int:
        raw = s.encode("utf-8")
        self._prep(4, 1 + len(raw) + 4)
        self._push(b"\x00")
        self._push(raw)
        self._push(struct.pack("<I", len(raw)))
        return self._offset()

    def bytes_vector(self, data: bytes) -> int:
        self._prep(4, len(data) + 4)
        self._push(bytes(data))
        self._push(struct.pack("<I", len(data)))
        return self._offset()

    def scalar_vector(self, kind: str, values: Sequence[Any]) -> int:
        fmt, size = _SCALAR_FMT[kind]
        vals = list(values)
        self._prep(max(4, size), len(vals) * size + 4)
        for v in reversed(vals):
            self._push(struct.pack(fmt, v))
        self._push(struct.pack("<I", len(vals)))
        return self._offset()

    def offset_vector(self, offsets: Sequence[int]) -> int:
        offs = list(offsets)
        self._prep(4, len(offs) * 4 + 4)
        for i, off in enumerate(reversed(offs)):
            rel = self._offset() + 4 - off
            if rel <= 0:
                raise ValueError("offset vector target not yet written")
            self._push(struct.pack("<I", rel))
        self._push(struct.pack("<I", len(offs)))
        return self._offset()

    # -- tables --------------------------------------------------------------
    def start_table(self) -> None:
        if self._current is not None:
            raise RuntimeError("nested start_table")
        self._current = []

    def add_scalar(self, field_id: int, kind: str, value: Any,
                   default: Any = 0) -> None:
        assert self._current is not None
        if value == default:
            return
        self._current.append((field_id, 0, value, kind))

    def add_offset(self, field_id: int, offset: Optional[int]) -> None:
        assert self._current is not None
        if not offset:
            return
        self._current.append((field_id, 1, offset, ""))

    def add_struct(self, field_id: int, fmt: str,
                   values: Sequence[Any], align: int = 4) -> None:
        """Inline struct field: packed with ``fmt``, ``align`` = largest
        member size (structs are stored in-place in the table)."""
        assert self._current is not None
        self._current.append(
            (field_id, 2, (struct.pack(fmt, *values), align), ""))

    def end_table(self) -> int:
        assert self._current is not None
        fields = self._current
        self._current = None
        # field data, high field-ids pushed first (= further from table
        # start in the file); layout order within a table is free-form
        slots: dict = {}   # field_id -> (end-offset of field start, size)
        for field_id, is_off, value, kind in sorted(
                fields, key=lambda f: -f[0]):
            if is_off == 1:
                self._push_u32_rel(value)
                size = 4
            elif is_off == 2:
                raw, align = value
                size = len(raw)
                self._prep(align, size)
                self._push(raw)
            else:
                fmt, size = _SCALAR_FMT[kind]
                self._prep(size, size)
                self._push(struct.pack(fmt, value))
            slots[field_id] = (self._offset(), size)
        # table header: int32 soffset to vtable, patched once vtable lands
        self._prep(4, 4)
        patch_at = len(self._buf)
        self._push(b"\x00\x00\x00\x00")
        table_pos = self._offset()
        n_slots = (max(slots) + 1) if slots else 0
        vt = [0] * n_slots
        for fid, (off, _size) in slots.items():
            vt[fid] = table_pos - off
        vt_key = tuple(vt)
        vpos = next((v for key, v in self._vtables if key == vt_key), None)
        if vpos is None:
            vt_bytes = 4 + 2 * n_slots
            tbl_bytes = (table_pos - min(off - size
                                         for off, size in slots.values())
                         if slots else 4)
            for fo in reversed(vt):
                self._push(struct.pack("<H", fo))
            self._push(struct.pack("<H", tbl_bytes))
            self._push(struct.pack("<H", vt_bytes))
            vpos = self._offset()
            self._vtables.append((vt_key, vpos))
        # soffset: vtable_pos = table_pos - soffset (absolute file coords)
        self._buf[patch_at:patch_at + 4] = bytes(
            reversed(struct.pack("<i", vpos - table_pos)))
        return table_pos

    def finish(self, root_offset: int,
               identifier: Optional[str] = None) -> bytes:
        header = 4 + (4 if identifier is not None else 0)
        self._prep(self._minalign, header)
        if identifier is not None:
            ident = identifier.encode("ascii")
            if len(ident) != 4:
                raise ValueError("identifier must be 4 bytes")
            self._push(ident)
        self._push_u32_rel(root_offset)
        return bytes(reversed(self._buf))
