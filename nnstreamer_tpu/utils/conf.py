"""Layered configuration system.

Parity with the reference conf system (gst/nnstreamer/nnstreamer_conf.c:
/etc/nnstreamer.ini + NNSTREAMER_CONF env override + env-var path
overrides + per-group custom values + framework priority for auto-detect):

1. defaults
2. ini file: ``/etc/nnstreamer_tpu.ini`` then ``NNS_TPU_CONF`` override
3. environment: ``NNS_TPU_<GROUP>_<KEY>``

Groups mirror the reference's: [common], [filter], [decoder], [converter],
plus per-framework groups like [xla].
"""

from __future__ import annotations

import configparser
import os
import threading
from typing import Dict, List, Optional

DEFAULT_CONF_PATH = "/etc/nnstreamer_tpu.ini"
CONF_ENV = "NNS_TPU_CONF"

_DEFAULTS: Dict[str, Dict[str, str]] = {
    "common": {},
    "filter": {
        # reference framework_priority_* (nnstreamer_conf.c): auto-detect
        # resolution order
        "framework_priority": "xla,python,custom",
    },
    "xla": {
        "compile_cache": "",
    },
}


class Conf:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._loaded = False
        self._values: Dict[str, Dict[str, str]] = {}

    def _load_locked(self) -> None:
        if self._loaded:
            return
        values = {g: dict(kv) for g, kv in _DEFAULTS.items()}
        paths = [DEFAULT_CONF_PATH]
        env_path = os.environ.get(CONF_ENV)
        if env_path:
            paths.append(env_path)
        parser = configparser.ConfigParser()
        parser.read([p for p in paths if p and os.path.exists(p)])
        for section in parser.sections():
            values.setdefault(section.lower(), {}).update(
                {k.lower(): v for k, v in parser.items(section)})
        self._values = values
        self._loaded = True

    def reload(self) -> None:
        with self._lock:
            self._loaded = False
            self._load_locked()

    def get(self, group: str, key: str,
            default: Optional[str] = None) -> Optional[str]:
        """Env override > ini > defaults (reference nnsconf_get_custom_value
        semantics)."""
        env = os.environ.get(f"NNS_TPU_{group.upper()}_{key.upper()}")
        if env is not None:
            return env
        with self._lock:
            self._load_locked()
            return self._values.get(group.lower(), {}).get(key.lower(),
                                                           default)

    def get_bool(self, group: str, key: str, default: bool = False) -> bool:
        v = self.get(group, key)
        if v is None:
            return default
        return parse_bool(v)

    def framework_priority(self) -> List[str]:
        raw = self.get("filter", "framework_priority") or ""
        return [p.strip() for p in raw.split(",") if p.strip()]

    def dump(self) -> Dict[str, Dict[str, str]]:
        """Introspection (reference nnsconf_dump)."""
        with self._lock:
            self._load_locked()
            return {g: dict(kv) for g, kv in self._values.items()}


def parse_bool(value) -> bool:
    """The ONE truthy-token rule for conf values and custom properties
    (divergent per-backend parses accepted different token sets)."""
    return str(value).strip().lower() in ("1", "true", "yes", "on")


conf = Conf()
