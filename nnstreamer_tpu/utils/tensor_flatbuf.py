"""Tensor-stream flatbuffer codec: the reference ``nnstreamer.fbs`` schema.

Faithful wire-format implementation of ext/nnstreamer/include/nnstreamer.fbs
(namespace nnstreamer.flatbuf, root_type Tensors):

- ``Tensor``  { name:string(0); type:Tensor_type(1, default NNS_END);
  dimension:[uint32](2); data:[ubyte](3); rank:uint(4, extension) }
- ``Tensors`` { num_tensor:int(0); fr:frame_rate struct(1);
  tensor:[Tensor](2); format:Tensor_format(3) }
- ``frame_rate`` struct { rate_n:int; rate_d:int }

Encoded buffers are parseable by flatc-generated readers of that schema
(and vice versa) — used by the flatbuf decoder/converter pair, the
counterpart of tensordec-flatbuf.cc / tensor_converter_flatbuf.cc.

The wire dimension vector cannot distinguish genuine leading unit dims
from rank padding (the reference writes all NNS_TENSOR_RANK_LIMIT slots,
1- or 0-padded).  This codec therefore appends a ``rank`` field at a NEW
vtable slot — flatbuffers schema evolution: reference readers ignore it,
reference-produced buffers simply lack it — so our own round trips stay
lossless while foreign buffers fall back to padding heuristics.
"""

from __future__ import annotations

from fractions import Fraction
from typing import List, Optional, Tuple

import numpy as np

from . import flatbuf as fb

#: Tensor_type enum order (nnstreamer.fbs:12-25)
_NNS_TYPES = ["int32", "uint32", "int16", "uint16", "int8", "uint8",
              "float64", "float32", "int64", "uint64"]
_NNS_END = 10


def encode_tensors(arrays: List[np.ndarray],
                   rate: Optional[Fraction] = None,
                   names: Optional[List[Optional[str]]] = None) -> bytes:
    """Arrays (numpy shape order) → finished ``Tensors`` flatbuffer."""
    b = fb.Builder()
    tensor_offs = []
    for i, arr in enumerate(arrays):
        arr = np.ascontiguousarray(arr)
        if arr.dtype.name not in _NNS_TYPES:
            raise ValueError(
                f"flatbuf: dtype {arr.dtype} not in nnstreamer.fbs "
                "Tensor_type")
        name = names[i] if names and i < len(names) else None
        name_off = b.string(name) if name else None
        if arr.ndim > 8:
            raise ValueError(
                f"flatbuf: rank {arr.ndim} exceeds NNS_TENSOR_RANK_LIMIT 8")
        # reference dim order (innermost-first), 1-padded to the rank limit
        # exactly like the reference writers (tensordec-flatbuf.cc:127)
        dims = list(reversed(arr.shape)) or [1]
        dim_off = b.scalar_vector("uint32",
                                  dims + [1] * (8 - len(dims)))
        data_off = b.bytes_vector(arr.tobytes())
        b.start_table()
        b.add_offset(0, name_off)
        b.add_scalar(1, "int32", _NNS_TYPES.index(arr.dtype.name),
                     default=_NNS_END)
        b.add_offset(2, dim_off)
        b.add_offset(3, data_off)
        b.add_scalar(4, "uint32", len(dims), default=0)   # rank extension
        tensor_offs.append(b.end_table())
    vec_off = b.offset_vector(tensor_offs)
    b.start_table()
    b.add_scalar(0, "int32", len(arrays))
    if rate is not None:
        b.add_struct(1, "<ii", (rate.numerator, rate.denominator))
    b.add_offset(2, vec_off)
    # format(3): static=0 is the default → omitted
    root_off = b.end_table()
    return b.finish(root_off)


def decode_tensors(blob: bytes) -> Tuple[List[np.ndarray],
                                         Optional[Fraction],
                                         List[Optional[str]]]:
    """``Tensors`` flatbuffer → (arrays, framerate, names)."""
    t = fb.root(bytes(blob))
    fr = t.struct(1, "<ii")
    rate = None
    if fr is not None and fr[1] != 0:
        rate = Fraction(fr[0], fr[1])
    arrays: List[np.ndarray] = []
    names: List[Optional[str]] = []
    for tt in t.table_vector(2):
        type_id = tt.scalar(1, "int32", _NNS_END)
        if type_id >= _NNS_END:
            raise ValueError(f"flatbuf: bad Tensor_type {type_id}")
        dtype = np.dtype(_NNS_TYPES[type_id])
        raw = tt.scalar_vector(2, "uint32")
        rank = tt.scalar(4, "uint32", 0)       # our rank extension field
        if rank:
            dims = list(raw[:rank])            # exact — lossless round trip
        else:
            # Foreign (reference-written) buffer: all NNS_TENSOR_RANK_LIMIT
            # entries serialized (tensordec-flatbuf.cc:127), unfilled slots
            # 0 when default-initialized (util_impl.c:131) but 1 when
            # parsed from a dim string (:951).  Strip zeros, then — for a
            # full-rank-limit vector — the trailing 1s (= outermost unit
            # dims), which are semantically neutral in nnstreamer.
            dims = [d for d in raw if d > 0]
            if len(raw) in (4, 8):
                while len(dims) > 1 and dims[-1] == 1:
                    dims.pop()
        shape = tuple(reversed(dims)) or (1,)
        data = tt.bytes_vector(3)
        arrays.append(np.frombuffer(data, dtype).reshape(shape))
        names.append(tt.string(0))
    n = t.scalar(0, "int32")
    if n != len(arrays):
        raise ValueError(f"flatbuf: num_tensor {n} != {len(arrays)}")
    return arrays, rate, names
