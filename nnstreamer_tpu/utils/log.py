"""Logging with backtrace support.

Parity with the reference logging layer (gst/nnstreamer/nnstreamer_log.h:
ml_logi/w/e/d macros + ml_loge_stacktrace): standard logging channel
``nnstreamer_tpu`` plus an error-with-backtrace helper.
"""

from __future__ import annotations

import logging
import traceback

logger = logging.getLogger("nnstreamer_tpu")

ml_logd = logger.debug
ml_logi = logger.info
ml_logw = logger.warning
ml_loge = logger.error


def ml_loge_stacktrace(msg: str, *args) -> None:
    """Error + formatted python stack (reference _backtrace_to_string)."""
    stack = "".join(traceback.format_stack()[:-1])
    logger.error(msg + "\nBacktrace:\n%s", *args, stack)
