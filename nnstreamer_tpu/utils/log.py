"""Structured logging with backtrace support.

Parity with the reference logging layer (gst/nnstreamer/nnstreamer_log.h:
ml_logi/w/e/d macros + ml_loge_stacktrace): standard logging channel
``nnstreamer_tpu`` plus an error-with-backtrace helper.  The ``ml_log*``
shims are unchanged call-site-compatible aliases.

Structured context (observability layer): every record emitted from
inside a traced ``chain()`` carries the active trace frame's context —
``element`` (whose chain is running on this thread), ``buffer_seq``, and
the emitting thread's name — injected by a :class:`logging.Filter` so
existing ``logger.warning("...", args)`` call sites pick it up without
changes.  Untraced pipelines pay one empty-stack check per record, and
only when a record is actually emitted.

``NNS_LOG=json`` switches the channel to one-JSON-object-per-line
(machine-parseable for log aggregation)::

    {"ts": 1722700000.123, "level": "WARNING", "logger": "nnstreamer_tpu",
     "msg": "...", "thread": "src:videotestsrc0", "element": "f",
     "buffer_seq": 17}

Any other ``NNS_LOG`` value sets the channel's level by name (e.g.
``NNS_LOG=debug``); both may be combined as ``NNS_LOG=json,debug``.
"""

from __future__ import annotations

import json
import logging
import os
import traceback

logger = logging.getLogger("nnstreamer_tpu")

#: context keys the trace-frame filter may attach to a record
_CONTEXT_KEYS = ("element", "buffer_seq")


class _TraceContextFilter(logging.Filter):
    """Attach the active trace frame's pipeline context to each record
    (pipeline/tracing.py active_frame_context)."""

    def filter(self, record: logging.LogRecord) -> bool:
        try:
            from ..pipeline.tracing import active_frame_context

            for key, value in active_frame_context().items():
                setattr(record, key, value)
        except Exception:   # noqa: BLE001 — logging must never raise
            pass
        return True


class JsonFormatter(logging.Formatter):
    """One JSON object per line (``NNS_LOG=json``)."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
            "thread": record.threadName,
        }
        for key in _CONTEXT_KEYS:
            value = getattr(record, key, None)
            if value is not None:
                out[key] = value
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def configure_from_env(env: "str | None" = None) -> None:
    """Apply ``NNS_LOG`` (idempotent): ``json`` installs the JSON
    formatter on a dedicated handler for the channel; a level name sets
    the channel level.  Comma-separated to combine."""
    spec = os.environ.get("NNS_LOG", "") if env is None else env
    if not spec:
        return
    for token in str(spec).split(","):
        token = token.strip().lower()
        if not token:
            continue
        if token == "json":
            for h in logger.handlers:
                if isinstance(h.formatter, JsonFormatter):
                    break
            else:
                handler = logging.StreamHandler()
                handler.setFormatter(JsonFormatter())
                logger.addHandler(handler)
                logger.propagate = False   # no double-emit via root
        else:
            level = logging.getLevelName(token.upper())
            if isinstance(level, int):
                logger.setLevel(level)


logger.addFilter(_TraceContextFilter())
configure_from_env()

ml_logd = logger.debug
ml_logi = logger.info
ml_logw = logger.warning
ml_loge = logger.error


def ml_loge_stacktrace(msg: str, *args) -> None:
    """Error + formatted python stack (reference _backtrace_to_string)."""
    stack = "".join(traceback.format_stack()[:-1])
    logger.error(msg + "\nBacktrace:\n%s", *args, stack)
