"""Minimal protobuf wire-format reader (dependency-free).

The image has no ``protobuf`` runtime, and vendoring generated stubs would
tie us to a schema compiler — the framework instead walks the wire format
directly by field number, the same approach the in-tree flatbuffer runtime
(``utils/flatbuf.py``) takes for flatbuffers.  Used by the TensorFlow
GraphDef loader (``filter/backends/tensorflow.py``); the hand-rolled
encoder side for the nnstreamer.proto tensor frames lives in
``decoders/serialize.py``.

Wire types: 0 varint, 1 fixed64, 2 length-delimited, 5 fixed32.
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Tuple, Union

Value = Union[int, bytes]


def read_varint(buf: bytes, off: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[off]
        off += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, off
        shift += 7
        if shift > 70:
            raise ValueError("protowire: varint too long")


def iter_fields(buf: bytes) -> Iterator[Tuple[int, int, Value]]:
    """Yield (field_number, wire_type, raw_value) over a message body.

    Length-delimited fields yield bytes; varint/fixed yield ints.
    """
    off, end = 0, len(buf)
    while off < end:
        key, off = read_varint(buf, off)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, off = read_varint(buf, off)
        elif wire == 1:
            v = struct.unpack_from("<Q", buf, off)[0]
            off += 8
        elif wire == 2:
            ln, off = read_varint(buf, off)
            v = bytes(buf[off:off + ln])
            if len(v) != ln:
                raise ValueError("protowire: truncated length-delimited field")
            off += ln
        elif wire == 5:
            v = struct.unpack_from("<I", buf, off)[0]
            off += 4
        else:
            raise ValueError(f"protowire: unsupported wire type {wire}")
        yield field, wire, v


def fields_dict(buf: bytes) -> Dict[int, List[Tuple[int, Value]]]:
    """Collect all fields: number → [(wire_type, value), ...]."""
    out: Dict[int, List[Tuple[int, Value]]] = {}
    for field, wire, v in iter_fields(buf):
        out.setdefault(field, []).append((wire, v))
    return out


def first(d: Dict[int, List[Tuple[int, Value]]], field: int,
          default=None) -> Value:
    vals = d.get(field)
    return vals[0][1] if vals else default


def repeated(d: Dict[int, List[Tuple[int, Value]]], field: int) -> List[Value]:
    return [v for _, v in d.get(field, [])]


def zigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def to_signed64(n: int) -> int:
    """Varint-encoded int64 fields arrive as unsigned — re-interpret."""
    return n - (1 << 64) if n >= (1 << 63) else n


def packed_or_repeated_varints(entries: List[Tuple[int, Value]]) -> List[int]:
    """A repeated varint field may arrive packed (wire 2) or one-per-entry
    (wire 0); normalize to a list of ints."""
    out: List[int] = []
    for wire, v in entries:
        if wire == 0:
            out.append(v)          # type: ignore[arg-type]
        elif wire == 2:
            off = 0
            while off < len(v):    # type: ignore[arg-type]
                n, off = read_varint(v, off)  # type: ignore[arg-type]
                out.append(n)
        else:
            raise ValueError("protowire: bad wire type for varint list")
    return out


def packed_or_repeated_fixed64(entries: List[Tuple[int, Value]],
                               fmt: str = "<d") -> List:
    out: List = []
    for wire, v in entries:
        if wire == 1:
            out.append(struct.unpack(fmt, struct.pack("<Q", v))[0])
        elif wire == 2:
            n = len(v) // 8        # type: ignore[arg-type]
            out.extend(struct.unpack(f"<{n}{fmt[-1]}", v))
        else:
            raise ValueError("protowire: bad wire type for fixed64 list")
    return out


def packed_or_repeated_fixed32(entries: List[Tuple[int, Value]],
                               fmt: str = "<f") -> List:
    out: List = []
    for wire, v in entries:
        if wire == 5:
            out.append(struct.unpack(fmt, struct.pack("<I", v))[0])
        elif wire == 2:
            n = len(v) // 4        # type: ignore[arg-type]
            out.extend(struct.unpack(f"<{n}{fmt[-1]}", v))
        else:
            raise ValueError("protowire: bad wire type for fixed32 list")
    return out
