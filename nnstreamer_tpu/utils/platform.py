"""Platform selection helper.

The tunneled-TPU image ships a sitecustomize that pre-selects the TPU
backend; the jax config update is the authoritative override (env vars
alone lose).  Shared by the CLI — the standalone examples/ scripts inline
the same three lines by design (they advertise copy-paste runnability).
"""

from __future__ import annotations

import os


def honor_jax_platforms() -> None:
    """Make the JAX_PLATFORMS env var win over any sitecustomize
    pre-selection.  Call before the first jax device/backend use."""
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        jax.config.update("jax_platforms", want)
