"""`nnstreamer_python` compatibility shim for reference user scripts.

The reference embeds CPython and exposes a small `nnstreamer_python`
module to user filter/converter/decoder scripts
(ext/nnstreamer/extra/nnstreamer_python_helper.py: `TensorShape`,
dims innermost-first, numpy dtypes).  Scripts written against it open
with ``import nnstreamer_python as nns`` — so a reference user's
existing .py filters (e.g. the fixtures
tests/test_models/models/passthrough.py / scaler.py) must run here
unmodified.  ``install()`` registers this module under that name
before a user script executes.

Behavior contract (not code) mirrored from the reference helper:
`TensorShape(dims, type)` holds a MUTABLE dims list (scripts mutate the
list returned by ``getDims`` in place — scaler.py does) and a numpy
dtype; rank ≤ 8, innermost-first, missing dims padded with 1.
"""

from __future__ import annotations

import os
import sys
from typing import List, Sequence

import numpy as np

_RANK_LIMIT = 8


class TensorShape:
    """One tensor's dims (innermost-first, ≤8, mutable) + numpy dtype."""

    def __init__(self, dims: Sequence[int], ttype=np.uint8):
        dims = [int(d) for d in list(dims)[:_RANK_LIMIT]]
        if not dims:
            dims = [1]
        self._dims: List[int] = dims
        self._type = np.dtype(ttype)

    def getDims(self) -> List[int]:
        # the LIVE list: reference scripts mutate it in place
        return self._dims

    def getType(self) -> np.dtype:
        return self._type

    def setDims(self, dims: Sequence[int]) -> None:
        self._dims = [int(d) for d in list(dims)[:_RANK_LIMIT]]

    def setType(self, ttype) -> None:
        self._type = np.dtype(ttype)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"TensorShape({self._dims}, {self._type.name})"


def install() -> None:
    """Make ``import nnstreamer_python`` resolve to this shim (no-op if
    a real module of that name is importable first)."""
    sys.modules.setdefault("nnstreamer_python", sys.modules[__name__])


def load_user_script(path: str, prefix: str, class_attr: str,
                     instance_attr: str):
    """Load a user script and return ``(cls_or_instance, ref_style)``.

    One loader for the three script subplugins (filter / converter /
    decoder): installs the shim, imports the file under a
    collision-safe module name, and reports whether the script is
    REFERENCE-style (it imported ``nnstreamer_python``) — callers gate
    the reference API contract on that, so scripts written against this
    framework's native contracts keep their behavior.  Returns the
    ``instance_attr`` attribute when the module defines it, else the
    ``class_attr`` CLASS (callers construct it — the filter passes the
    custom string through).  A failed exec leaves no half-registered
    module behind.
    """
    import importlib.util

    if not os.path.exists(path):
        raise FileNotFoundError(f"script not found: {path}")
    install()
    name = f"{prefix}_{abs(hash(os.path.abspath(path))) & 0xffffffff:x}"
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        sys.modules.pop(name, None)
        raise
    shim = sys.modules.get("nnstreamer_python")
    # reference-style detection must also catch `from nnstreamer_python
    # import TensorShape`: any script global that IS a shim-defined API
    # member (by identity) marks the script, not just the bound module
    # object.  Only members DEFINED here count — matching re-exported
    # imports (np, os) would misclassify native scripts that import
    # numpy themselves.
    shim_api_ids = {id(v) for k, v in vars(shim).items()
                    if not k.startswith("_")
                    and getattr(v, "__module__", None) == shim.__name__
                    } if shim else set()
    ref_style = any(v is shim or id(v) in shim_api_ids
                    for v in vars(mod).values())
    if hasattr(mod, instance_attr):
        return getattr(mod, instance_attr), ref_style
    if hasattr(mod, class_attr):
        return getattr(mod, class_attr), ref_style
    raise AttributeError(
        f"{path} defines neither {class_attr} nor {instance_attr}")


def to_tensors_info(shapes):
    """list[TensorShape] -> framework TensorsInfo (trailing 1-dims
    trimmed: the reference pads to rank 8 for the wire, the framework
    keeps natural rank)."""
    from ..tensor.info import TensorInfo, TensorsInfo
    from ..tensor.types import TensorType

    infos = []
    for s in shapes:
        dims = list(s.getDims())
        while len(dims) > 1 and dims[-1] == 1:
            dims.pop()
        infos.append(TensorInfo(TensorType.from_string(s.getType().name),
                                tuple(dims)))
    return TensorsInfo(infos)


def from_tensors_info(info) -> List[TensorShape]:
    """Framework TensorsInfo -> list[TensorShape] (padded to rank 8,
    the shape reference scripts index into)."""
    shapes = []
    for ti in info:
        dims = list(ti.dims)
        dims += [1] * (_RANK_LIMIT - len(dims))
        shapes.append(TensorShape(dims, ti.np_dtype))
    return shapes
