"""Dependency-free media decoders: PNG, PNM (PGM/PPM), WAV.

The reference's golden pipelines lean on GStreamer's media plugins
(``pngdec``, ``pnmdec``, ``wavparse``) in front of ``tensor_converter``
(e.g. tests/nnstreamer_filter_tensorflow2_lite/runTest.sh pipes
``filesrc ! pngdec ! videoconvert …``).  The TPU framework ships the same
roles as in-tree pure functions — stdlib ``zlib`` for the PNG inflate, no
PIL/libpng — wrapped by the ``pngdec``/``pnmdec``/``wavparse`` elements
(elements/mediadec.py).

Scope (sufficient for the reference's fixtures and typical goldens):
8-bit PNGs, color types gray/RGB/palette/gray+alpha/RGBA, no interlace;
binary PGM/PPM with maxval ≤ 255; PCM and IEEE-float WAV.
"""

from __future__ import annotations

import struct
import zlib
from typing import Tuple

import numpy as np

_PNG_SIG = b"\x89PNG\r\n\x1a\n"


def _unfilter(raw: np.ndarray, h: int, stride: int, bpp: int) -> np.ndarray:
    """Reverse PNG scanline filtering → flat uint8 image rows."""
    out = np.empty((h, stride), np.uint8)
    pos = 0
    prev = np.zeros(stride, np.uint16)
    for y in range(h):
        ftype = raw[pos]
        line = raw[pos + 1:pos + 1 + stride].astype(np.uint16)
        pos += 1 + stride
        if ftype == 0:              # None
            cur = line
        elif ftype == 2:            # Up
            cur = (line + prev) & 0xFF
        elif ftype == 1:            # Sub: per-channel prefix sum mod 256
            acc = np.add.accumulate(
                raw[pos - stride:pos].reshape(-1, bpp),
                axis=0, dtype=np.uint8)
            cur = acc.reshape(-1).astype(np.uint16)
        elif ftype in (3, 4):       # Average / Paeth: sequential in x
            cur = np.zeros(stride, np.uint16)
            for x in range(stride):
                a = int(cur[x - bpp]) if x >= bpp else 0
                b = int(prev[x])
                if ftype == 3:
                    val = int(line[x]) + ((a + b) >> 1)
                else:
                    c = int(prev[x - bpp]) if x >= bpp else 0
                    p = a + b - c
                    pa, pb, pc = abs(p - a), abs(p - b), abs(p - c)
                    pred = a if (pa <= pb and pa <= pc) else \
                        (b if pb <= pc else c)
                    val = int(line[x]) + pred
                cur[x] = val & 0xFF
        else:
            raise ValueError(f"png: unknown filter type {ftype}")
        out[y] = cur.astype(np.uint8)
        prev = cur
    return out


def decode_png(data: bytes) -> np.ndarray:
    """PNG bytes → (H, W, C) uint8 (C=1 gray, 3 RGB; alpha dropped)."""
    if not data.startswith(_PNG_SIG):
        raise ValueError("png: bad signature")
    pos = len(_PNG_SIG)
    ihdr = None
    palette = None
    idat = []
    while pos + 8 <= len(data):
        length, ctype = struct.unpack_from(">I4s", data, pos)
        body = data[pos + 8:pos + 8 + length]
        pos += 12 + length  # length + type + body + crc
        if ctype == b"IHDR":
            ihdr = struct.unpack(">IIBBBBB", body)
        elif ctype == b"PLTE":
            palette = np.frombuffer(body, np.uint8).reshape(-1, 3)
        elif ctype == b"IDAT":
            idat.append(body)
        elif ctype == b"IEND":
            break
    if ihdr is None or not idat:
        raise ValueError("png: missing IHDR/IDAT")
    w, h, depth, color, comp, filt, interlace = ihdr
    if depth != 8:
        raise ValueError(f"png: bit depth {depth} unsupported (8 only)")
    if interlace:
        raise ValueError("png: Adam7 interlace unsupported")
    if comp or filt:
        raise ValueError("png: nonstandard compression/filter method")
    channels = {0: 1, 2: 3, 3: 1, 4: 2, 6: 4}.get(color)
    if channels is None:
        raise ValueError(f"png: color type {color} unsupported")
    raw = np.frombuffer(zlib.decompress(b"".join(idat)), np.uint8)
    stride = w * channels
    img = _unfilter(raw, h, stride, channels).reshape(h, w, channels)
    if color == 3:
        if palette is None:
            raise ValueError("png: palette image without PLTE")
        img = palette[img[..., 0]]
    elif color == 4:    # gray+alpha → gray
        img = img[..., :1]
    elif color == 6:    # RGBA → RGB (GStreamer pipelines videoconvert this)
        img = img[..., :3]
    return np.ascontiguousarray(img)


def decode_pnm(data: bytes) -> np.ndarray:
    """Binary PGM (P5) / PPM (P6) → (H, W, C) uint8."""
    if not data[:2] in (b"P5", b"P6"):
        raise ValueError("pnm: only binary P5/P6 supported")
    fields = []
    pos = 2
    while len(fields) < 3:
        # skip whitespace and comments
        while pos < len(data) and data[pos:pos + 1].isspace():
            pos += 1
        if data[pos:pos + 1] == b"#":
            while pos < len(data) and data[pos] != 0x0A:
                pos += 1
            continue
        start = pos
        while pos < len(data) and not data[pos:pos + 1].isspace():
            pos += 1
        fields.append(int(data[start:pos]))
    pos += 1  # single whitespace after maxval
    w, h, maxval = fields
    if maxval > 255:
        raise ValueError("pnm: 16-bit samples unsupported")
    ch = 3 if data[:2] == b"P6" else 1
    img = np.frombuffer(data, np.uint8, count=w * h * ch, offset=pos)
    return img.reshape(h, w, ch).copy()


def parse_wav(data: bytes) -> Tuple[np.ndarray, int]:
    """WAV bytes → ((frames, channels) samples, rate).  PCM 8/16/32-bit
    and IEEE float32."""
    if data[:4] != b"RIFF" or data[8:12] != b"WAVE":
        raise ValueError("wav: not a RIFF/WAVE file")
    pos = 12
    fmt = None
    samples = None
    while pos + 8 <= len(data):
        cid, ln = struct.unpack_from("<4sI", data, pos)
        body = data[pos + 8:pos + 8 + ln]
        pos += 8 + ln + (ln & 1)
        if cid == b"fmt ":
            fmt = struct.unpack_from("<HHIIHH", body)
        elif cid == b"data":
            samples = body
    if fmt is None or samples is None:
        raise ValueError("wav: missing fmt/data chunk")
    audio_fmt, channels, rate, _, _, bits = fmt
    if audio_fmt == 3 and bits == 32:
        arr = np.frombuffer(samples, np.float32)
    elif audio_fmt == 1 and bits == 16:
        arr = np.frombuffer(samples, np.int16)
    elif audio_fmt == 1 and bits == 8:
        arr = np.frombuffer(samples, np.uint8)
    elif audio_fmt == 1 and bits == 32:
        arr = np.frombuffer(samples, np.int32)
    else:
        raise ValueError(f"wav: format {audio_fmt}/{bits}bit unsupported")
    if channels > 1:
        arr = arr.reshape(-1, channels)
    else:
        arr = arr.reshape(-1, 1)
    return arr.copy(), rate
