"""NTP wall-clock utilities for cross-device timestamp alignment.

Parity with the reference's NTP support (gst/mqtt/ntputil.c: SNTPv4 query,
xmit-timestamp → unix epoch µs, ``pool.ntp.org:123`` default) used by its
MQTT elements to embed a shared epoch so PTS from different devices can be
aligned (Documentation/synchronization-in-mqtt-elements.md).  The network
call is injectable (``_query``) so tests run hermetically — the reference
gmocks ``ntohl``/``recvfrom`` the same way
(tests/gstreamer_mqtt/unittest_ntp_util_mock.cc).
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Callable, Optional, Sequence

#: seconds between the NTP era (1900) and the unix epoch (1970)
NTP_TIMESTAMP_DELTA = 2_208_988_800
_FRAC_PER_SEC = 1 << 32

DEFAULT_HOSTS = ("pool.ntp.org",)
DEFAULT_PORT = 123


class NTPError(OSError):
    pass


def _udp_query(host: str, port: int, packet: bytes, timeout: float) -> bytes:
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        s.settimeout(timeout)
        s.sendto(packet, (host, port))
        data, _ = s.recvfrom(512)
    return data


def parse_xmit_epoch_us(response: bytes) -> int:
    """Transmit-timestamp (offset 40: u32 sec, u32 frac, big endian) →
    unix-epoch microseconds (reference ntputil.c conversion)."""
    if len(response) < 48:
        raise NTPError(f"short NTP response ({len(response)} bytes)")
    sec, frac = struct.unpack_from(">II", response, 40)
    if sec == 0:
        raise NTPError("NTP response has zero transmit timestamp")
    usec = (sec - NTP_TIMESTAMP_DELTA) * 1_000_000 \
        + (frac * 1_000_000) // _FRAC_PER_SEC
    return usec


def get_epoch_us(hosts: Optional[Sequence[str]] = None,
                 ports: Optional[Sequence[int]] = None,
                 timeout: float = 3.0,
                 _query: Optional[Callable[[str, int, bytes, float],
                                           bytes]] = None) -> int:
    """Query the first answering NTP server for the current unix epoch (µs).

    Reference: ``ntputil_get_epoch`` — iterates (host, port) pairs, SNTPv4
    client packet (LI=0 VN=4 mode=3), returns xmit timestamp.
    """
    hosts = list(hosts or DEFAULT_HOSTS)
    ports = list(ports or [DEFAULT_PORT] * len(hosts))
    query = _query or _udp_query
    packet = bytearray(48)
    packet[0] = 0x23                      # LI=0, VN=4, mode=3 (client)
    err: Optional[Exception] = None
    for host, port in zip(hosts, ports):
        try:
            return parse_xmit_epoch_us(query(host, port, bytes(packet),
                                             timeout))
        except (OSError, struct.error) as e:
            err = e
    raise NTPError(f"no NTP server reachable: {err}")


class WallClockSync:
    """Cached NTP↔local offset; falls back to the local clock when no
    server answers (the reference's ``g_get_real_time`` fallback).

    ``now_us()`` is the NTP-aligned wall clock; ``synced`` says whether an
    NTP server actually contributed.  Offset refreshes lazily every
    ``refresh_s`` (the caching the reference marks @todo).
    """

    def __init__(self, hosts: Optional[Sequence[str]] = None,
                 ports: Optional[Sequence[int]] = None,
                 refresh_s: float = 300.0,
                 _query=None, _local_us: Optional[Callable[[], int]] = None):
        self._hosts, self._ports = hosts, ports
        self._refresh_s = refresh_s
        self._query = _query
        self._local_us = _local_us or (lambda: time.time_ns() // 1000)
        self._offset_us = 0
        self._synced = False
        self._last_sync = float("-inf")
        self._lock = threading.Lock()

    @property
    def synced(self) -> bool:
        return self._synced

    def _maybe_refresh(self) -> None:
        now = time.monotonic()
        if now - self._last_sync < self._refresh_s:
            return
        self._last_sync = now
        try:
            ntp = get_epoch_us(self._hosts, self._ports, _query=self._query)
            self._offset_us = ntp - self._local_us()
            self._synced = True
        except NTPError:
            # keep the last-known-good offset on a transient re-query
            # failure — zeroing it would jump now_us() mid-stream
            if not self._synced:
                self._offset_us = 0

    def offset_us(self) -> int:
        with self._lock:
            self._maybe_refresh()
            return self._offset_us

    def now_us(self) -> int:
        with self._lock:
            self._maybe_refresh()
            return self._local_us() + self._offset_us


def stream_origin_epoch_us(ntp_host, element_name: str = "edge") -> int:
    """Stream-origin wall-clock epoch (µs) for edge elements.

    Shared by edge_sink/edge_src start(): parses the ``ntp-host`` property
    (comma-separated servers, None → local clock), queries via
    :class:`WallClockSync`, and — when NTP was explicitly requested but no
    server answered — warns loudly instead of silently using the local
    clock, since unaligned epochs corrupt cross-device PTS re-basing.
    """
    from .log import ml_logw

    if not ntp_host:
        return time.time_ns() // 1000
    hosts = [h.strip() for h in str(ntp_host).split(",") if h.strip()]
    if not hosts:
        # degenerate value like "," — local clock, NOT the default public
        # pool (get_epoch_us would substitute DEFAULT_HOSTS for [])
        return time.time_ns() // 1000
    sync = WallClockSync(hosts=hosts)
    epoch = sync.now_us()
    if not sync.synced:
        ml_logw("%s: ntp-host=%s set but no NTP server answered — "
                "falling back to the LOCAL clock; cross-device PTS "
                "alignment will be off by this host's clock error",
                element_name, ntp_host)
    return epoch
