"""Measurement-derived runtime defaults (data over theory).

The quant-graph ``compute:auto`` mode on TPU is a default DECIDED BY
HARDWARE DATA, not theory: in theory int8×int8→int32 on the MXU is 2×
the bf16 rate (v5e) with ¼ the f32 weight traffic, but the only
hardware capture so far (BENCH_int8_r04.json, degraded window) measured
native int8 at 0.65× the f32-emulation batched rate — with an
internally inconsistent per-invoke win, pointing at window drift.

``tools/tflite_int8_tpu_bench.py`` measures all three modes
(f32-emulation / native int8 / weight-only w8) on the real chip each
healthy capture window and emits a ``recommended_default``; running it
with ``--apply`` rewrites the record below from its green artifact, so
the shipped default always carries its own provenance.  The reference's
analogue decision — which delegate serves a quant graph — is hardcoded
per-vendor (tensor_filter_tensorflow_lite.cc:55-118); here it follows
the measurement.
"""

#: compute mode quant tflite graphs get under ``compute:auto`` on TPU:
#: "int8" (native MXU int8), "w8" (weight-only), or "float32"
#: (f32 emulation).
QUANT_AUTO_TPU = "int8"

#: where the current value came from (rewritten by
#: tools/tflite_int8_tpu_bench.py --apply)
QUANT_AUTO_PROVENANCE = (
    "theory default (MXU int8 2x bf16 rate, exact accumulation); the "
    "only capture, BENCH_int8_r04.json, measured 0.65x vs emulation "
    "batched in a DEGRADED window with an inconsistent per-invoke win "
    "- awaiting a healthy-window 3-mode capture (r5 loop armed)")

#: (block_q, block_k) the flash kernel defaults to for long sequences
#: on TPU, measured by tools/flash_tpu_bench.py --tune and applied with
#: --tune --apply.  Used only when both sequence lengths cover the tile
#: (short sequences keep the 128x128 MXU-shaped default so tiny inputs
#: don't pad up to a giant tile).
FLASH_TILES = (128, 128)

FLASH_TILES_PROVENANCE = (
    "default (MXU-shaped 128x128); no healthy-window tile-tune capture "
    "applied yet (r5 loop runs flash_tpu_bench --tune each window)")

#: Per-length measured tiles ``((T, block_q, block_k), ...)`` — the
#: tune step sweeps each length in its TUNE_LENGTHS (8192 and 16384:
#: the 16k grid-overhead loss is why long-T tiles differ) and each
#: row ships only with an on-chip gradcheck at its winning tile.
#: _default_tiles picks the largest measured length <= the sequence;
#: lengths below every row fall back to FLASH_TILES.  Applied with
#: ``flash_tpu_bench --tune --apply``.
FLASH_TILES_BY_T = ()

FLASH_TILES_BY_T_PROVENANCE = (
    "no healthy-window tile-tune capture applied yet (r5 loop runs "
    "flash_tpu_bench --tune each window)")

#: Sequence-length threshold above which full-attention callers
#: (``flash=None``) pick the Pallas flash kernel over naive XLA
#: attention (ops/flash_attention.py flash_wins).  Measured by the
#: timing rows of tools/flash_tpu_bench.py with SUFFIX-WIN semantics:
#: the smallest measured T such that the kernel wins (speedup > 1, or
#: naive fails to compile/OOMs) at that T *and every longer measured
#: T* — a threshold gate must not route an interior losing length to
#: the kernel just because some shorter length won.  Applied with
#: ``flash_tpu_bench --apply-crossover <proof.json>``.
FLASH_MIN_T = 16384

FLASH_MIN_T_PROVENANCE = (
    "r4 default: BENCH_flash_r04.json showed naive faster at every "
    "captured length (0.81x@2k, 0.95x@8k), kernel kept only for the "
    "O(T*d) memory regime; awaiting a healthy-window proof capture "
    "(r5 loop applies the measured crossover automatically)")

#: Measured per-length kernel-vs-naive outcomes, ``((T, wins), ...)``
#: sorted by T, from the same proof timings as FLASH_MIN_T.  The
#: hardware data is NOT monotonic in T (r5: win@2k, win@8k, loss@16k
#: under un-tuned long-T tiles), which a single threshold cannot
#: express — within the table's measured span ``flash_wins`` routes by
#: this evidence (exact hit: that row; between rows: the kernel only
#: when BOTH neighbors won); outside the span the FLASH_MIN_T
#: threshold gate still decides, preserving the memory-regime fallback
#: beyond the longest measurement.  Rows where the kernel itself
#: errored record ``wins=False``; naive-path failures that look like
#: transient infra (not device capacity) contribute no row.  Applied
#: with ``flash_tpu_bench --apply-crossover``.
FLASH_WIN_TABLE = ((2048,True),(8192,True),(16384,False),)

FLASH_WIN_TABLE_PROVENANCE = (
    "measured: BENCH_flash_r05.json \u2014 2048:1.365x, 8192:1.011x, 16384:0.795x, 32768:no-evidence; TPU v5 lite0; applied by flash_tpu_bench --apply-crossover"
)
