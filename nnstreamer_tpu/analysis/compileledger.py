"""Runtime compile-ledger sentinel: every XLA compile, attributed.

The static auditor (:mod:`~nnstreamer_tpu.analysis.jitaudit`) proves
the bounded-executable discipline over the source; this module proves
it over a RUN.  With ``NNS_JIT_SENTINEL=1`` every executable-cache miss
in the wired sites — ``SegmentExec._compile``, the four
``DecodeEngine`` warm-set installers, the ``JitExecMixin`` dispatch
paths — calls :func:`record` with a *site* (a stable dotted name,
``llm.engine.step``) and a *signature* (the hashable tuple that keyed
the executable).  The ledger keeps, per site:

- the ordered compile events, each carrying the **field diff against
  the nearest cached neighbor** — the one previously recorded
  signature differing in the fewest fields.  A compile storm's ledger
  reads like a confession: ``site=llm.engine.step seq=17
  diff=(('padded', 128, 136),)`` says someone is feeding raw lengths
  past the quantizer.
- a **budget**, declared at the site with :func:`compile_budget`:
  the number of distinct signatures the site is ALLOWED to compile
  (buckets × variants, a small closed set by design).  Exceeding it
  raises :class:`CompileBudgetExceeded` carrying both the offending
  signature and its nearest neighbor, diffed — the bench gates and
  soak runs turn silent recompile regressions into a stack trace at
  the moment of the extra compile, not a throughput mystery later.

The ledger exports ``nns_jit_compiles_total{site=...}`` through the
obs registry, so the federation plane and flight recorder see compile
storms fleet-wide; the counter is incremented OUTSIDE the ledger lock
(lock class ``analysis.ledger``, rank just below ``obs.metrics``).

Sentinel OFF (the default) costs one attribute load and one falsy test
per *compile* — dispatch paths guard their signature bookkeeping with
``if compileledger.ENABLED:`` so steady-state inference pays nothing.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from .sanitizer import make_lock

__all__ = [
    "ENABLED", "enabled", "configure", "record", "compile_budget",
    "declare_budget", "snapshot", "events", "budgets", "reset",
    "CompileEvent", "CompileBudgetExceeded", "diff_signatures",
    "format_diff", "LEDGER",
]


def _env_on() -> bool:
    return os.environ.get("NNS_JIT_SENTINEL", "").strip().lower() \
        not in ("", "0", "false", "no", "off")


#: module-level flag so hot paths can guard with a single attribute
#: load; mutate only through :func:`configure`
ENABLED: bool = _env_on()


def enabled() -> bool:
    return ENABLED


def configure(on: bool) -> None:
    """Flip the sentinel at runtime (tests, bench stages).  Does not
    clear the ledger — call :func:`reset` for that."""
    global ENABLED
    ENABLED = bool(on)


def _normalize(signature: Any) -> Tuple[Tuple[str, Any], ...]:
    """Signatures become ``((field, value), ...)`` so diffs are
    field-addressed.  Mappings keep their keys; plain sequences get
    positional ``arg[i]`` names; scalars become a single field."""
    if isinstance(signature, dict):
        return tuple(sorted((str(k), v) for k, v in signature.items()))
    if isinstance(signature, (tuple, list)):
        out = []
        for i, v in enumerate(signature):
            if isinstance(v, (tuple, list)) and len(v) == 2 \
                    and isinstance(v[0], str):
                out.append((v[0], v[1]))
            else:
                out.append((f"arg[{i}]", v))
        return tuple(out)
    return ((("value"), signature),)


def diff_signatures(a: Tuple[Tuple[str, Any], ...],
                    b: Tuple[Tuple[str, Any], ...],
                    ) -> Tuple[Tuple[str, Any, Any], ...]:
    """``((field, a_value, b_value), ...)`` for every field present in
    either signature where the values differ."""
    da, db = dict(a), dict(b)
    out: List[Tuple[str, Any, Any]] = []
    for k in list(da) + [k for k in db if k not in da]:
        va, vb = da.get(k, "<absent>"), db.get(k, "<absent>")
        if va != vb:
            out.append((k, va, vb))
    return tuple(out)


def format_diff(diff: Tuple[Tuple[str, Any, Any], ...]) -> str:
    if not diff:
        return "(first compile at site)"
    return ", ".join(f"{k}: {va!r} -> {vb!r}" for k, va, vb in diff)


@dataclass
class CompileEvent:
    site: str
    seq: int                                   # per-site ordinal, 0-based
    signature: Tuple[Tuple[str, Any], ...]
    #: field diff vs the nearest previously-recorded signature at this
    #: site (empty for the site's first compile)
    diff: Tuple[Tuple[str, Any, Any], ...]

    def __str__(self) -> str:
        return (f"compile site={self.site} seq={self.seq} "
                f"diff=({format_diff(self.diff)})")


class CompileBudgetExceeded(RuntimeError):
    """A site compiled more distinct signatures than it declared.

    Carries the offending event so gates can assert on structure, and
    renders BOTH signatures diffed — the recompile's cause is the
    message, not an exercise for the reader."""

    def __init__(self, event: CompileEvent, budget: int,
                 neighbor: Optional[Tuple[Tuple[str, Any], ...]]):
        self.event = event
        self.budget = budget
        self.neighbor = neighbor
        msg = (f"compile budget exceeded at site {event.site!r}: "
               f"compile #{event.seq + 1} > budget {budget}\n"
               f"  new signature:     {event.signature!r}\n"
               f"  nearest neighbor:  {neighbor!r}\n"
               f"  differing fields:  {format_diff(event.diff)}")
        super().__init__(msg)


class CompileLedger:
    """Process-wide compile event log + per-site budgets."""

    def __init__(self) -> None:
        self._lock = make_lock("analysis.ledger")
        self._events: List[CompileEvent] = []
        self._site_sigs: Dict[str, List[Tuple[Tuple[str, Any], ...]]] \
            = {}
        self._site_seq: Dict[str, int] = {}
        self._budgets: Dict[str, int] = {}

    # -- write path ----------------------------------------------------
    def record(self, site: str, signature: Any) -> CompileEvent:
        """Record one compile.  Raises CompileBudgetExceeded AFTER
        recording (the ledger keeps the evidence either way)."""
        sig = _normalize(signature)
        with self._lock:
            sigs = self._site_sigs.setdefault(site, [])
            neighbor: Optional[Tuple[Tuple[str, Any], ...]] = None
            diff: Tuple[Tuple[str, Any, Any], ...] = ()
            if sigs:
                neighbor = min(
                    sigs, key=lambda s: len(diff_signatures(s, sig)))
                diff = diff_signatures(neighbor, sig)
            seq = self._site_seq.get(site, 0)
            self._site_seq[site] = seq + 1
            event = CompileEvent(site, seq, sig, diff)
            novel = sig not in sigs
            if novel:
                sigs.append(sig)
            self._events.append(event)
            budget = self._budgets.get(site)
            # only a NOVEL signature can overflow the budget: the
            # budget caps the executable SET, not the compile count
            over = budget is not None and novel and len(sigs) > budget
        # counter outside the ledger lock: analysis.ledger (73) ranks
        # below obs.metrics (74), and we never hold both
        try:
            from ..obs.metrics import REGISTRY
            REGISTRY.counter("nns_jit_compiles_total", site=site).inc()
        except Exception:
            pass                   # obs plane absent: ledger still works
        if over:
            raise CompileBudgetExceeded(event, budget, neighbor)
        return event

    def declare_budget(self, site: str, n: int) -> None:
        with self._lock:
            self._budgets[site] = int(n)

    # -- read path -----------------------------------------------------
    def snapshot(self) -> Dict[str, int]:
        """site -> total compiles recorded (the bench gates diff two
        of these around a steady-state window)."""
        with self._lock:
            out: Dict[str, int] = {}
            for ev in self._events:
                out[ev.site] = out.get(ev.site, 0) + 1
            return out

    def count(self, site: Optional[str] = None) -> int:
        with self._lock:
            if site is None:
                return len(self._events)
            return sum(1 for ev in self._events if ev.site == site)

    def events(self, site: Optional[str] = None) -> List[CompileEvent]:
        with self._lock:
            if site is None:
                return list(self._events)
            return [ev for ev in self._events if ev.site == site]

    def budgets(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._budgets)

    def reset(self) -> None:
        """Clear events and signature history; budgets persist (they
        are declarations, not state)."""
        with self._lock:
            self._events.clear()
            self._site_sigs.clear()
            self._site_seq.clear()


#: the process ledger; import the module and call the functions below
LEDGER = CompileLedger()


def record(site: str, signature: Any) -> Optional[CompileEvent]:
    """The sentinel write path: no-op (None) when the sentinel is off."""
    if not ENABLED:
        return None
    return LEDGER.record(site, signature)


def declare_budget(site: str, n: int) -> None:
    LEDGER.declare_budget(site, n)


def compile_budget(n: int, site: str):
    """Decorator form of :func:`declare_budget`: annotate the function
    that performs the compile with the number of distinct signatures
    its site may legitimately produce.  The function body is returned
    unchanged — the declaration is the point::

        @compile_budget(16, site="llm.engine.step")
        def _step_fn(self, padded): ...
    """
    def deco(fn):
        LEDGER.declare_budget(site, n)
        return fn
    return deco


def snapshot() -> Dict[str, int]:
    return LEDGER.snapshot()


def count(site: Optional[str] = None) -> int:
    return LEDGER.count(site)


def events(site: Optional[str] = None) -> List[CompileEvent]:
    return LEDGER.events(site)


def budgets() -> Dict[str, int]:
    return LEDGER.budgets()


def reset() -> None:
    LEDGER.reset()
