"""Static pipeline verifier: reject bad graphs before any buffer flows.

The NNStreamer papers' core pipeline claim is that stream topologies can
be validated BEFORE dataflow starts; this module applies it to the
constructed (not yet playing) pipeline graph:

- **caps compatibility**: for every linked src pad, what the element can
  statically produce (pad template, narrowed by a ``caps`` property /
  capsfilter constraint) must intersect what downstream will accept
  (the existing ``peer_allowed_caps`` query, which walks through
  passthrough elements and capsfilter constraints).  An empty
  intersection is exactly the negotiation failure that would otherwise
  crash the first streaming thread — reported with the element path.
- **deadlock cycles**: the pad graph must be a DAG.  A dataflow cycle
  (e.g. a tee branch feeding back into a mux upstream of the tee)
  deadlocks once the bounded queue on the cycle fills, or recurses
  unboundedly without one.  Recurrent topologies built through
  ``tensor_reposink``/``tensor_reposrc`` slots are detected as LOGICAL
  cycles and reported as info (that is the supported recurrence
  mechanism: the repo slot decouples the cycle with its own thread and
  a dummy priming frame).
- **dead branches**: elements no source can ever feed (warning), and
  unlinked pads (error — mirrors ``Pipeline._check_links``).
- **scheduler misconfigurations**: per-element
  :meth:`~nnstreamer_tpu.pipeline.element.Element.static_check` hooks
  report configurations the scheduler cannot honor (``workers>1`` with
  ``batch>1``, ``inflight``/``batch-timeout-ms`` without batching,
  ``mesh:dp=N`` without micro-batching, demux pick/pad mismatches) —
  the same decisions ``start()`` makes, surfaced before play.
- **thread-boundary structure**: which streaming thread drives which
  segment (``thread_segments``), plus warnings for fan-outs that
  serialize branches on one thread.

Entry points: :func:`verify_pipeline` returns findings;
:func:`preflight` is called by ``Pipeline.play()`` (``NNS_VERIFY=0``
disables) and raises :class:`~nnstreamer_tpu.pipeline.graph.VerifyError`
on error-severity findings; ``launch.py --check`` drives the same walk
from the CLI without playing.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional, Set, Tuple

#: severity order for sorting reports
_SEV_ORDER = {"error": 0, "warning": 1, "info": 2}


@dataclasses.dataclass
class Finding:
    severity: str          # "error" | "warning" | "info"
    rule: str              # "caps-mismatch" | "deadlock-cycle" | ...
    path: str              # element path diagnostic ("a.src -> b -> c")
    message: str
    element: Any = dataclasses.field(default=None, repr=False)

    def __str__(self) -> str:
        return f"{self.severity} [{self.rule}] {self.path}: {self.message}"


def verify_pipeline(pipeline) -> List[Finding]:
    """Run every static check; returns findings sorted errors-first."""
    findings: List[Finding] = []
    _check_links(pipeline, findings)
    _check_cycles(pipeline, findings)
    _check_reachability(pipeline, findings)
    _check_caps(pipeline, findings)
    _check_element_configs(pipeline, findings)
    _check_thread_structure(pipeline, findings)
    _check_lowering(pipeline, findings)
    findings.sort(key=lambda f: _SEV_ORDER.get(f.severity, 3))
    return findings


def preflight(pipeline) -> None:
    """``Pipeline.play()`` hook: verify, log warnings, raise on errors.

    ``NNS_VERIFY=0`` disables (the escape hatch for intentionally
    unusual graphs); anything else runs the walk — it is a pure graph
    traversal, microseconds against a play() that spawns threads."""
    if os.environ.get("NNS_VERIFY", "1") == "0":
        return
    findings = verify_pipeline(pipeline)
    errors = [f for f in findings if f.severity == "error"]
    warnings = [f for f in findings if f.severity == "warning"]
    if warnings:
        from ..utils.log import ml_logw

        for f in warnings:
            ml_logw("verify %s: %s", pipeline.name, f)
    if errors:
        from ..pipeline.graph import VerifyError

        raise VerifyError(errors)


# --------------------------------------------------------------------------
# graph helpers
# --------------------------------------------------------------------------

def _succ(el) -> List[Any]:
    """Downstream peer elements of ``el`` (via linked src pads)."""
    return [p.peer.element for p in el.src_pads if p.peer is not None]


def _chain_path(el, limit: int = 6) -> str:
    """Element-path diagnostic: ``el`` and its linear downstream run."""
    parts = [el.name]
    cur = el
    for _ in range(limit):
        nxt = _succ(cur)
        if len(nxt) != 1:
            break
        cur = nxt[0]
        parts.append(cur.name)
    if _succ(cur):
        parts.append("...")
    return " -> ".join(parts)


def _is_source(el) -> bool:
    from ..pipeline.graph import Source

    return isinstance(el, Source) or not el.sink_pads


# --------------------------------------------------------------------------
# checks
# --------------------------------------------------------------------------

def _check_links(pipeline, findings: List[Finding]) -> None:
    for el in pipeline.elements:
        for p in el.sink_pads + el.src_pads:
            if p.peer is None:
                findings.append(Finding(
                    "error", "unlinked-pad", p.full_name,
                    "pad is not linked (request pads are created "
                    "sequentially: naming sink_N also creates "
                    "sink_0..sink_N-1, which must all be linked)", el))


def _cycle_from(start, adjacency) -> Optional[List[Any]]:
    """Return one cycle reachable from ``start`` as an element list, or
    None.  Iterative DFS with an on-stack set."""
    stack: List[Tuple[Any, int]] = [(start, 0)]
    path: List[Any] = []
    on_path: Set[int] = set()
    visited: Set[int] = set()
    while stack:
        node, idx = stack.pop()
        if idx == 0:
            if id(node) in visited:
                continue
            visited.add(id(node))
            path.append(node)
            on_path.add(id(node))
        succ = adjacency.get(id(node), [])
        if idx < len(succ):
            stack.append((node, idx + 1))
            child = succ[idx]
            if id(child) in on_path:
                return path[path.index(child):] + [child]
            if id(child) not in visited:
                stack.append((child, 0))
        else:
            path.pop()
            on_path.discard(id(node))
    return None


def _check_cycles(pipeline, findings: List[Finding]) -> None:
    adjacency: Dict[int, List[Any]] = {
        id(el): _succ(el) for el in pipeline.elements}
    cycle = None
    for el in pipeline.elements:
        cycle = _cycle_from(el, adjacency)
        if cycle is not None:
            break
    if cycle is not None:
        names = " -> ".join(e.name for e in cycle)
        has_queue = any(e.FACTORY == "queue" for e in cycle)
        how = ("deadlocks once the bounded queue on the cycle fills"
               if has_queue else
               "recurses unboundedly on one streaming thread")
        findings.append(Finding(
            "error", "deadlock-cycle", names,
            f"dataflow cycle in the pad graph ({how}); recurrent "
            "topologies must decouple through tensor_reposink/"
            "tensor_reposrc slots", cycle[0]))
        return
    # logical recurrence via repo slots: reposink slot K feeds reposrc
    # slot K.  Legal (the slot decouples the cycle) — report as info so
    # --check shows the topology is recurrent.
    slots_out: Dict[int, Any] = {}
    for el in pipeline.elements:
        if el.FACTORY == "tensor_reposink":
            slots_out[int(el.get_property("slot-index"))] = el
    if not slots_out:
        return
    for el in pipeline.elements:
        if el.FACTORY == "tensor_reposrc":
            slot = int(el.get_property("slot-index"))
            sink = slots_out.get(slot)
            if sink is not None:
                findings.append(Finding(
                    "info", "recurrent-topology",
                    f"{sink.name} -> [repo slot {slot}] -> {el.name}",
                    "recurrent cycle through the repo slot (decoupled: "
                    "reposrc primes frame 0 with a dummy buffer)", el))


def _check_reachability(pipeline, findings: List[Finding]) -> None:
    sources = [el for el in pipeline.elements if _is_source(el)]
    reached: Set[int] = set()
    frontier = list(sources)
    while frontier:
        el = frontier.pop()
        if id(el) in reached:
            continue
        reached.add(id(el))
        frontier.extend(_succ(el))
    for el in pipeline.elements:
        if id(el) not in reached:
            findings.append(Finding(
                "warning", "dead-branch", _chain_path(el),
                "no source can feed this element (dead branch: it will "
                "never see a buffer or an EOS, so Pipeline.wait() would "
                "block forever on its sink)", el))


def _check_caps(pipeline, findings: List[Finding]) -> None:
    for el in pipeline.elements:
        for pad in el.src_pads:
            if pad.peer is None:
                continue   # reported by unlinked-pad
            try:
                produced = el.static_src_caps(pad)
            except Exception as exc:  # noqa: BLE001 - bad caps property
                findings.append(Finding(
                    "error", "caps-mismatch", _chain_path(el),
                    f"cannot evaluate {el.name}'s output caps: {exc}", el))
                continue
            if produced is None:
                continue   # element cannot know statically: skip
            try:
                allowed = pad.peer_allowed_caps()
            except Exception as exc:  # noqa: BLE001 - bad constraint
                findings.append(Finding(
                    "error", "caps-mismatch", _chain_path(el),
                    f"downstream caps query failed at {pad.full_name}: "
                    f"{exc}", el))
                continue
            if produced.intersect(allowed).is_empty():
                findings.append(Finding(
                    "error", "caps-mismatch", _chain_path(el),
                    f"{pad.full_name} produces {produced} but downstream "
                    f"accepts {allowed}: no common caps — negotiation "
                    "would fail on the first CAPS event", el))


def _check_element_configs(pipeline, findings: List[Finding]) -> None:
    for el in pipeline.elements:
        try:
            checks = el.static_check()
        except Exception as exc:  # noqa: BLE001 - a config so broken the
            #                       check itself failed is an error too
            findings.append(Finding(
                "error", "misconfig", el.name,
                f"static_check failed: {exc!r}", el))
            continue
        for check in checks:
            # two shapes: (severity, message) — the original hook
            # contract, reported under the generic "misconfig" rule —
            # and (severity, rule, message) for elements whose checks
            # are named rules of their own (the llm element's
            # llm-slots-lt-batch / llm-no-max-seq / llm-page-size /
            # llm-prefix-without-pages family), so --check output and
            # tests can address them by name
            if len(check) == 3:
                severity, rule, message = check
            else:
                severity, message = check
                rule = "misconfig"
            findings.append(Finding(
                severity, rule, _chain_path(el), message, el))


def _check_lowering(pipeline, findings: List[Finding]) -> None:
    """``fuse=xla`` requested: warn for every linear element whose
    :meth:`~nnstreamer_tpu.pipeline.element.Element.lower_reason` says
    it cannot join a whole-segment XLA computation — its segment will
    silently run at the fuse-python tier.  Property-level (pre-start)
    assessment, so ``launch.py --check`` reports it without playing;
    the compiled plan's ``fallback`` row is the runtime twin."""
    if getattr(pipeline, "fuse_tier", None) != "xla":
        return
    for el in pipeline.elements:
        if len(el.sink_pads) != 1 or len(el.src_pads) != 1:
            continue
        try:
            # boundary elements (queue etc.) never fuse: no warning.
            # A plan_step that needs started state (tensor_filter) is
            # assumed fusable; lower_reason is property-level.
            if el.plan_step() is None:
                continue
        except Exception:  # noqa: BLE001 — state-dependent plan_step
            pass
        try:
            reason = el.lower_reason()
        except Exception as exc:  # noqa: BLE001 — config so broken the
            #                       assessment itself failed
            reason = f"lower_reason failed: {exc!r}"
        if reason:
            findings.append(Finding(
                "warning", "xla-fallback", _chain_path(el),
                f"fuse=xla requested but {el.name} cannot lower: "
                f"{reason} — its segment will run fuse-python", el))


def _check_thread_structure(pipeline, findings: List[Finding]) -> None:
    from ..pipeline.graph import Queue, Tee

    for el in pipeline.elements:
        if isinstance(el, Tee):
            branches = [p for p in el.src_pads if p.peer is not None]
            if len(branches) < 2:
                continue
            queued = sum(1 for p in branches
                         if isinstance(p.peer.element, Queue))
            if queued < len(branches) - 1:
                findings.append(Finding(
                    "info", "thread-structure", _chain_path(el),
                    f"{len(branches) - queued} of {len(branches)} tee "
                    "branches run serialized on the upstream streaming "
                    "thread (insert a queue per branch for parallelism)",
                    el))


# --------------------------------------------------------------------------
# fleet configs (reported by --check on a .json argument)
# --------------------------------------------------------------------------

def verify_fleet_config(config) -> List[Finding]:
    """Static findings for a fleet config document
    (:class:`~nnstreamer_tpu.fleet.config.FleetConfig` or a dict/path
    it loads from).  The fleet tier's structural failure modes are
    graph-shaped — a router fronting zero workers, inverted autoscaler
    bounds, a drain grace that cuts resident cross-stream buckets —
    so they get the pipeline verifier's treatment: named errors BEFORE
    anything spawns (``launch.py --check fleet.json``)."""
    from ..fleet.config import load_fleet_config

    try:
        cfg = load_fleet_config(config)
    except (OSError, ValueError, TypeError) as exc:
        return [Finding("error", "fleet-config", str(config),
                        f"cannot load fleet config: {exc}")]
    findings = [Finding(sev, rule, "fleet", message)
                for sev, rule, message in cfg.validate()]
    findings.sort(key=lambda f: _SEV_ORDER.get(f.severity, 3))
    return findings


# --------------------------------------------------------------------------
# thread-boundary structure (reported by --check)
# --------------------------------------------------------------------------

def thread_segments(pipeline) -> List[Dict[str, Any]]:
    """The pipeline's streaming-thread structure: one entry per thread
    owner (every Source and every Queue owns a thread), with the
    elements that run synchronously downstream of it up to the next
    boundary."""
    from ..pipeline.graph import Queue, Source

    segments: List[Dict[str, Any]] = []
    for el in pipeline.elements:
        if not isinstance(el, (Source, Queue)):
            continue
        members: List[str] = []
        frontier = list(_succ(el))
        seen: Set[int] = set()
        while frontier:
            nxt = frontier.pop()
            if id(nxt) in seen or isinstance(nxt, Queue):
                continue
            seen.add(id(nxt))
            members.append(nxt.name)
            frontier.extend(_succ(nxt))
        segments.append({
            "thread": ("src:" if isinstance(el, Source) else "queue:")
            + el.name,
            "elements": members,
        })
    return segments
