"""Declared lock hierarchy for the whole package.

Every lock in the codebase belongs to a named CLASS of locks (all
per-connection send locks are one class, every queue's slot condition is
one class, ...).  The hierarchy assigns each class a rank; the invariant
is:

    A thread holding a lock of rank R may only acquire locks of rank
    strictly GREATER than R (same-rank re-acquisition is allowed only
    for classes in SAME_NAME_OK, where distinct instances nest strictly
    along the dataflow DAG and therefore cannot invert).

Ranks encode the real acquisition chains of the streaming hot path: a
``tensor_filter`` deadline flush pushes downstream while holding its
coalesce lock, so everything a downstream ``chain()`` can take — queue
slot conditions, collectpads, send locks, the buffer pool, the tracer,
the pipeline state condition — must rank above ``filter.coalesce``.

Creation sites register by name through
:func:`nnstreamer_tpu.analysis.sanitizer.make_lock` /
``make_rlock`` / ``make_condition``; ``tools/nnslint.py`` resolves the
same names statically from those calls, so the static checker and the
runtime sanitizer enforce one registry.  Adding a lock to the codebase
means adding (or reusing) a class here — an unranked name is itself a
lint warning.
"""

from __future__ import annotations

from typing import Dict, Optional

#: name -> rank.  Lower rank = acquired earlier (outermost).  Gaps are
#: deliberate: new classes slot in without renumbering.
HIERARCHY: Dict[str, int] = {
    # scheduling layer -----------------------------------------------------
    "planner": 10,          # SegmentPlanner._lock (plan compile/invalidate)
    "element": 20,          # Element._lock (per-element state guard)
    "filter.coalesce": 30,  # tensor_filter micro-batch coalescer
    "filter.workers": 32,   # tensor_filter worker-pool condition
    "llm.engine": 34,       # tensor_llm pending-queue/session condition
    #                         (llm/element.py): the decode thread takes
    #                         session bookkeeping under it but NEVER
    #                         pushes downstream while holding it, and
    #                         chain() enqueues under it — so everything
    #                         a push can reach (queue slots, send locks,
    #                         tracer, pool) must rank above
    "llm.pool": 36,         # KVCachePool slot table (llm/pool.py); the
    #                         engine acquires it with llm.engine held
    # thread boundaries ----------------------------------------------------
    "queue.space": 40,      # Queue slot condition (bounded-buffer wait)
    "collectpads": 42,      # mux/merge N-pad sync engine
    "repo": 44,             # tensor_repo slot/caps table
    "shm.ring": 46,         # shm ring local wakeup condition
    # fleet tier -------------------------------------------------------------
    "fleet.autoscaler": 47,  # autoscaler cooldown/decision state; calls
    #                          into the pool, so below fleet.pool
    "fleet.pool": 48,       # worker-pool table; membership callbacks
    #                         call into the router, so below fleet.router
    "fleet.router": 49,     # router membership + routed-client table;
    #                         rebalance calls FailoverConnection
    #                         endpoint updates, so below query.client
    # query / transport layer ----------------------------------------------
    "query.registry": 50,   # server/broker connection registries
    "query.client": 52,     # FailoverConnection endpoint state
    "query.overload": 54,   # admission controller / shed policy /
    #                         token bucket state (query/overload.py;
    #                         may evaluate metric gauges, so it ranks
    #                         below obs.metrics)
    "query.send": 60,       # per-connection/stream send locks
    # observability / memory -----------------------------------------------
    "slo": 66,              # SLO evaluator window store + flight-recorder
    #                         ring (slo/): held while snapshotting the
    #                         registry and exporting the span ring, so it
    #                         ranks below tracer/obs.ring/obs.metrics
    "obs.timeseries": 67,   # time-series ring sample store + signal
    #                         state (obs/timeseries.py); may evaluate
    #                         registry snapshots, so below obs.metrics
    "obs.federation": 68,   # federation collector origin table
    #                         (obs/federation.py); merges local registry
    #                         snapshots, so below obs.metrics
    "tracer": 70,           # Tracer stats table
    "obs.ring": 72,         # SpanRing append/snapshot (obs/span.py)
    "analysis.ledger": 73,  # compile-ledger event/budget tables
    #                         (analysis/compileledger.py); exports the
    #                         nns_jit_compiles_total counter, which is
    #                         incremented OUTSIDE this lock, so it ranks
    #                         below obs.metrics
    "obs.metrics": 74,      # metrics registry + per-metric state
    #                         (obs/metrics.py; scrape snapshots under the
    #                         registry lock, then evaluates gauges
    #                         outside it)
    "pool": 80,             # TensorBufferPool free lists
    "lease": 85,            # BufferLease refcount
    "pipeline.state": 90,   # Pipeline error/EOS condition (post_error
    #                         is reachable from under most of the above)
    "leaf": 95,             # one-shot module registries (default pool,
    #                         server/broker tables, native loader, conf)
}

#: classes whose distinct INSTANCES may nest (always along the dataflow
#: DAG, upstream instance acquired first — a reverse edge would need a
#: dataflow cycle, which the static verifier rejects as an error).
SAME_NAME_OK = frozenset({
    "element", "filter.coalesce", "filter.workers", "queue.space",
    "collectpads", "repo", "shm.ring", "query.send", "lease",
    "pipeline.state",
})


def rank_of(name: str) -> Optional[int]:
    """Rank of a lock class, or None when unregistered (unregistered
    locks are exempt from ordering checks but reported by the lint)."""
    return HIERARCHY.get(name)


def check_order(held_name: str, acquiring_name: str) -> Optional[str]:
    """Return a violation description when acquiring ``acquiring_name``
    while holding ``held_name`` breaks the hierarchy, else None."""
    held = rank_of(held_name)
    acq = rank_of(acquiring_name)
    if held is None or acq is None:
        return None
    if held_name == acquiring_name:
        if held_name in SAME_NAME_OK:
            return None
        return (f"same-class nesting of {held_name!r} (rank {held}) is "
                "not declared instance-safe (SAME_NAME_OK)")
    if acq < held:
        return (f"acquired {acquiring_name!r} (rank {acq}) while holding "
                f"{held_name!r} (rank {held}); hierarchy requires "
                f"{acquiring_name!r} first")
    return None
