"""Static JIT-boundary auditor: the bounded-executable discipline as a
machine-checked property.

Every headline win since the padded-bucket tier rests on one unwritten
contract: shapes reaching a jit boundary are QUANTIZED (``pad_rows`` /
pow2), mutated pools are DONATED (``donate_argnums``), and lowered code
never host-syncs.  Violated once, a hot path silently recompiles per
fill level or copies a whole KV arena per step — the donation lesson
cost >50 % of solo-session throughput before it was found by hand.
This module makes the contract a named static property, the way
``lockorder.py`` did for lock ranks: an AST dataflow pass over the jit
call graph (``jax.jit`` call sites and decorators, the model-function
roots, ``lower_step``/``lower_decode`` traced closures) reporting five
named findings:

``unquantized-shape-at-jit``
    A shape-derived value (``len(x)``, ``x.shape[i]``, arithmetic on
    them) reaches an executable-cache key — a shape-keyed executable
    getter (``_step_fn``/``_pstep_fn``/``_chunk_fn``/``_prefill_fn``)
    — without flowing through a registered quantizer (``pad_rows``,
    ``quantize_prompt``, ``quantize_pages``, ``_next_pow2``).  Raw lengths
    at a jit signature mean one executable PER FILL LEVEL: a compile
    storm.

``missing-donation``
    A function handed to ``jax.jit`` updates an array parameter in
    place (``p = p.at[...]...`` / ``dynamic_update_slice(p, ...)``),
    directly or one call level down, and the jit call does not donate
    that parameter.  Without donation XLA materializes an input+output
    copy of the WHOLE buffer per step.

``host-sync-in-jit``
    ``np.asarray``/``np.array``, ``float()``/``int()``/``bool()``,
    ``.block_until_ready()``, ``.item()``/``.tolist()`` or
    ``jax.device_get`` applied to a traced value anywhere in the jit
    call graph — the whole-graph extension of nnslint's
    ``host-sync-in-lower`` (which only covers the lowering hooks).
    Tracedness is propagated interprocedurally: a helper called with
    only static arguments (a shape, a config) stays host code even
    when a jitted function calls it at trace time.

``tracer-branch``
    A Python ``if``/``while`` on a traced value inside the jit graph.
    Under tracing this concretizes (error) at best; at worst it forks
    the executable set.  Branching on shapes/``len()`` is static and
    fine; ``is None`` structure checks are fine.

``unbounded-signature``
    An executable-cache key builder (``_sig``/``_cfg_key``-style
    functions) iterates a parameter collection with no declared bound
    — a dict/list signature component whose cardinality nothing caps
    is an unbounded executable set by construction.  Declare the bound
    (slice, cap) or pragma WITH the reason the arity is fixed
    elsewhere.

Pragma: append ``# nnsjit: allow(<rule>)`` to the offending line or the
comment line directly above it (give the reason in the comment) — the
``nnslint`` convention.

The pass is intentionally import-free (pure ``ast``): it audits files
that import jax without needing jax in the environment, the same
standalone discipline as ``tools/nnslint.py``.  The RUNTIME half of the
contract — every compile that actually happens, attributed to a site
and diffed against its nearest cached neighbor — lives in
:mod:`nnstreamer_tpu.analysis.compileledger`.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Set, Tuple

RULES = ("unquantized-shape-at-jit", "missing-donation",
         "host-sync-in-jit", "tracer-branch", "unbounded-signature")

#: registered shape quantizers: a value that flowed through one of
#: these is bounded by construction (their laws — idempotent, monotone,
#: >= input, capped — are pinned by tests/test_quantizers.py, which is
#: what licenses this whitelist)
QUANTIZERS = frozenset({"pad_rows", "quantize_prompt", "quantize_pages",
                        "_next_pow2"})

#: shape-keyed executable getters: their int arguments ARE the
#: executable-cache key (llm/engine.py warm-set dicts), so a raw
#: length here is a compile per fill level
SHAPE_KEYED_GETTERS = frozenset({"_step_fn", "_pstep_fn", "_chunk_fn",
                                 "_prefill_fn"})

#: executable-cache key builders: unbounded-signature applies to their
#: bodies
SIG_BUILDERS = frozenset({"_sig", "_cfg_key"})

#: jit-graph roots that are wired through runtime indirection the AST
#: cannot see (registry forwards jitted by the filter backend, the
#: decode/prefill twins jitted through closures): audited as if
#: directly jitted
KNOWN_JIT_ROOTS = frozenset({
    "forward_logits", "prefill_kv", "decode_step", "decode_step_pooled",
    "decode_step_paged", "prefill_chunk_paged",
})

#: lowering hooks whose nested defs are traced closures (PR 12
#: contract: LoweredStep.fn joins the segment's jitted computation)
LOWER_HOOKS = frozenset({"lower_step", "lower_decode"})

#: attribute calls that force a device->host sync on a traced value
HOST_SYNC_METHODS = frozenset({"block_until_ready", "item", "tolist"})

#: builtins that force concretization when applied to a tracer
HOST_CAST_BUILTINS = frozenset({"float", "int", "bool"})

#: annotation substrings marking a parameter as STATIC (python-level)
#: rather than traced: branches and casts on these are fine
_STATIC_ANN_TOKENS = ("int", "float", "bool", "str", "Config", "None",
                      "Callable")
_TRACED_ANN_TOKENS = ("ndarray", "Array", "Dict", "dict", "List",
                      "list", "Any", "Tuple", "tuple")

#: attribute roots that are module namespaces, not instances — a call
#: through them never resolves to a repo-local def by bare name
_MODULE_ROOTS = ("jnp", "np", "_np", "numpy", "jax", "lax", "nn", "os",
                 "time", "math", "json", "re", "sys", "ast")

#: higher-order callees whose Name arguments are function references
#: entering the traced graph (jax transforms); a bare Name argument to
#: anything else is just a value
_HOF_CALLEES = frozenset({"jit", "scan", "cond", "while_loop",
                          "fori_loop", "switch", "vmap", "pmap",
                          "remat", "checkpoint", "custom_vjp",
                          "custom_jvp", "grad", "value_and_grad"})


@dataclasses.dataclass
class Finding:
    path: str
    line: int
    rule: str
    func: str
    message: str

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule} "
                f"[{self.func}]: {self.message}")


def _pragma_lines(source: str) -> Dict[int, Set[str]]:
    """line number -> rules allowed there; a pragma on a pure comment
    line also covers the next non-comment line (nnslint convention)."""
    allowed: Dict[int, Set[str]] = {}
    pending: Set[str] = set()
    for i, text in enumerate(source.splitlines(), start=1):
        rules: Set[str] = set()
        marker = "# nnsjit: allow("
        pos = text.find(marker)
        if pos >= 0:
            inner = text[pos + len(marker):]
            rules = {r.strip() for r in
                     inner.partition(")")[0].split(",") if r.strip()}
        stripped = text.strip()
        if stripped.startswith("#"):
            pending |= rules
            continue
        here = rules | pending
        if stripped:
            pending = set()
        if here:
            allowed[i] = allowed.get(i, set()) | here
    return allowed


def _call_name(call: ast.Call) -> str:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def _is_jit_callee(func: ast.AST) -> bool:
    """``jax.jit`` / ``self._jax.jit`` / bare ``jit`` as a call target
    or decorator."""
    if isinstance(func, ast.Attribute) and func.attr == "jit":
        return True
    if isinstance(func, ast.Name) and func.id == "jit":
        return True
    return False


def _is_shape_access(node: ast.AST) -> bool:
    """Expressions that are STATIC under tracing even when rooted at a
    traced value: ``x.shape``/``x.shape[i]``, ``x.ndim``, ``x.dtype``,
    ``len(x)`` — abstract-value metadata, not data."""
    if isinstance(node, ast.Subscript):
        return _is_shape_access(node.value)
    if isinstance(node, ast.Attribute):
        return node.attr in ("shape", "ndim", "dtype", "size",
                             "weak_type")
    if isinstance(node, ast.Call):
        fn = node.func
        return isinstance(fn, ast.Name) and fn.id == "len"
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_shape_access(e) or isinstance(e, ast.Constant)
                   for e in node.elts)
    return False


def _params(node: ast.AST) -> List[ast.arg]:
    a = node.args
    return list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)


def _param_is_traced(arg: ast.arg) -> bool:
    """A parameter counts as traced unless its annotation names a
    static python scalar/config type.  Unannotated parameters (jit
    closures) are traced — that is what being jitted means."""
    ann = arg.annotation
    if ann is None:
        return True
    try:
        text = ast.unparse(ann)
    except Exception:
        text = ""
    if any(tok in text for tok in _TRACED_ANN_TOKENS):
        return True
    return not any(tok in text for tok in _STATIC_ANN_TOKENS)


def _expr_traced(expr: ast.AST, tainted: Set[str]) -> bool:
    """True when a traced value's DATA (not its static metadata) feeds
    the expression: prune shape accesses at every level."""
    if _is_shape_access(expr):
        return False
    if isinstance(expr, ast.Name):
        return expr.id in tainted
    return any(_expr_traced(child, tainted)
               for child in ast.iter_child_nodes(expr))


def _own_nodes(node: ast.AST) -> List[ast.AST]:
    """All descendants of ``node`` EXCLUDING nested function bodies
    (nested defs are audited as their own functions)."""
    out: List[ast.AST] = []

    def walk(cur: ast.AST) -> None:
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                continue
            out.append(child)
            walk(child)

    walk(node)
    return out


def _compute_taint(node: ast.AST, initial: Set[str]) -> Set[str]:
    """Forward taint over the function's OWN statements (two passes:
    the hot-path code shape is straight-line math, but a second pass
    picks up simple use-before-redef orderings)."""
    tainted = set(initial)
    own = _own_nodes(node)
    for _ in range(2):
        for sub in own:
            if isinstance(sub, ast.Assign):
                rhs = _expr_traced(sub.value, tainted)
                for t in sub.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            if rhs:
                                tainted.add(n.id)
                            else:
                                tainted.discard(n.id)
            elif isinstance(sub, ast.AugAssign):
                if isinstance(sub.target, ast.Name) \
                        and _expr_traced(sub.value, tainted):
                    tainted.add(sub.target.id)
            elif isinstance(sub, ast.For):
                if _expr_traced(sub.iter, tainted):
                    for n in ast.walk(sub.target):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
    # params are axioms: a reassignment cannot untaint the NAME when
    # the update derives from itself (p = p.at[...].set(v))
    tainted |= initial & _compute_selfupdates(node)
    return tainted


def _compute_selfupdates(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for sub in _own_nodes(node):
        if isinstance(sub, ast.Assign):
            rhs_names = {n.id for n in ast.walk(sub.value)
                         if isinstance(n, ast.Name)}
            for t in sub.targets:
                if isinstance(t, ast.Name) and t.id in rhs_names:
                    out.add(t.id)
    return out


@dataclasses.dataclass
class _FuncInfo:
    name: str
    qual: str
    node: ast.AST                       # FunctionDef | AsyncFunctionDef
    file: "_FileInfo"
    parent_names: Tuple[str, ...]       # enclosing def/class names


@dataclasses.dataclass
class _FileInfo:
    path: str
    rel: str
    tree: ast.Module
    source: str
    allowed: Dict[int, Set[str]]


class _JitGraph:
    """Cross-file function table + interprocedural traced-parameter
    masks, propagated from the jit roots: a callee's parameter is
    traced iff SOME in-graph call site feeds it a traced argument (or
    the callee is itself a root, where annotations decide)."""

    def __init__(self, files: List[_FileInfo]) -> None:
        self.files = files
        self.funcs: List[_FuncInfo] = []
        self.by_name: Dict[str, List[_FuncInfo]] = {}
        self.by_id: Dict[int, _FuncInfo] = {}
        self.jit_sites: List[Tuple[_FileInfo, ast.Call]] = []
        for fi in files:
            self._collect_file(fi)
        #: id(node) -> set of traced parameter names (membership in
        #: this dict IS "in the jit graph")
        self.masks: Dict[int, Set[str]] = {}
        self._propagate()

    # -- collection ----------------------------------------------------
    def _collect_file(self, fi: _FileInfo) -> None:
        stack: List[str] = []

        def walk(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    info = _FuncInfo(child.name,
                                     ".".join(stack + [child.name]),
                                     child, fi, tuple(stack))
                    self.funcs.append(info)
                    self.by_name.setdefault(child.name, []).append(info)
                    self.by_id[id(child)] = info
                    stack.append(child.name)
                    walk(child)
                    stack.pop()
                elif isinstance(child, ast.ClassDef):
                    stack.append(child.name)
                    walk(child)
                    stack.pop()
                else:
                    walk(child)

        walk(fi.tree)
        for node in ast.walk(fi.tree):
            if isinstance(node, ast.Call) and _is_jit_callee(node.func):
                self.jit_sites.append((fi, node))

    def resolve(self, name: str, fi: _FileInfo) -> Optional[_FuncInfo]:
        """Callee resolution: same file first, else a UNIQUE global
        match (ambiguous bare names are skipped, not guessed)."""
        cands = self.by_name.get(name, [])
        local = [c for c in cands if c.file is fi]
        if len(local) == 1:
            return local[0]
        if len(cands) == 1:
            return cands[0]
        return None

    # -- roots + mask propagation --------------------------------------
    def _root_mask(self, info: _FuncInfo) -> Set[str]:
        return {a.arg for a in _params(info.node)
                if _param_is_traced(a)}

    def _roots(self) -> List[_FuncInfo]:
        out: List[_FuncInfo] = []
        seen: Set[int] = set()

        def add(info: Optional[_FuncInfo]) -> None:
            if info is not None and id(info.node) not in seen:
                seen.add(id(info.node))
                out.append(info)

        for fi, call in self.jit_sites:
            if call.args and isinstance(call.args[0], ast.Name):
                add(self.resolve(call.args[0].id, fi))
        for info in self.funcs:
            for deco in getattr(info.node, "decorator_list", ()):
                target = deco.func if isinstance(deco, ast.Call) else deco
                if _is_jit_callee(target):
                    add(info)
            if info.name in KNOWN_JIT_ROOTS:
                add(info)
            elif info.parent_names and \
                    info.parent_names[-1] in LOWER_HOOKS:
                add(info)
        return out

    def _propagate(self) -> None:
        work: List[_FuncInfo] = []
        for info in self._roots():
            self.masks[id(info.node)] = self._root_mask(info)
            work.append(info)
        while work:
            info = work.pop()
            mask = self.masks[id(info.node)]
            taint = _compute_taint(info.node, mask)
            for sub in _own_nodes(info.node):
                if not isinstance(sub, ast.Call):
                    continue
                self._flow_call(info, sub, taint, work)
                # functions passed BY NAME into a jax transform
                # (lax.scan/cond/vmap, jax.jit) are traced with all
                # their (unannotated) params
                if _call_name(sub) in _HOF_CALLEES:
                    for a in list(sub.args) + [kw.value
                                               for kw in sub.keywords]:
                        if isinstance(a, ast.Name):
                            target = self.resolve(a.id, info.file)
                            if target is not None:
                                self._grow(target,
                                           self._root_mask(target),
                                           work)

    def _flow_call(self, info: _FuncInfo, call: ast.Call,
                   taint: Set[str], work: List[_FuncInfo]) -> None:
        callee_name: Optional[str] = None
        fn = call.func
        same_file_only = False
        if isinstance(fn, ast.Name):
            callee_name = fn.id
        elif isinstance(fn, ast.Attribute):
            root = fn.value
            if not (isinstance(root, ast.Name)
                    and root.id in _MODULE_ROOTS):
                # a bare method name is only trustworthy within its
                # own file — `x.find(...)` must not resolve to an
                # unrelated global `find` elsewhere in the package
                callee_name = fn.attr
                same_file_only = True
        if callee_name is None:
            return
        target = self.resolve(callee_name, info.file)
        if same_file_only and target is not None \
                and target.file is not info.file:
            return
        if target is None or target.node is info.node:
            return
        names = [a.arg for a in _params(target.node)]
        # methods called through an instance: drop the self slot
        offset = 1 if names[:1] == ["self"] and not (
            isinstance(fn, ast.Name)) else 0
        grow: Set[str] = set()
        for pos, a in enumerate(call.args):
            idx = pos + offset
            if idx < len(names) and _expr_traced(a, taint):
                grow.add(names[idx])
        for kw in call.keywords:
            if kw.arg in names and _expr_traced(kw.value, taint):
                grow.add(kw.arg)
        if grow:
            self._grow(target, grow, work)

    def _grow(self, info: _FuncInfo, add: Set[str],
              work: List[_FuncInfo]) -> None:
        cur = self.masks.get(id(info.node))
        if cur is None:
            self.masks[id(info.node)] = set(add)
            work.append(info)
        elif not add <= cur:
            cur |= add
            work.append(info)


class _Auditor:
    def __init__(self, graph: _JitGraph) -> None:
        self.graph = graph
        self.findings: List[Finding] = []

    def _add(self, fi: _FileInfo, node: ast.AST, rule: str, func: str,
             message: str) -> None:
        line = getattr(node, "lineno", 0)
        if rule in fi.allowed.get(line, ()):
            return
        self.findings.append(Finding(fi.rel, line, rule, func, message))

    def run(self) -> List[Finding]:
        for info in self.graph.funcs:
            mask = self.graph.masks.get(id(info.node))
            if mask is not None:
                self._audit_traced(info, mask)
            if info.name in SIG_BUILDERS:
                self._audit_signature(info)
            self._audit_host_quantization(info)
        self._audit_donation()
        # one finding per site+rule (nested walks overlap)
        seen, unique = set(), []
        for f in sorted(self.findings,
                        key=lambda f: (f.path, f.line, f.rule)):
            key = (f.path, f.line, f.rule)
            if key not in seen:
                seen.add(key)
                unique.append(f)
        return unique

    # -- traced-body rules: host-sync-in-jit + tracer-branch -----------
    def _audit_traced(self, info: _FuncInfo, mask: Set[str]) -> None:
        tainted = _compute_taint(info.node, mask)
        for sub in _own_nodes(info.node):
            if isinstance(sub, ast.Call):
                self._check_host_sync(info, sub, tainted)
            elif isinstance(sub, (ast.If, ast.While)):
                self._check_branch(info, sub, tainted)

    def _check_host_sync(self, info: _FuncInfo, call: ast.Call,
                         tainted: Set[str]) -> None:
        fn = call.func
        name = _call_name(call)
        arg_traced = any(_expr_traced(a, tainted) for a in call.args)
        if isinstance(fn, ast.Attribute):
            if name in HOST_SYNC_METHODS \
                    and _expr_traced(fn.value, tainted):
                self._add(info.file, call, "host-sync-in-jit",
                          info.qual,
                          f".{name}() on a traced value forces a "
                          "device->host sync inside the jit graph — "
                          "return the value and materialize outside")
                return
            if name in ("asarray", "array") \
                    and isinstance(fn.value, ast.Name) \
                    and fn.value.id in ("np", "numpy", "_np") \
                    and arg_traced:
                self._add(info.file, call, "host-sync-in-jit",
                          info.qual,
                          f"np.{name}() on a traced value "
                          "materializes on host mid-trace — use jnp, "
                          "or hoist the conversion out of the jit "
                          "graph")
                return
            if name == "device_get" and arg_traced:
                self._add(info.file, call, "host-sync-in-jit",
                          info.qual,
                          "jax.device_get inside the jit graph is a "
                          "blocking transfer — hoist it to the caller")
                return
        if isinstance(fn, ast.Name) and name in HOST_CAST_BUILTINS \
                and arg_traced:
            self._add(info.file, call, "host-sync-in-jit", info.qual,
                      f"{name}() on a traced value concretizes the "
                      "tracer (device sync + retrace hazard) — keep "
                      "it an array or make the input static")

    def _check_branch(self, info: _FuncInfo, node: ast.AST,
                      tainted: Set[str]) -> None:
        test = node.test
        # `x is None` / `x is not None` is pytree STRUCTURE, not data
        if isinstance(test, ast.Compare) \
                and all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in test.ops):
            return
        if _expr_traced(test, tainted):
            kw = "while" if isinstance(node, ast.While) else "if"
            self._add(info.file, node, "tracer-branch", info.qual,
                      f"python `{kw}` on a traced value: under "
                      "tracing this concretizes (error) or forks the "
                      "executable set — use jnp.where / lax.cond, or "
                      "branch on shapes (static)")

    # -- unbounded-signature -------------------------------------------
    def _audit_signature(self, info: _FuncInfo) -> None:
        params = {a.arg for a in _params(info.node)}
        for sub in ast.walk(info.node):
            iters: List[ast.AST] = []
            if isinstance(sub, (ast.GeneratorExp, ast.ListComp,
                                ast.SetComp, ast.DictComp)):
                iters = [c.iter for c in sub.generators]
            elif isinstance(sub, ast.For):
                iters = [sub.iter]
            for it in iters:
                root = it
                # unwrap sorted(x) / vars(x).items() / enumerate(x)
                while True:
                    if isinstance(root, ast.Call):
                        if isinstance(root.func, ast.Attribute):
                            root = root.func.value
                            continue
                        if root.args:
                            root = root.args[0]
                            continue
                    break
                if isinstance(root, ast.Subscript):
                    continue   # x[:n] — an explicit bound
                if isinstance(root, ast.Name) and root.id in params:
                    self._add(
                        info.file, sub, "unbounded-signature",
                        info.qual,
                        f"signature builder iterates parameter "
                        f"{root.id!r} with no declared bound: a "
                        "dict/list key component nothing caps is an "
                        "unbounded executable set — slice/cap it, or "
                        "pragma WITH the reason the arity is fixed")

    # -- unquantized-shape-at-jit --------------------------------------
    def _audit_host_quantization(self, info: _FuncInfo) -> None:
        """Host-side pass over EVERY function: shape-derived ints must
        be quantized before keying an executable getter."""
        shape_vars: Set[str] = set()
        clean_vars: Set[str] = set()

        def tainted_expr(expr: ast.AST) -> bool:
            if isinstance(expr, ast.Call):
                if _call_name(expr) in QUANTIZERS:
                    return False
                if _call_name(expr) == "len":
                    return True
                return any(tainted_expr(a) for a in expr.args)
            if _is_shape_access(expr):
                return True
            if isinstance(expr, ast.Name):
                return expr.id in shape_vars and \
                    expr.id not in clean_vars
            if isinstance(expr, (ast.BinOp, ast.IfExp, ast.Tuple,
                                 ast.List, ast.Compare, ast.BoolOp,
                                 ast.UnaryOp)):
                return any(tainted_expr(c)
                           for c in ast.iter_child_nodes(expr))
            return False

        own = _own_nodes(info.node)
        for sub in own:
            if isinstance(sub, ast.Assign):
                is_taint = tainted_expr(sub.value)
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        if is_taint:
                            shape_vars.add(t.id)
                            clean_vars.discard(t.id)
                        else:
                            clean_vars.add(t.id)
                            shape_vars.discard(t.id)
        for sub in own:
            if not isinstance(sub, ast.Call):
                continue
            if _call_name(sub) in SHAPE_KEYED_GETTERS:
                for a in sub.args:
                    if tainted_expr(a):
                        self._add(
                            info.file, sub, "unquantized-shape-at-jit",
                            info.qual,
                            f"shape-derived value reaches "
                            f"{_call_name(sub)}() — an executable-"
                            "cache key — without a registered "
                            "quantizer (pad_rows / quantize_prompt / "
                            "quantize_pages / _next_pow2): one "
                            "executable "
                            "per fill level")

    # -- missing-donation ----------------------------------------------
    def _audit_donation(self) -> None:
        for fi, call in self.graph.jit_sites:
            if not call.args or not isinstance(call.args[0], ast.Name):
                continue
            target = self.graph.resolve(call.args[0].id, fi)
            if target is None:
                continue
            mutated = self._mutated_param_indices(target, depth=1)
            if not mutated:
                continue
            donated = self._donated(call)
            if donated is None:
                self._add(
                    fi, call, "missing-donation",
                    call.args[0].id,
                    f"jitted function mutates array parameter(s) "
                    f"{sorted(mutated)} in place but the jit call "
                    "declares no donate_argnums: XLA will copy the "
                    "whole buffer per step (the >50% pool-copy tax)")
            else:
                missing = mutated - donated
                if missing:
                    self._add(
                        fi, call, "missing-donation",
                        call.args[0].id,
                        f"donate_argnums={sorted(donated)} does not "
                        f"cover mutated parameter(s) {sorted(missing)}")

    @staticmethod
    def _donated(call: ast.Call) -> Optional[Set[int]]:
        for kw in call.keywords:
            if kw.arg != "donate_argnums":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return {v.value}
            if isinstance(v, (ast.Tuple, ast.List)):
                out = set()
                for el in v.elts:
                    if isinstance(el, ast.Constant) \
                            and isinstance(el.value, int):
                        out.add(el.value)
                return out
            return set()   # computed donation: trust but cannot check
        return None

    def _mutated_param_indices(self, info: _FuncInfo,
                               depth: int) -> Set[int]:
        """Positional param indices updated in place: ``p = p.at[..]``
        chains and ``p = ...dynamic_update_slice(p, ...)`` — plus one
        level of positional flow into callees that do the same."""
        node = info.node
        index = {a.arg: i for i, a in enumerate(_params(node))}
        mutated: Set[int] = set()
        for sub in _own_nodes(node):
            if not isinstance(sub, ast.Assign):
                continue
            for t in sub.targets:
                if isinstance(t, ast.Name) and t.id in index \
                        and self._inplace_update_of(sub.value, t.id):
                    mutated.add(index[t.id])
        if depth > 0:
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                callee = self.graph.resolve(_call_name(sub), info.file)
                if callee is None or callee.node is node:
                    continue
                inner = self._mutated_param_indices(callee,
                                                    depth=depth - 1)
                if not inner:
                    continue
                for pos, a in enumerate(sub.args):
                    if isinstance(a, ast.Name) and a.id in index \
                            and pos in inner:
                        mutated.add(index[a.id])
        return mutated

    @staticmethod
    def _inplace_update_of(expr: ast.AST, name: str) -> bool:
        for n in ast.walk(expr):
            # name.at[...].set/add/...(...)
            if isinstance(n, ast.Attribute) and n.attr == "at" \
                    and isinstance(n.value, ast.Name) \
                    and n.value.id == name:
                return True
            # dynamic_update_slice(name, ...) / scatter*(name, ...)
            if isinstance(n, ast.Call):
                cn = _call_name(n)
                if (cn.startswith("dynamic_update_slice")
                        or cn.startswith("scatter")) and n.args \
                        and isinstance(n.args[0], ast.Name) \
                        and n.args[0].id == name:
                    return True
        return False


def _iter_py(paths: List[str]) -> List[str]:
    out: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        else:
            out.append(path)
    return out


def audit_paths(paths: List[str],
                root: Optional[str] = None) -> List[Finding]:
    """The entry point ``tools/nnsjit.py`` and ``launch.py --check
    --jit`` share: parse every file, build ONE cross-file jit graph
    (the decode twins are defined in models/ and jitted from llm/), and
    run the five rules."""
    root = root or os.getcwd()
    files: List[_FileInfo] = []
    findings: List[Finding] = []
    for path in _iter_py(paths):
        rel = os.path.relpath(path, root)
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=path)
        except SyntaxError as exc:
            findings.append(Finding(rel, exc.lineno or 0, "syntax", "-",
                                    f"cannot parse: {exc.msg}"))
            continue
        except OSError as exc:
            findings.append(Finding(rel, 0, "io", "-", str(exc)))
            continue
        files.append(_FileInfo(path, rel, tree, source,
                               _pragma_lines(source)))
    graph = _JitGraph(files)
    findings.extend(_Auditor(graph).run())
    return findings
