"""Runtime concurrency sanitizer: lock-order + buffer-aliasing checking.

Off by default and ZERO-COST when off: the lock factories below return
plain ``threading`` primitives and the hot-path hooks are a single
module-global test.  On (``NNS_DEBUG=1`` in the environment, or
:func:`enable` from tests) every lock created through
:func:`make_lock` / :func:`make_rlock` / :func:`make_condition` is
wrapped so the sanitizer sees each acquisition:

- **Acquisition graph**: per-thread held-lock stacks feed a global
  directed graph over lock CLASSES (names from
  :mod:`~nnstreamer_tpu.analysis.lockorder`).  The first time an edge
  closes a cycle, the sanitizer reports the potential deadlock with the
  acquisition stacks of BOTH directions — the two code paths that can
  interleave into a hang.
- **Hierarchy check**: every nested acquisition is checked against the
  declared hierarchy (:func:`lockorder.check_order`); inversions are
  reported with the acquiring stack even before a full cycle exists.
- **Aliasing checker**: :class:`~nnstreamer_tpu.tensor.buffer.
  BufferLease` registers the read-only numpy views decoded over its
  slab (via :func:`note_views`, called when a ``TensorBuffer`` carrying
  a lease is built).  A writable grant of the slab
  (``BufferLease.memory()``) or a pool re-issue while any registered
  view is still alive is the zero-copy contract violation that
  silently corrupts frames — reported with the view's creation stack.

``strict`` mode (the default under :func:`enable`; tests use it) raises
:class:`LockOrderError` / :class:`AliasingError` at the violation site;
non-strict (the ``NNS_DEBUG=1`` default) records findings for
:func:`report` so a live pipeline keeps streaming while evidence
accumulates.

Locks are instrumented at CREATION: enabling the sanitizer affects
objects constructed afterwards (pipelines built inside a test, a
process started with ``NNS_DEBUG=1``), never retroactively.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import traceback
import weakref
from typing import Any, Dict, List, Tuple

from . import lockorder

__all__ = [
    "enable", "disable", "enabled", "report", "reset", "findings",
    "make_lock", "make_rlock", "make_condition",
    "note_views", "check_writable_grant", "check_slab_reissue",
    "guard_readonly", "LockOrderError", "AliasingError", "Finding",
]


class LockOrderError(RuntimeError):
    """A lock acquisition broke the declared hierarchy or closed a
    potential-deadlock cycle (strict mode only)."""


class AliasingError(RuntimeError):
    """A pooled slab was written (or re-issued for writing) while
    read-only zero-copy views over it were still alive — or a consumer
    attempted to write through such a view (see :func:`guard_readonly`)."""


@dataclasses.dataclass
class Finding:
    kind: str            # "lock-cycle" | "lock-hierarchy" | "aliasing"
    message: str
    #: formatted stacks giving both sides of the conflict where known
    stacks: List[str] = dataclasses.field(default_factory=list)

    def __str__(self) -> str:
        body = f"[{self.kind}] {self.message}"
        for s in self.stacks:
            body += "\n" + s
        return body


# --------------------------------------------------------------------------
# global state
# --------------------------------------------------------------------------

#: fast hot-path gate, read by buffer.py / protocol.py per frame
_ENABLED = False
_STRICT = False
_STATE_LOCK = threading.Lock()   # guards the structures below
_FINDINGS: List[Finding] = []
#: lock-class edge graph: name -> {successor names}
_EDGES: Dict[str, set] = {}
#: (a, b) -> formatted stack of the first observed a-held-acquiring-b
_EDGE_STACKS: Dict[Tuple[str, str], str] = {}
#: id(slab) -> list of (weakref-to-view, creation stack summary)
_SLAB_VIEWS: Dict[int, List[Tuple[Any, str]]] = {}

_TLS = threading.local()


def _held() -> list:
    stack = getattr(_TLS, "held", None)
    if stack is None:
        stack = _TLS.held = []
    return stack


def _fmt_stack(skip: int = 3, limit: int = 14) -> str:
    frames = traceback.format_stack()[:-skip]
    return "".join(frames[-limit:]).rstrip()


def enabled() -> bool:
    return _ENABLED


def enable(strict: bool = True) -> None:
    """Turn the sanitizer on (affects locks/buffers created from now
    on).  ``strict`` raises at the violation site; else findings are
    only recorded for :func:`report`."""
    global _ENABLED, _STRICT
    _ENABLED = True
    _STRICT = bool(strict)


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def reset() -> None:
    """Drop all recorded findings, edges and view registrations."""
    with _STATE_LOCK:
        _FINDINGS.clear()
        _EDGES.clear()
        _EDGE_STACKS.clear()
        _SLAB_VIEWS.clear()


def findings() -> List[Finding]:
    with _STATE_LOCK:
        return list(_FINDINGS)


def report() -> str:
    """Human-readable report of everything recorded so far."""
    out = findings()
    if not out:
        return "sanitizer: no findings"
    return "\n\n".join(str(f) for f in out)


def _record(finding: Finding, error_cls) -> None:
    with _STATE_LOCK:
        _FINDINGS.append(finding)
    if _STRICT:
        raise error_cls(str(finding))


# NNS_DEBUG=1 arms the sanitizer for the whole process (non-strict: a
# live pipeline should keep streaming while evidence accumulates)
if os.environ.get("NNS_DEBUG", "") == "1":
    enable(strict=False)


# --------------------------------------------------------------------------
# lock instrumentation
# --------------------------------------------------------------------------

def _reaches(src: str, dst: str) -> bool:
    """DFS: does the edge graph already have a path src -> dst?"""
    seen = set()
    stack = [src]
    while stack:
        node = stack.pop()
        if node == dst:
            return True
        if node in seen:
            continue
        seen.add(node)
        stack.extend(_EDGES.get(node, ()))
    return False


def _note_acquired(lock: "_Tracked") -> None:
    held = _held()
    acq_stack = None
    for h in held:
        if h is lock:
            continue
        violation = lockorder.check_order(h.name, lock.name)
        if violation is not None:
            if acq_stack is None:
                acq_stack = _fmt_stack()
            _record(Finding(
                "lock-hierarchy",
                f"{violation} (thread {threading.current_thread().name})",
                [f"--- acquiring {lock.name!r}:\n{acq_stack}"],
            ), LockOrderError)
        if h.name != lock.name:
            with _STATE_LOCK:
                edge = (h.name, lock.name)
                new_edge = lock.name not in _EDGES.get(h.name, ())
                if new_edge:
                    if acq_stack is None:
                        acq_stack = _fmt_stack()
                    _EDGES.setdefault(h.name, set()).add(lock.name)
                    _EDGE_STACKS[edge] = acq_stack
                    cycle = _reaches(lock.name, h.name)
                    back = _EDGE_STACKS.get((lock.name, h.name))
                else:
                    cycle = False
                    back = None
            if cycle:
                stacks = [f"--- {h.name!r} -> {lock.name!r} "
                          f"(thread {threading.current_thread().name}):\n"
                          f"{acq_stack}"]
                if back is not None:
                    stacks.append(
                        f"--- {lock.name!r} -> {h.name!r} (earlier):\n"
                        f"{back}")
                _record(Finding(
                    "lock-cycle",
                    f"potential deadlock: acquisition order cycle "
                    f"{h.name!r} -> {lock.name!r} -> ... -> {h.name!r}",
                    stacks), LockOrderError)
    held.append(lock)


def _note_released(lock: "_Tracked") -> None:
    held = _held()
    # release order may not be LIFO (lock handoffs); remove last match
    for i in range(len(held) - 1, -1, -1):
        if held[i] is lock:
            del held[i]
            return


class _Tracked:
    """Instrumented Lock/RLock: every successful acquire/release is
    mirrored into the per-thread held stack."""

    __slots__ = ("_inner", "name", "_reentrant", "_counts")

    def __init__(self, inner, name: str, reentrant: bool) -> None:
        self._inner = inner
        self.name = name
        self._reentrant = reentrant
        # per-thread recursion depth so an RLock re-acquire is not a
        # second held-stack entry (thread-keyed; tiny, debug-only)
        self._counts: Dict[int, int] = {}

    def acquire(self, blocking: bool = True, timeout: float = -1):
        # Lock.acquire forbids a timeout with blocking=False (the pool's
        # __del__-safe reclaim path uses exactly that): forward the
        # timeout only when one was given
        if timeout == -1:
            got = self._inner.acquire(blocking)
        else:
            got = self._inner.acquire(blocking, timeout)
        if got:
            tid = threading.get_ident()
            depth = self._counts.get(tid, 0)
            if not self._reentrant or depth == 0:
                _note_acquired(self)
            self._counts[tid] = depth + 1
        return got

    def release(self) -> None:
        tid = threading.get_ident()
        depth = self._counts.get(tid, 1) - 1
        if depth <= 0:
            self._counts.pop(tid, None)
            _note_released(self)
        else:
            self._counts[tid] = depth
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # threading.Condition(lock=...) support: delegate the internal
    # save/restore protocol to the wrapped primitive while keeping the
    # held-stack in sync across the wait window
    def _release_save(self):
        tid = threading.get_ident()
        depth = self._counts.pop(tid, 1)
        _note_released(self)
        if hasattr(self._inner, "_release_save"):
            return (self._inner._release_save(), depth)
        self._inner.release()
        return (None, depth)

    def _acquire_restore(self, state) -> None:
        saved, depth = state
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(saved)
        else:
            self._inner.acquire()
        _note_acquired(self)
        self._counts[threading.get_ident()] = depth

    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


def make_lock(name: str):
    """A ``threading.Lock`` belonging to lock class ``name`` (see
    :mod:`~nnstreamer_tpu.analysis.lockorder`); instrumented when the
    sanitizer is enabled, a plain lock otherwise."""
    if not _ENABLED:
        return threading.Lock()
    return _Tracked(threading.Lock(), name, reentrant=False)


def make_rlock(name: str):
    if not _ENABLED:
        return threading.RLock()
    return _Tracked(threading.RLock(), name, reentrant=True)


def make_condition(name: str):
    """A ``threading.Condition`` whose underlying lock participates in
    lock-order tracking."""
    if not _ENABLED:
        return threading.Condition()
    return threading.Condition(lock=_Tracked(threading.Lock(), name,
                                             reentrant=False))


# --------------------------------------------------------------------------
# BufferLease aliasing checker
# --------------------------------------------------------------------------

def note_views(slab, tensors) -> None:
    """Register the live zero-copy views decoded over ``slab`` (called
    from ``TensorBuffer`` construction when a lease rides the buffer).
    Only weakref-able ndarray payloads are tracked."""
    if not _ENABLED or slab is None:
        return
    key = id(slab)
    stack = _fmt_stack()
    with _STATE_LOCK:
        entries = _SLAB_VIEWS.setdefault(key, [])
        for t in tensors:
            try:
                entries.append((weakref.ref(t), stack))
            except TypeError:
                continue   # jax arrays etc.: not slab views


def _live_views(slab) -> List[str]:
    """Creation stacks of still-alive registered views over ``slab``
    (pruning dead entries as a side effect)."""
    with _STATE_LOCK:
        entries = _SLAB_VIEWS.get(id(slab))
        if not entries:
            return []
        alive = [(r, s) for (r, s) in entries if r() is not None]
        if alive:
            _SLAB_VIEWS[id(slab)] = alive
        else:
            del _SLAB_VIEWS[id(slab)]
        return [s for _, s in alive]


def check_writable_grant(slab, origin: str) -> None:
    """A writable view of ``slab`` is about to be handed out
    (``BufferLease.memory()``): writes through it would corrupt every
    live shared view."""
    if not _ENABLED or slab is None:
        return
    stacks = _live_views(slab)
    if stacks:
        _record(Finding(
            "aliasing",
            f"{origin}: writable grant of a pooled slab with "
            f"{len(stacks)} live zero-copy view(s) — writing would "
            "corrupt frames already handed downstream",
            [f"--- view decoded at:\n{stacks[0]}",
             f"--- writable grant at:\n{_fmt_stack()}"],
        ), AliasingError)


def check_slab_reissue(slab) -> None:
    """A recycled slab is about to be re-issued by the pool: by the
    no-alias invariant nothing may still see it."""
    if not _ENABLED or slab is None:
        return
    stacks = _live_views(slab)
    if stacks:
        _record(Finding(
            "aliasing",
            "pool re-issued a slab that still has live zero-copy "
            "view(s) — the refcount reclaim invariant is broken",
            [f"--- view decoded at:\n{stacks[0]}",
             f"--- re-issue at:\n{_fmt_stack()}"],
        ), AliasingError)


# --------------------------------------------------------------------------
# read-only view guard (clear error instead of numpy's)
# --------------------------------------------------------------------------

def guard_readonly(arr):
    """Wrap a read-only zero-copy tensor view so a write attempt raises
    a CLEAR :class:`AliasingError` naming the contract, instead of
    numpy's bare ``assignment destination is read-only``.  No-op (and
    no subclass) when the sanitizer is off — the view stays a plain
    read-only ndarray."""
    if not _ENABLED:
        return arr
    guarded = arr.view(_ReadOnlyTensorView)
    guarded.flags.writeable = False
    return guarded


def _readonly_write_error():
    return AliasingError(
        "write attempt on a read-only zero-copy tensor view: this array "
        "aliases a shared transport payload (pooled slab / tee fan-out "
        "contract, see tensor/buffer.py BufferLease); copy it first "
        "(np.array(x)) if you need to mutate")


try:
    import numpy as _np

    class _ReadOnlyTensorView(_np.ndarray):
        """ndarray subclass for sanitized zero-copy views: mutation of a
        read-only instance raises :class:`AliasingError` with the
        contract spelled out.  Derived WRITABLE arrays (copies, op
        results) behave exactly like ndarray."""

        def __setitem__(self, key, value):
            if not self.flags.writeable:
                raise _readonly_write_error()
            _np.ndarray.__setitem__(self, key, value)

        def fill(self, value):
            if not self.flags.writeable:
                raise _readonly_write_error()
            _np.ndarray.fill(self, value)

        def sort(self, *a, **k):
            if not self.flags.writeable:
                raise _readonly_write_error()
            _np.ndarray.sort(self, *a, **k)

except Exception:  # pragma: no cover - numpy is a hard dep in practice
    _ReadOnlyTensorView = None  # type: ignore[assignment]
