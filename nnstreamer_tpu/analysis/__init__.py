"""Correctness tooling for the fused parallel core.

Three layers, one goal — find pipeline and concurrency bugs BEFORE they
surface as a rare hang or a silently corrupted frame:

- :mod:`~nnstreamer_tpu.analysis.verify` — static pipeline verifier.
  Walks the pad graph of a constructed (not yet playing) pipeline and
  reports caps incompatibilities, dataflow cycles that deadlock, dead
  branches, and scheduler misconfigurations with element-path
  diagnostics.  Runs automatically at ``Pipeline.play()`` (gate:
  ``NNS_VERIFY=0`` disables) and from ``launch.py --check``.
- :mod:`~nnstreamer_tpu.analysis.lockorder` — the package's DECLARED
  lock hierarchy.  One canonical acquisition order for every lock class
  in the codebase; both the static lint (``tools/nnslint.py``) and the
  runtime sanitizer check real acquisitions against it.
- :mod:`~nnstreamer_tpu.analysis.sanitizer` — runtime sanitizer, on
  under ``NNS_DEBUG=1`` (or :func:`sanitizer.enable` in tests).
  Instruments lock acquisition into a per-thread graph and reports
  potential-deadlock cycles and hierarchy inversions with both stacks;
  its :class:`BufferLease` aliasing checker catches writes to pooled
  slabs that still have live shared views.

The NNStreamer papers' core claim (arXiv:1901.04985, arXiv:2101.06371)
is that the stream paradigm lets pipeline correctness be checked before
data flows; this package is that claim applied to our own reproduction,
including the concurrency machinery (worker pools, fused segments,
zero-copy leases) the papers' GStreamer substrate got for free.
"""

from . import lockorder, sanitizer  # noqa: F401  (verify imports pipeline
#                                     modules; keep it lazy to avoid cycles)

__all__ = ["lockorder", "sanitizer"]
