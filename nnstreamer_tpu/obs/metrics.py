"""Metrics registry: counters, gauges, log-bucket latency histograms.

The pull model is the design constraint: the dataflow hot path must not
pay for metrics it is not producing.  So:

- **Gauges are lazy**: register a callable (queue depth, pool
  occupancy, filter inflight) and it is evaluated at *scrape* time —
  zero instructions per buffer.
- **Histograms are push** but only written by code that is already
  observing (a :class:`~nnstreamer_tpu.pipeline.tracing.Tracer` with
  its per-element clock reads); no tracer, no writes.
- **Counters** wrap the same monotonic-int contract as
  ``query/resilience.py`` STATS (which the registry bridges at render
  time rather than duplicating).

Histogram buckets are fixed log-spaced: ``factor = 2**(1/4)`` (~19 %
relative width), so quantiles interpolated at the geometric bucket
midpoint land within ~9 % of the true value — tight enough for p50/p95/
p99 latency reporting with a 128-slot fixed footprint and O(1) observe.

``render_prometheus()`` emits Prometheus text exposition (counters and
gauges as-is, histograms as summaries with quantile labels) — the
``NNS_METRICS_PORT`` endpoint (obs/httpd.py) serves exactly this.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..analysis.sanitizer import make_lock

#: buckets per factor-of-2 (quarter-octave): bucket i covers
#: [2**(i/4), 2**((i+1)/4))
_SUB = 4
_LOG2_SUB = _SUB / math.log(2.0)
_NBUCKETS = 128            # covers [1, 2**32) — 1 µs .. ~71 min in µs


def _bucket_of(value: float) -> int:
    if value <= 1.0:
        return 0
    i = int(math.log(value) * _LOG2_SUB)
    return i if i < _NBUCKETS else _NBUCKETS - 1


def _bucket_mid(i: int) -> float:
    """Geometric midpoint of bucket ``i`` (the quantile interpolant)."""
    return 2.0 ** ((i + 0.5) / _SUB)


def _bucket_lo(i: int) -> float:
    """Lower edge of bucket ``i`` — the reported value for mass in the
    OVERFLOW bucket, whose upper edge is unbounded (a midpoint of an
    open interval would be an invention, not an interpolation)."""
    return 2.0 ** (i / _SUB)


def _escape_label_value(value: str) -> str:
    """Prometheus exposition label-value escaping: backslash, double
    quote and newline are the three characters the text format reserves
    (escaped as ``\\\\``, ``\\"`` and ``\\n`` — in that order, backslash
    first, or the other escapes would double-escape)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    """Monotonic counter."""

    def __init__(self, name: str, labels: Dict[str, str]) -> None:
        self.name = name
        self.labels = labels
        self._value = 0
        self._lock = make_lock("obs.metrics")

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Point-in-time value: either explicitly ``set()`` or backed by a
    callable evaluated at scrape time (the zero-hot-path-cost form).

    Teardown contract: a lazy provider belonging to a stopping element
    can be called by a concurrent scrape AFTER the element tore its
    state down.  A provider that raises (or returns something
    non-numeric) is a DEAD provider: :meth:`sample` answers ``None``
    and every renderer drops the sample — the scrape never 500s, never
    leaks an exception into the httpd thread, and never emits a bogus
    value for a metric that no longer exists."""

    def __init__(self, name: str, labels: Dict[str, str],
                 fn: Optional[Callable[[], float]] = None) -> None:
        self.name = name
        self.labels = labels
        self.fn = fn
        self._value = 0.0

    def set(self, value: float) -> None:
        self._value = float(value)

    def sample(self) -> Optional[float]:
        """The scrape read: the provider's value, or ``None`` when the
        provider is dead (raised / non-numeric) — a dropped sample,
        not an error."""
        if self.fn is not None:
            try:
                return float(self.fn())
            except Exception:   # noqa: BLE001 — dead provider (element
                return None     # stopped under the scrape): drop
        return self._value

    @property
    def value(self) -> float:
        """Back-compat numeric read; dead providers read as NaN (use
        :meth:`sample` to distinguish dead from NaN-valued)."""
        v = self.sample()
        return float("nan") if v is None else v


class Histogram:
    """Fixed log-bucket histogram with quantile estimation.

    ``observe`` is O(1): one log, one increment.  128 quarter-octave
    buckets cover 1 µs .. ~71 min when observations are microseconds
    (the unit every caller in this package uses).
    """

    __slots__ = ("name", "labels", "counts", "count", "total", "vmin",
                 "vmax", "_lock")

    def __init__(self, name: str, labels: Dict[str, str]) -> None:
        self.name = name
        self.labels = labels
        self.counts = [0] * _NBUCKETS
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = 0.0
        self._lock = make_lock("obs.metrics")

    def observe(self, value: float) -> None:
        i = _bucket_of(value)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.total += value
            if value < self.vmin:
                self.vmin = value
            if value > self.vmax:
                self.vmax = value

    def quantile(self, q: float) -> float:
        """Estimated ``q``-quantile (geometric bucket-midpoint
        interpolation); 0.0 when empty."""
        with self._lock:
            n = self.count
            if n == 0:
                return 0.0
            target = q * n
            seen = 0
            for i, c in enumerate(self.counts):
                seen += c
                if seen >= target:
                    mid = _bucket_mid(i)
                    # clamp to observed range: the edge buckets would
                    # otherwise report midpoints outside the data
                    return min(max(mid, self.vmin), self.vmax)
            return self.vmax

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            n = self.count
            if n == 0:
                return {"count": 0}
            mean = self.total / n
        return {"count": n, "mean": round(mean, 2),
                "min": round(self.vmin, 2), "max": round(self.vmax, 2),
                "p50": round(self.quantile(0.50), 2),
                "p95": round(self.quantile(0.95), 2),
                "p99": round(self.quantile(0.99), 2)}

    def state(self) -> Tuple[int, float, Tuple[int, ...]]:
        """Point-in-time ``(count, total, bucket counts)`` — the
        window-diff primitive: two states subtracted bucket-wise give a
        windowed distribution (``quantile_from_counts``) without
        resetting the live histogram."""
        with self._lock:
            return (self.count, self.total, tuple(self.counts))


def quantile_from_counts(counts, q: float) -> float:
    """``q``-quantile of a (possibly diff'd) bucket-count vector, using
    the same geometric-midpoint interpolation as
    :meth:`Histogram.quantile`.  This is how a WINDOWED p99 is computed
    from two :meth:`Histogram.state` snapshots without any
    per-observation timestamping.

    Documented edge behavior (pinned by property tests against numpy
    quantiles in tests/test_attrib.py):

    - **empty window** (all-zero vector, or empty vector): ``0.0`` —
      "no observations" reads as zero latency, never as an
      interpolated fiction;
    - **single-bucket mass**: every quantile answers that bucket's
      geometric midpoint (the only value the histogram can still
      distinguish — within the ~9 % bucket-resolution error);
    - **mass in the overflow bucket** (observations at/beyond the last
      bucket edge, ~71 min in µs): the overflow bucket's LOWER edge is
      returned, never a midpoint interpolated off the end of the range
      — the answer is a documented underestimate ("at least this"),
      not an invented point in an unbounded interval.
    """
    n = sum(counts)
    if n <= 0:
        return 0.0
    last = len(counts) - 1
    target = q * n
    seen = 0
    for i, c in enumerate(counts):
        seen += c
        if seen >= target:
            return _bucket_lo(i) if i == last else _bucket_mid(i)
    return _bucket_lo(last)


def count_over_threshold(counts, threshold: float) -> int:
    """Observations in a bucket-count vector whose bucket lies entirely
    at-or-above ``threshold``.  Bucket boundaries are log-spaced, so the
    answer is exact up to the bucket containing the threshold (that
    bucket is counted as over iff its geometric midpoint is over) —
    within the histogram's documented ~9 % quantile error.

    Documented edge behavior: ``threshold <= 1`` counts everything
    (bucket 0's lower edge is 1); an empty vector counts 0; a
    threshold at or beyond the overflow bucket's midpoint counts 0 —
    the histogram cannot distinguish values inside its open-ended last
    bucket, so it makes no claim rather than a wrong one."""
    if threshold <= 1.0:
        return sum(counts)
    lo = _bucket_of(threshold)
    if _bucket_mid(lo) < threshold:
        lo += 1
    return sum(counts[lo:])


class MetricsRegistry:
    """Process-wide metric table.

    Metrics are identified by (name, labels); re-registering returns
    the existing instance so call sites need no get-or-create dance.
    ``unregister_matching`` lets elements drop their gauges at stop.
    """

    def __init__(self) -> None:
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                            Any] = {}
        self._lock = make_lock("obs.metrics")

    def _key(self, name: str, labels: Dict[str, str]):
        return (name, tuple(sorted(labels.items())))

    def counter(self, name: str, **labels: str) -> Counter:
        return self._get_or_make(Counter, name, labels)

    def gauge(self, name: str, fn: Optional[Callable[[], float]] = None,
              **labels: str) -> Gauge:
        g = self._get_or_make(Gauge, name, labels, fn=fn)
        if fn is not None:
            g.fn = fn           # re-registration rebinds the provider
        return g

    def histogram(self, name: str, **labels: str) -> Histogram:
        return self._get_or_make(Histogram, name, labels)

    def _get_or_make(self, cls, name: str, labels: Dict[str, str],
                     **kw) -> Any:
        key = self._key(name, labels)
        with self._lock:
            m = self._metrics.get(key)
            if m is None or m.__class__ is not cls:
                m = self._metrics[key] = cls(name, dict(labels), **kw)
            return m

    def register(self, metric: Any) -> Any:
        """Install (or REPLACE) a caller-constructed metric under its
        (name, labels) key.  Replacement is the point: a freshly
        attached tracer's per-element histograms must supersede a prior
        run's instances so the endpoint serves the live distributions,
        not an accumulation across runs."""
        key = self._key(metric.name, metric.labels)
        with self._lock:
            self._metrics[key] = metric
        return metric

    def unregister(self, metric: Any) -> bool:
        """Remove ``metric`` ONLY if it is still the registered instance
        for its key.  The identity check is what makes element teardown
        safe when names collide: if a later pipeline re-registered the
        same (name, labels) key, stopping the earlier element must not
        delete the live provider."""
        key = self._key(metric.name, metric.labels)
        with self._lock:
            if self._metrics.get(key) is metric:
                del self._metrics[key]
                return True
            return False

    def unregister_matching(self, name: str, **labels: str) -> int:
        """Drop every metric with this name whose labels are a superset
        of ``labels``; returns how many were removed."""
        want = set(labels.items())
        with self._lock:
            victims = [k for k, m in self._metrics.items()
                       if m.name == name and want <= set(k[1])]
            for k in victims:
                del self._metrics[k]
            return len(victims)

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()

    def _snapshot(self) -> List[Any]:
        with self._lock:
            return list(self._metrics.values())

    # -- state snapshot / diff (the SLO evaluator's substrate) ---------------
    def snapshot_state(self, prefix: str = "") -> Dict[str, Any]:
        """Numeric state of every metric (optionally name-filtered by
        ``prefix``), keyed like :meth:`report`.  Counters/gauges carry
        their value, histograms their full ``(count, total, buckets)``
        state — so two snapshots taken at different times diff into
        exact windowed rates and windowed quantiles
        (:func:`state_delta`).  Taken under the registry lock only for
        the metric list; per-metric state reads take each metric's own
        lock, gauges evaluate their provider (scrape semantics)."""
        out: Dict[str, Any] = {}
        for m in self._snapshot():
            if prefix and not m.name.startswith(prefix):
                continue
            key = m.name + _label_str(m.labels)
            if isinstance(m, Histogram):
                count, total, counts = m.state()
                out[key] = {"kind": "histogram", "count": count,
                            "total": total, "counts": counts}
            elif isinstance(m, Counter):
                out[key] = {"kind": "counter", "value": m.value}
            else:
                v = m.sample() if isinstance(m, Gauge) else m.value
                if v is None:
                    continue   # dead provider: dropped sample
                out[key] = {"kind": "gauge", "value": v}
        return out

    # -- rendering -----------------------------------------------------------
    def report(self) -> Dict[str, Any]:
        """JSON-friendly snapshot (embedded in ``launch.py --trace``
        reports next to the tracer's per-element table)."""
        out: Dict[str, Any] = {}
        for m in self._snapshot():
            key = m.name + _label_str(m.labels)
            if isinstance(m, Histogram):
                out[key] = m.snapshot()
            else:
                v = m.sample() if isinstance(m, Gauge) else m.value
                if v is None:
                    continue   # dead provider: dropped sample
                out[key] = round(v, 4) if isinstance(v, float) else v
        for name, value in _resilience_items():
            out.setdefault(name, value)
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition format, v0.0.4."""
        lines: List[str] = []
        seen_families = set()

        def family(name: str, kind: str, help_: str) -> None:
            if name not in seen_families:
                seen_families.add(name)
                lines.append(f"# HELP {name} {help_}")
                lines.append(f"# TYPE {name} {kind}")

        for m in self._snapshot():
            if isinstance(m, Counter):
                family(m.name, "counter", "nnstreamer_tpu counter")
                lines.append(f"{m.name}{_label_str(m.labels)} {m.value}")
            elif isinstance(m, Gauge):
                v = m.sample()
                if v is None:
                    continue   # dead provider (element stopped under
                    #            the scrape): dropped sample, not a 500
                family(m.name, "gauge", "nnstreamer_tpu gauge")
                val = "NaN" if v != v else repr(round(v, 6))
                lines.append(f"{m.name}{_label_str(m.labels)} {val}")
            elif isinstance(m, Histogram):
                family(m.name, "summary", "nnstreamer_tpu latency summary")
                base = dict(m.labels)
                for q in (0.5, 0.95, 0.99):
                    lbl = _label_str({**base, "quantile": str(q)})
                    lines.append(f"{m.name}{lbl} "
                                 f"{round(m.quantile(q), 3)}")
                ls = _label_str(base)
                lines.append(f"{m.name}_sum{ls} {round(m.total, 3)}")
                lines.append(f"{m.name}_count{ls} {m.count}")
        for name, value in _resilience_items():
            family(name, "counter", "query resilience counter")
            lines.append(f"{name} {value}")
        return "\n".join(lines) + "\n"


def state_delta(new: Dict[str, Any], old: Dict[str, Any]
                ) -> Dict[str, Any]:
    """Window diff of two :meth:`MetricsRegistry.snapshot_state` maps:
    counters/histograms subtract (monotonic — a metric absent from
    ``old`` counts from zero, covering mid-window registration),
    gauges keep the NEW point-in-time value.  Histogram deltas carry
    the diff'd bucket vector, ready for :func:`quantile_from_counts` /
    :func:`count_over_threshold` — windowed rates and quantiles with no
    per-observation timestamping.

    Counter-reset hardening: a monotonic count going BACKWARDS between
    the two snapshots is a restart (a federated worker process died and
    came back under the same origin, or a same-key metric was
    re-registered) — the naive subtraction would yield a negative
    windowed rate that poisons burn rates and sustained-signal
    detection.  The delta clamps to zero AND carries ``reset: True`` so
    consumers that must not act on a restart artifact (the
    ``SustainedSignal`` detector, obs/timeseries.py) can skip the
    sample entirely instead of reading "zero traffic" as recovery."""
    out: Dict[str, Any] = {}
    for key, cur in new.items():
        kind = cur.get("kind")
        prev = old.get(key)
        if prev is not None and prev.get("kind") != kind:
            prev = None         # re-registered as a different type
        if kind == "counter":
            base = prev["value"] if prev else 0
            row = {"kind": "counter",
                   "value": max(0, cur["value"] - base)}
            if cur["value"] < base:
                row["reset"] = True
            out[key] = row
        elif kind == "histogram":
            reset = False
            if prev:
                # per-bucket clamp: a same-key histogram re-registered
                # mid-window (register() REPLACES — tracer re-attach)
                # resets counts below the base; a negative bucket would
                # poison windowed quantiles and burn rates
                counts = tuple(max(0, c - p) for c, p in
                               zip(cur["counts"], prev["counts"]))
                count = cur["count"] - prev["count"]
                total = cur["total"] - prev["total"]
                reset = cur["count"] < prev["count"]
            else:
                counts, count, total = (cur["counts"], cur["count"],
                                        cur["total"])
            row = {"kind": "histogram", "count": max(0, count),
                   "total": max(0.0, total), "counts": counts}
            if reset:
                row["reset"] = True
            out[key] = row
        else:
            out[key] = dict(cur)
    return out


def _resilience_items() -> List[Tuple[str, int]]:
    """The PR 1 resilience counters (process-wide STATS), bridged into
    the exposition under ``nns_resilience_*`` — the registry does not
    duplicate their accounting, it renders their live snapshot."""
    from ..query.resilience import STATS

    out = []
    for key, value in sorted(STATS.snapshot().items()):
        name = "nns_resilience_" + key.replace(".", "_").replace("-", "_")
        out.append((name, value))
    return out


#: process-wide registry (the endpoint serves this; elements register
#: their gauges here)
REGISTRY = MetricsRegistry()
