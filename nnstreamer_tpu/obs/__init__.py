"""Observability layer: spans, trace context, metrics, live endpoint.

The reference's profiling story is external GstShark tracers bolted onto
GStreamer (``proctime``/``framerate``/``interlatency``); the NNStreamer
papers (arXiv:1901.04985, arXiv:2101.06371) make per-element pipeline
profiling the core argument for the stream paradigm.  This package is
the built-in equivalent, designed around the same zero-cost-when-off
discipline as ``pipeline/tracing.py``:

- :mod:`~nnstreamer_tpu.obs.clock` — monotonic/wall clock helpers and
  the peer clock-offset estimator (NTP-midpoint style, over the query
  heartbeat/reply stamps).
- :mod:`~nnstreamer_tpu.obs.span` — per-buffer timeline spans, the
  bounded span ring, the compact wire trace-context, and Chrome
  ``trace_event`` export (Perfetto-renderable).
- :mod:`~nnstreamer_tpu.obs.metrics` — counters / gauges / log-bucket
  latency histograms with p50/p95/p99, a process-wide registry with a
  snapshot/diff API (``snapshot_state``/``state_delta`` — windowed
  rates and quantiles for the SLO evaluator), and Prometheus text
  rendering.
- :mod:`~nnstreamer_tpu.obs.httpd` — the pull-based ``NNS_METRICS_PORT``
  HTTP endpoint serving the registry, plus the ``/healthz`` readiness
  aggregate (``starting|serving|degraded|draining`` health sources).
- :mod:`~nnstreamer_tpu.obs.attrib` — wait-state attribution: a traced
  frame's end-to-end wall time decomposed into a closed state set
  (source-pacing, queue-wait, admission-wait, serialize, wire,
  device-invoke/compile, reorder-wait, sink, dispatch), with the
  conservation guarantee that state sums equal e2e; plus the device
  FLOPs/bytes cost model and the per-chip peak tables behind the live
  ``nns_mfu`` gauge (the same tables bench.py imports).
- :mod:`~nnstreamer_tpu.obs.profile` — the :class:`Profiler` surface
  over all of it: blame tables, folded-stack flamegraphs, per-element
  occupancy gauges (``launch.py --profile``).
- :mod:`~nnstreamer_tpu.obs.timeseries` — bounded ring of periodic
  registry snapshots (windowed rates / quantiles-over-window via
  ``state_delta``) plus :class:`SustainedSignal` detection (threshold
  × min-hold × disarm hysteresis) on a subscribable signal bus — the
  substrate autoscaling decisions and soak verdicts read.
- :mod:`~nnstreamer_tpu.obs.federation` — cross-process metric
  federation: worker registries pushed as ``T_METRICS`` deltas over
  the query wire into a collector that re-renders ONE origin-labeled
  ``/metrics`` + worst-of ``/healthz`` for the whole fleet.
- :mod:`~nnstreamer_tpu.obs.dashboard` — the ``nns-top`` live terminal
  view over a time-series ring or a scraped endpoint
  (``tools/nns_top.py``, ``launch.py --top``).

Nothing in this package runs on the dataflow hot path unless a tracer
with span recording is attached: metrics are lazy callable gauges
evaluated at scrape time, and untraced compiled plans contain zero obs
references (enforced by ``tools/hotpath_bench.py --stage obs --assert``).
"""

from .attrib import (STATES, blame_from_spans,  # noqa: F401
                     device_peaks, estimate_jit_cost)
from .clock import OffsetEstimator, mono_ns, wall_us  # noqa: F401
from .metrics import (REGISTRY, Counter, Gauge, Histogram,  # noqa: F401
                      MetricsRegistry, count_over_threshold,
                      quantile_from_counts, state_delta)
from .span import (Span, SpanRing, TraceContext,  # noqa: F401
                   chrome_trace_events, new_trace_id)
from .timeseries import (RingSampler, SignalBus,  # noqa: F401
                         SustainedSignal, TimeSeriesRing)

# federation imports query/protocol lazily at wire use, but the module
# itself is import-light; exported here so consumers reach the fleet
# plane through one namespace
from .federation import (CollectorServer, MetricsCollector,  # noqa: F401
                         MetricsPublisher, origin_id)
