"""``nns-top``: a live terminal view over the telemetry plane.

The rendering core behind ``tools/nns_top.py`` and ``launch.py --top``.
Deliberately source-agnostic: a frame is built from *flat samples* —
``[(t_seconds, {metric_key: float}), …]`` — which both telemetry
sources produce:

- a local :class:`~nnstreamer_tpu.obs.timeseries.TimeSeriesRing`
  (``flat_samples()``), including one running over a federation
  collector, and
- a scraped ``/metrics`` endpoint (:func:`parse_prometheus` over
  periodic GETs), local or federated.

Everything interesting is therefore computed the same way the fleet
autoscaler will compute it: gauges read from the newest sample, rates
from windowed counter diffs, trends from per-sample series.  The frame
builder (:func:`build_view`) and renderer (:func:`render_frame`) are
pure functions of the samples — tests feed synthetic histories with an
injected clock and assert on the text.

Sections: origins (federation), serving rates (admitted / shed /
batched frames), queue + bucket occupancy bars, MFU, per-element
occupancy/latency, and armed/fired sustained signals
(``nns_signal_state`` travels the same metric plane, so federated
signal states render too).
"""

from __future__ import annotations

import re
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: sparkline glyph ramp (8 levels + blank)
_SPARK = " ▁▂▃▄▅▆▇█"
_BAR_FILL, _BAR_EMPTY = "#", "."

_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')
_SIGNAL_STATES = {0: "idle", 1: "holding", 2: "FIRED"}


def parse_prometheus(text: str) -> Dict[str, float]:
    """One ``/metrics`` body parsed to ``{name{labels}: value}`` (the
    flat-sample shape).  Unparseable values are skipped, comments
    ignored.  Handles the exposition format's optional trailing
    timestamp (``name{l} value ts``) and label values containing
    spaces — the split point is after the closing brace, never inside
    the label block."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("{"):
            continue        # malformed: no metric name
        brace = line.find("{")
        if brace >= 0:
            end = line.rfind("}")
            if end < brace:
                continue    # malformed label block
            key, rest = line[:end + 1], line[end + 1:]
        else:
            key, _, rest = line.partition(" ")
        fields = rest.split()
        if not fields:
            continue
        try:
            out[key] = float(fields[0])     # fields[1] = timestamp
        except ValueError:
            continue
    return out


def key_name(key: str) -> str:
    return key.partition("{")[0]


_UNESCAPE_RE = re.compile(r'\\(["\\n])')


def _unescape_label(value: str) -> str:
    """Single-pass inverse of metrics.py's ``_escape_label_value``:
    sequential ``str.replace`` calls cannot round-trip (``\\\\n`` — an
    escaped backslash followed by a literal ``n`` — would decode as a
    newline)."""
    return _UNESCAPE_RE.sub(
        lambda m: "\n" if m.group(1) == "n" else m.group(1), value)


def key_labels(key: str) -> Dict[str, str]:
    _, brace, rest = key.partition("{")
    if not brace:
        return {}
    return {m.group(1): _unescape_label(m.group(2))
            for m in _LABEL_RE.finditer(rest)}


def sparkline(points: Sequence[float], width: int = 16) -> str:
    """Fixed-width sparkline of the most recent ``width`` points,
    scaled to their own min..max (a flat series renders mid-level, so
    'boring' is visibly distinct from 'empty')."""
    pts = list(points)[-width:]
    if not pts:
        return " " * width
    lo, hi = min(pts), max(pts)
    span = hi - lo
    out = []
    for v in pts:
        if span <= 0:
            out.append(_SPARK[4] if hi else _SPARK[0])
        else:
            idx = 1 + int((v - lo) / span * 7)
            out.append(_SPARK[min(8, idx)])
    return "".join(out).rjust(width)


def bar(frac: float, width: int = 12) -> str:
    frac = max(0.0, min(1.0, frac))
    n = int(round(frac * width))
    return "[" + _BAR_FILL * n + _BAR_EMPTY * (width - n) + "]"


# ---------------------------------------------------------------------------
# view model
# ---------------------------------------------------------------------------

def _latest(samples) -> Dict[str, float]:
    return samples[-1][1] if samples else {}


def _match(flat: Dict[str, float], family: str) -> Dict[str, float]:
    return {k: v for k, v in flat.items() if key_name(k) == family}


def _rate(samples, family: str, window_s: float) -> float:
    """Summed counter rate over the trailing window (clamped at 0 so a
    worker restart between samples never renders a negative rate)."""
    if len(samples) < 2:
        return 0.0
    t_new, new = samples[-1]
    base_t, base = samples[0]
    for t, flat in samples:
        if t <= t_new - window_s:
            base_t, base = t, flat
        else:
            break
    span = t_new - base_t
    if span <= 0:
        return 0.0
    total = sum(max(0.0, v - base.get(k, 0.0))
                for k, v in _match(new, family).items())
    return total / span


def _series(samples, family: str, per_second: bool = False
            ) -> List[float]:
    """Per-sample summed family value (optionally diffed to rates) —
    the sparkline feed."""
    out: List[float] = []
    prev_t = prev_v = None
    for t, flat in samples:
        v = sum(_match(flat, family).values())
        if per_second:
            if prev_t is not None and t > prev_t:
                out.append(max(0.0, (v - prev_v) / (t - prev_t)))
            prev_t, prev_v = t, v
        else:
            out.append(v)
    return out


def build_view(samples: Sequence[Tuple[float, Dict[str, float]]],
               window_s: float = 10.0,
               origins: Optional[List[Dict[str, Any]]] = None,
               signal_report: Optional[Dict[str, Any]] = None,
               source: str = "registry") -> Dict[str, Any]:
    """The dashboard's frame model, computed purely from flat samples
    (+ optional collector origin rows / ring signal report)."""
    flat = _latest(samples)
    view: Dict[str, Any] = {"source": source, "window_s": window_s,
                            "samples": len(samples)}

    # -- origins (federation): explicit rows, else derived from labels
    if origins is None:
        keys = sorted({key_labels(k).get("origin") for k in flat}
                      - {None})
        origins = [{"origin": o} for o in keys]

    # -- fleet tier (fleet/router.py + launch.py NNS_FLEET_ROLE):
    # per-origin role tags from the nns_fleet_role gauges, per-worker
    # routed-connection counts + draining state from the router's
    # gauges — all riding the same federated scrape, so the fleet view
    # needs no side channel
    roles: Dict[str, str] = {}
    for k in _match(flat, "nns_fleet_role"):
        labels = key_labels(k)
        role = labels.get("role")
        if role:
            roles[labels.get("origin", "")] = role
    origins = [dict(o) for o in origins]
    for o in origins:
        role = roles.get(str(o.get("origin", "")))
        if role:
            o["role"] = role
    view["origins"] = origins
    fleet_workers: Dict[str, Dict[str, Any]] = {}
    for k, v in _match(flat, "nns_fleet_routed_connections").items():
        w = key_labels(k).get("worker", "?")
        fleet_workers.setdefault(w, {"worker": w})["routed"] = v
    for k, v in _match(flat, "nns_fleet_worker_draining").items():
        w = key_labels(k).get("worker", "?")
        fleet_workers.setdefault(w, {"worker": w})["draining"] = \
            bool(v)
    view["fleet"] = [fleet_workers[w] for w in sorted(fleet_workers)]

    # -- serving rates
    rates = []
    for label, family in (
            ("admitted", "nns_query_server_admitted_total"),
            ("shed", "nns_query_server_shed_total"),
            ("accepted conns", "nns_query_server_accepted_total"),
            ("batched frames", "nns_xbatch_frames_total"),
            ("evicted", "nns_query_server_evicted_total")):
        vals = _match(flat, family)
        if not vals:
            continue
        rates.append({"label": label, "family": family,
                      "total": sum(vals.values()),
                      "rate": _rate(samples, family, window_s),
                      "spark": _series(samples, family,
                                       per_second=True)})
    view["rates"] = rates

    # -- gauges: queue depth vs capacity-ish peak, occupancy, mfu, shed
    def _gauge(family: str, agg=max) -> Optional[float]:
        vals = _match(flat, family)
        return agg(vals.values()) if vals else None

    depth = _gauge("nns_query_server_queue_depth")
    peak = _gauge("nns_query_server_queue_peak")
    gauges = []
    if depth is not None:
        gauges.append({"label": "queue depth", "value": depth,
                       "of": peak,
                       "spark": _series(samples,
                                        "nns_query_server_queue_depth")})
    occ = _gauge("nns_xbatch_occupancy")
    if occ is not None:
        gauges.append({"label": "bucket occupancy", "value": occ,
                       "of": None,
                       "spark": _series(samples,
                                        "nns_xbatch_occupancy")})
    fill = _gauge("nns_xbatch_fill")
    if fill is not None:
        gauges.append({"label": "bucket fill", "value": fill,
                       "of": 1.0,
                       "spark": _series(samples, "nns_xbatch_fill")})
    shed_rate = _gauge("nns_query_server_shed_rate")
    if shed_rate is not None:
        gauges.append({"label": "shed fraction", "value": shed_rate,
                       "of": 1.0,
                       "spark": _series(samples,
                                        "nns_query_server_shed_rate")})
    mfu = _gauge("nns_mfu")
    if mfu is not None:
        gauges.append({"label": "mfu", "value": mfu, "of": None,
                       "spark": _series(samples, "nns_mfu")})
    clients = _gauge("nns_query_server_clients", agg=sum)
    if clients is not None:
        gauges.append({"label": "clients", "value": clients,
                       "of": None,
                       "spark": _series(samples,
                                        "nns_query_server_clients")})
    view["gauges"] = gauges

    # -- per-element occupancy + p99 proctime
    elements: Dict[str, Dict[str, Any]] = {}
    for k, v in _match(flat, "nns_element_occupancy").items():
        name = key_labels(k).get("element", key_labels(k).get(
            "name", "?"))
        elements.setdefault(name, {})["occupancy"] = v
    for k, v in _match(flat, "nns_element_proctime_us").items():
        labels = key_labels(k)
        if labels.get("quantile") != "0.99":
            continue
        name = labels.get("element", labels.get("name", "?"))
        elements.setdefault(name, {})["p99_us"] = v
    view["elements"] = [{"element": n, **row}
                        for n, row in sorted(elements.items())]

    # -- LLM serving panel (llm/element.py gauges + llm/tokenobs.py
    # histograms): resident sessions, mean decode-step fill, decode
    # tok/s, TTFT p99 sparkline, free-pages trend — present only when
    # an LLM element is exporting (the families exist), so non-LLM
    # dashboards render unchanged
    llm: Dict[str, Any] = {}
    active = _gauge("nns_llm_active_seqs", agg=sum)
    if active is not None:
        llm["active_seqs"] = active
        llm["active_spark"] = _series(samples, "nns_llm_active_seqs")
    fill_llm = _gauge("nns_llm_decode_fill")
    if fill_llm is not None:
        llm["decode_fill"] = fill_llm
    toks = _gauge("nns_llm_tokens_per_s", agg=sum)
    if toks is not None:
        llm["tokens_per_s"] = toks
        llm["tokens_spark"] = _series(samples, "nns_llm_tokens_per_s")
    for k, v in flat.items():
        if key_name(k) == "nns_llm_ttft_us" and \
                key_labels(k).get("quantile") == "0.99":
            llm["ttft_p99_us"] = max(v, llm.get("ttft_p99_us", 0.0))
    if "ttft_p99_us" in llm:
        # per-sample max across class labels — the worst class's trend
        spark: List[float] = []
        for _, f in samples:
            vals = [v for k, v in f.items()
                    if key_name(k) == "nns_llm_ttft_us"
                    and key_labels(k).get("quantile") == "0.99"]
            spark.append(max(vals) if vals else 0.0)
        llm["ttft_spark"] = spark
    free = _gauge("nns_llm_free_pages", agg=min)
    if free is not None:
        llm["free_pages"] = free
        llm["pages_spark"] = _series(samples, "nns_llm_free_pages")
    hit = _gauge("nns_llm_prefix_hit_rate")
    if hit is not None:
        llm["prefix_hit_rate"] = hit
    if llm:
        view["llm"] = llm

    # -- sustained signals: the ring's own report when available, else
    # reconstructed from nns_signal_state gauges (scrape / federated)
    signals = []
    if signal_report is not None:
        for s in signal_report.get("signals", ()):
            signals.append({"signal": s["signal"], "state": s["state"],
                            "firings": s["firings"],
                            "value": s.get("value")})
    else:
        for k, v in _match(flat, "nns_signal_state").items():
            labels = key_labels(k)
            signals.append({"signal": labels.get("signal", "?"),
                            "state": _SIGNAL_STATES.get(int(v),
                                                        str(v)),
                            "firings": None, "value": None,
                            "origin": labels.get("origin")})
    view["signals"] = signals

    # -- latency summary (slo loadgen / service histograms, when the
    # source pre-renders quantiles — scrapes and flat_samples both do)
    lat = []
    for family in ("nns_slo_latency_us", "nns_query_service_us",
                   "nns_element_proctime_us"):
        for k, v in flat.items():
            labels = key_labels(k)
            if key_name(k) == family and labels.get("quantile") \
                    == "0.99" and "element" not in labels:
                lat.append({"label": f"{family} p99", "value": v})
                break
    view["latency"] = lat
    return view


# ---------------------------------------------------------------------------
# renderer
# ---------------------------------------------------------------------------

def _fmt(v: Optional[float]) -> str:
    if v is None:
        return "-"
    if v != v:
        return "NaN"
    if v and abs(v) < 0.001:
        return f"{v:.2e}"
    if abs(v) >= 1000:
        return f"{v:,.0f}"
    return f"{v:.3g}"


def render_frame(view: Dict[str, Any], width: int = 96,
                 clock: Optional[float] = None) -> str:
    """One dashboard frame as plain text (no ANSI — the refresh loop
    owns cursor control), sectioned and column-aligned."""
    when = time.strftime("%H:%M:%S",
                         time.localtime(clock if clock is not None
                                        else time.time()))
    lines = [f"nns-top — {view['source']}  {when}  "
             f"window {view['window_s']:g}s  "
             f"samples {view['samples']}"]
    lines.append("=" * min(width, 96))

    origins = view.get("origins") or []
    if origins:
        cells = []
        for o in origins:
            cell = o["origin"]
            extra = []
            if o.get("role"):
                extra.append(str(o["role"]))
            if o.get("health"):
                extra.append(str(o["health"]))
            if o.get("age_s") is not None:
                extra.append(f"age {o['age_s']:.1f}s")
            if extra:
                cell += " (" + ", ".join(extra) + ")"
            cells.append(cell)
        lines.append("origins: " + "   ".join(cells))

    fleet = view.get("fleet") or []
    if fleet:
        lines.append(f"{'fleet worker':<24}{'routed':>8}  state")
        for w in fleet:
            state = "draining" if w.get("draining") else "serving"
            lines.append(f"{w['worker']:<24}"
                         f"{_fmt(w.get('routed')):>8}  {state}")

    if view.get("rates"):
        lines.append(f"{'throughput':<18}{'total':>12}{'rate/s':>10}"
                     f"  trend")
        for r in view["rates"]:
            lines.append(f"{r['label']:<18}{_fmt(r['total']):>12}"
                         f"{_fmt(r['rate']):>10}  "
                         f"{sparkline(r['spark'])}")

    if view.get("gauges"):
        lines.append(f"{'gauge':<18}{'value':>12}{'':>10}  trend")
        for g in view["gauges"]:
            if g["of"]:
                meter = bar(g["value"] / g["of"])
                val = f"{_fmt(g['value'])}/{_fmt(g['of'])}"
            else:
                meter = ""
                val = _fmt(g["value"])
            lines.append(f"{g['label']:<18}{val:>12}{meter:>14}  "
                         f"{sparkline(g['spark'])}")

    llm = view.get("llm") or {}
    if llm:
        lines.append(f"{'llm serving':<18}{'value':>12}{'':>10}  trend")
        if "active_seqs" in llm:
            lines.append(f"{'resident sessions':<18}"
                         f"{_fmt(llm['active_seqs']):>12}{'':>14}  "
                         f"{sparkline(llm.get('active_spark', ()))}")
        if "decode_fill" in llm:
            lines.append(f"{'decode step fill':<18}"
                         f"{_fmt(llm['decode_fill']):>12}"
                         f"{bar(llm['decode_fill']):>14}")
        if "tokens_per_s" in llm:
            lines.append(f"{'decode tok/s':<18}"
                         f"{_fmt(llm['tokens_per_s']):>12}{'':>14}  "
                         f"{sparkline(llm.get('tokens_spark', ()))}")
        if "ttft_p99_us" in llm:
            lines.append(f"{'ttft p99 us':<18}"
                         f"{_fmt(llm['ttft_p99_us']):>12}{'':>14}  "
                         f"{sparkline(llm.get('ttft_spark', ()))}")
        if "free_pages" in llm:
            lines.append(f"{'free pages':<18}"
                         f"{_fmt(llm['free_pages']):>12}{'':>14}  "
                         f"{sparkline(llm.get('pages_spark', ()))}")
        if "prefix_hit_rate" in llm:
            lines.append(f"{'prefix hit rate':<18}"
                         f"{_fmt(llm['prefix_hit_rate']):>12}"
                         f"{bar(llm['prefix_hit_rate']):>14}")

    if view.get("latency"):
        for row in view["latency"]:
            lines.append(f"{row['label']:<34}{_fmt(row['value']):>10}us")

    if view.get("elements"):
        lines.append(f"{'element':<18}{'occupancy':>12}{'p99 us':>12}")
        for e in view["elements"]:
            occ = e.get("occupancy")
            meter = bar(occ) if occ is not None else ""
            lines.append(f"{e['element']:<18}{_fmt(occ):>12}"
                         f"{_fmt(e.get('p99_us')):>12}  {meter}")

    sigs = view.get("signals") or []
    if sigs:
        cells = []
        for s in sigs:
            cell = f"{s['signal']}={s['state']}"
            if s.get("firings"):
                cell += f"(x{s['firings']})"
            if s.get("origin"):
                cell += f"@{s['origin']}"
            cells.append(cell)
        lines.append("signals: " + "  ".join(cells))
    else:
        lines.append("signals: (none configured)")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# sources + refresh loop
# ---------------------------------------------------------------------------

class RingSource:
    """Dashboard source over an in-process
    :class:`~nnstreamer_tpu.obs.timeseries.TimeSeriesRing` (optionally
    ring-over-collector — then origin rows come from the collector)."""

    def __init__(self, ring, collector=None,
                 label: str = "registry") -> None:
        self.ring = ring
        self.collector = collector
        self.label = label

    def frame(self, window_s: float) -> Dict[str, Any]:
        samples = self.ring.flat_samples()
        origins = (self.collector.origins()
                   if self.collector is not None else None)
        return build_view(samples, window_s=window_s, origins=origins,
                          signal_report=self.ring.signal_report(),
                          source=self.label)


class ScrapeSource:
    """Dashboard source over a remote ``/metrics`` endpoint: each
    ``frame()`` scrapes once and appends to its own bounded history —
    the dashboard builds its ring from the wire."""

    def __init__(self, url: str, retention: int = 240) -> None:
        from collections import deque
        from urllib.parse import urlparse

        if "://" not in url:
            url = f"http://{url}"
        if urlparse(url).path in ("", "/"):
            # '/metrics appended when missing' applies to full URLs
            # too: http://host:port must scrape the metrics path, not
            # 404 against the endpoint root
            url = url.rstrip("/") + "/metrics"
        self.url = url
        self.samples: "deque" = deque(maxlen=retention)
        self.scrape_errors = 0

    def scrape(self) -> Optional[Dict[str, float]]:
        import urllib.request

        try:
            with urllib.request.urlopen(self.url, timeout=5) as resp:
                return parse_prometheus(
                    resp.read().decode("utf-8", "replace"))
        except OSError:
            self.scrape_errors += 1
            return None

    def frame(self, window_s: float) -> Dict[str, Any]:
        flat = self.scrape()
        if flat is not None:
            self.samples.append((time.monotonic(), flat))
        return build_view(list(self.samples), window_s=window_s,
                          source=self.url)


class TopLoop:
    """The refresh loop: render a frame every ``interval_s`` to
    ``out`` with ANSI home+clear between frames (plain frames when
    ``ansi=False`` — piped output, tests)."""

    def __init__(self, source, interval_s: float = 1.0,
                 window_s: float = 10.0, out=None,
                 ansi: bool = True) -> None:
        import sys
        import threading

        self.source = source
        self.interval_s = max(0.05, float(interval_s))
        self.window_s = float(window_s)
        self.out = out if out is not None else sys.stdout
        self.ansi = ansi
        self.frames = 0
        self._stop = threading.Event()
        self._thread = None

    def render_once(self) -> str:
        text = render_frame(self.source.frame(self.window_s))
        self.frames += 1
        return text

    def _emit(self) -> None:
        text = self.render_once()
        if self.ansi:
            self.out.write("\x1b[H\x1b[2J" + text)
        else:
            self.out.write(text)
        try:
            self.out.flush()
        except (OSError, ValueError):
            pass

    def run(self, duration_s: Optional[float] = None) -> None:
        """Foreground loop (tools/nns_top.py): render until stopped,
        Ctrl-C or ``duration_s``."""
        from .clock import mono_ns

        deadline = (mono_ns() / 1e9 + duration_s
                    if duration_s is not None else None)
        self._emit()
        while not self._stop.wait(self.interval_s):
            if deadline is not None and mono_ns() / 1e9 >= deadline:
                return
            self._emit()

    def start(self) -> "TopLoop":
        """Background loop (launch.py --top renders while the pipeline
        streams)."""
        import threading

        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self.run,
                                            daemon=True,
                                            name="nns-top")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)
