"""On-host metrics time series: a bounded ring of registry snapshots
plus sustained-signal detection.

The PR 5–8 instrument stack answers "what is the value NOW" (lazy
gauges, cumulative counters, live histograms) — but every consumer that
wants to *act* on telemetry needs "what has the value BEEN": a windowed
shed rate, a queue depth held above watermark for 10 s, an MFU trend.
The SLO evaluator (slo/evaluator.py) already solved this for burn rates
with its snapshot store; this module generalizes the same substrate —
periodic :meth:`MetricsRegistry.snapshot_state` captures diffed with
:func:`~nnstreamer_tpu.obs.metrics.state_delta` — into a reusable ring
any consumer can query:

- :class:`TimeSeriesRing` — bounded (interval × retention) store of
  snapshots with windowed counter rates, histogram
  quantiles-over-window, and per-capture flattened series (the
  ``nns-top`` dashboard's sparkline feed).
- :class:`SustainedSignal` — threshold × min-hold-duration × disarm
  hysteresis.  PR 6's arming philosophy, applied to a single metric:
  a startup blip or one hot scrape must NEVER fire; only a condition
  that holds continuously for ``min_hold_s`` does, and once fired the
  signal stays armed until the value recovers past a *lower* disarm
  threshold (no flapping at the boundary).  Samples whose window delta
  carries ``reset: True`` (a worker restarted — counters went
  backwards) are skipped entirely: a restart artifact is neither load
  nor recovery.
- :class:`SignalBus` — subscribable fan-out of signal transitions
  (``armed``/``fired``/``cleared``): the hook the future fleet
  autoscaler and ``tools/soak.py`` verdicts consume.
- :class:`RingSampler` — the background capture loop
  (absolute-deadline pacing, ``Event.wait`` — no ``time.sleep``
  polling).

Every signal also exports its state as a lazy gauge
(``nns_signal_state{signal=...}``: 0 idle, 1 holding, 2 fired), so
signal states travel through /metrics scrapes and metric federation
(obs/federation.py) for free.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..analysis.sanitizer import make_lock
from .clock import mono_ns, wall_us
from .metrics import (REGISTRY, quantile_from_counts, state_delta)

#: signal lifecycle states (exported via the nns_signal_state gauge)
SIGNAL_IDLE, SIGNAL_HOLDING, SIGNAL_FIRED = "idle", "holding", "fired"
_SIGNAL_STATE_NUM = {SIGNAL_IDLE: 0, SIGNAL_HOLDING: 1, SIGNAL_FIRED: 2}


def _family(key: str) -> str:
    return key.partition("{")[0]


def _key_match(key: str, family: str, match: Optional[str]) -> bool:
    if _family(key) != family:
        return False
    return not match or match in key


class SignalBus:
    """Subscribable signal-event fan-out.

    ``subscribe(fn)`` registers a callable receiving every event dict;
    events are delivered synchronously from the capture thread, OUTSIDE
    every ring/signal lock (a slow subscriber delays the next capture,
    never deadlocks it).  A subscriber that raises is dropped from that
    event's delivery but stays subscribed — telemetry consumers must
    not kill the sampler."""

    def __init__(self) -> None:
        self._lock = make_lock("leaf")
        self._subs: List[Callable[[Dict[str, Any]], None]] = []
        #: bounded recent-events ring (verdict/debug surface for
        #: consumers that poll instead of subscribing)
        self.events: "deque[Dict[str, Any]]" = deque(maxlen=256)

    def subscribe(self, fn: Callable[[Dict[str, Any]], None]
                  ) -> Callable[[], None]:
        """Register ``fn``; returns an unsubscribe callable."""
        with self._lock:
            self._subs.append(fn)

        def _unsubscribe() -> None:
            with self._lock:
                try:
                    self._subs.remove(fn)
                except ValueError:
                    pass
        return _unsubscribe

    def publish(self, event: Dict[str, Any]) -> None:
        with self._lock:
            subs = list(self._subs)
            self.events.append(event)
        for fn in subs:
            try:
                fn(event)
            except Exception:   # noqa: BLE001 — a consumer must not
                pass            # kill the capture thread


class SustainedSignal:
    """One sustained condition over one metric family.

    ``kind`` picks how the value is computed from the ring at each
    capture:

    - ``"gauge"`` — aggregate (``agg``: max | sum | mean) of the NEWEST
      sample's matching gauge values;
    - ``"rate"`` — summed matching counter deltas over ``window_s``,
      divided by the window span (events/s);
    - ``"p99"``/``"p95"``/``"p50"`` — windowed histogram quantile over
      ``window_s`` (summed matching bucket vectors).

    Lifecycle: value ``>= threshold`` starts the hold clock
    (``holding``); held CONTINUOUSLY for ``min_hold_s`` → ``fired``
    (latched; ``firings`` increments once per onset); value ``<=
    disarm_below`` (default ``threshold / 2``) → ``cleared`` back to
    idle, re-armable.  A dip below threshold but above ``disarm_below``
    while holding resets the hold clock without a ``cleared`` event —
    hysteresis, not flapping.

    ``direction="below"`` inverts the comparison for *idle* conditions
    (the fleet autoscaler's drain-on-idle signal): the condition holds
    while ``value <= threshold`` and disarms at ``value >=
    disarm_above`` (default ``2 x threshold``, or ``1.0`` when the
    threshold is 0 — "any traffic at all clears idleness").  Same
    state machine, same reset discipline, mirrored band.

    Reset discipline: when any matching key's window delta carries
    ``reset: True`` (state_delta detected counters going backwards — a
    worker restart), the tick is SKIPPED: the hold clock neither
    advances nor resets, and no transition fires.  A restart artifact
    must never page and must never read as recovery.
    """

    def __init__(self, name: str, metric: str, *, threshold: float,
                 min_hold_s: float, kind: str = "gauge",
                 window_s: float = 10.0,
                 disarm_below: Optional[float] = None,
                 direction: str = "above",
                 disarm_above: Optional[float] = None,
                 agg: str = "max", match: Optional[str] = None) -> None:
        if kind not in ("gauge", "rate", "p99", "p95", "p50"):
            raise ValueError(f"signal {name}: kind={kind!r}")
        if agg not in ("max", "sum", "mean"):
            raise ValueError(f"signal {name}: agg={agg!r}")
        if direction not in ("above", "below"):
            raise ValueError(f"signal {name}: direction={direction!r}")
        self.name = name
        self.metric = metric
        self.kind = kind
        self.direction = direction
        self.threshold = float(threshold)
        self.min_hold_s = float(min_hold_s)
        self.window_s = float(window_s)
        if direction == "below":
            if disarm_below is not None:
                raise ValueError(
                    f"signal {name}: direction=below disarms ABOVE the "
                    "threshold — use disarm_above")
            self.disarm_above = (float(disarm_above) if disarm_above
                                 is not None
                                 else (self.threshold * 2.0
                                       or 1.0))
            self.disarm_below = None
            if self.disarm_above < self.threshold:
                raise ValueError(
                    f"signal {name}: disarm_above {self.disarm_above} "
                    f"under threshold {self.threshold} (an idle signal "
                    "must disarm ABOVE where it arms)")
        else:
            if disarm_above is not None:
                raise ValueError(
                    f"signal {name}: direction=above disarms BELOW the "
                    "threshold — use disarm_below")
            self.disarm_above = None
            self.disarm_below = (float(disarm_below) if disarm_below
                                 is not None else self.threshold / 2.0)
            if self.disarm_below > self.threshold:
                raise ValueError(
                    f"signal {name}: disarm_below {self.disarm_below} "
                    f"above threshold {self.threshold} (hysteresis "
                    "must disarm BELOW where it arms)")
        self.agg = agg
        self.match = match
        self.state = SIGNAL_IDLE
        self.firings = 0
        self.value: Optional[float] = None
        #: OBSERVED seconds the condition has held (accumulated over
        #: valid ticks only — a skipped tick's gap contributes nothing,
        #: so an unobserved restart window can never satisfy min_hold)
        self._held_s = 0.0
        self._last_valid_t: Optional[float] = None
        self._fired_at: Optional[float] = None

    # -- value extraction ----------------------------------------------------
    def _value_from(self, newest: Dict[str, Any],
                    delta: Dict[str, Any], span_s: float
                    ) -> Tuple[Optional[float], bool]:
        """(value, reset_seen) for this capture; value None = the
        metric is absent (signal stays wherever it is)."""
        if self.kind == "gauge":
            vals = [st["value"] for key, st in newest.items()
                    if st.get("kind") == "gauge"
                    and _key_match(key, self.metric, self.match)]
            if not vals:
                return None, False
            if self.agg == "max":
                return max(vals), False
            if self.agg == "sum":
                return sum(vals), False
            return sum(vals) / len(vals), False
        reset = False
        if self.kind == "rate":
            total = 0
            seen = False
            for key, st in delta.items():
                if st.get("kind") != "counter" \
                        or not _key_match(key, self.metric, self.match):
                    continue
                seen = True
                reset = reset or bool(st.get("reset"))
                total += st["value"]
            if not seen:
                return None, reset
            return total / max(span_s, 1e-9), reset
        # windowed quantile
        q = {"p99": 0.99, "p95": 0.95, "p50": 0.50}[self.kind]
        counts: Optional[List[int]] = None
        for key, st in delta.items():
            if st.get("kind") != "histogram" \
                    or not _key_match(key, self.metric, self.match):
                continue
            reset = reset or bool(st.get("reset"))
            if counts is None:
                counts = list(st["counts"])
            else:
                for i, c in enumerate(st["counts"]):
                    counts[i] += c
        if counts is None or not sum(counts):
            return None, reset
        return quantile_from_counts(counts, q), reset

    def _breaches(self, value: float) -> bool:
        if self.direction == "below":
            return value <= self.threshold
        return value >= self.threshold

    def _disarms(self, value: float) -> bool:
        if self.direction == "below":
            return value >= self.disarm_above
        return value <= self.disarm_below

    # -- lifecycle -----------------------------------------------------------
    def evaluate(self, now: float, newest: Dict[str, Any],
                 delta: Dict[str, Any], span_s: float,
                 reset_keys: frozenset = frozenset()
                 ) -> List[Dict[str, Any]]:
        """One capture's worth of state machine; returns the transition
        events to publish (possibly several: holding→fired in one tick
        when min_hold_s is 0).  ``reset_keys`` carries the keys whose
        ADJACENT-sample delta detected a counter reset this capture —
        the windowed delta alone can mask a mid-window restart (base →
        newest may still be net-positive across it)."""
        skip = (any(_key_match(k, self.metric, self.match)
                    for k in reset_keys))
        value = None
        if not skip:
            value, reset = self._value_from(newest, delta, span_s)
            skip = reset
        if skip or value is None:
            # worker restart inside the window (or the metric is
            # absent this tick): skip entirely — the hold PROGRESS is
            # kept but the unobserved gap must not count toward
            # min_hold, so the next valid tick re-anchors instead of
            # crediting time nobody measured
            self._last_valid_t = None
            return []
        self.value = value
        if self.state == SIGNAL_HOLDING and self._last_valid_t is not None \
                and self._breaches(value):
            self._held_s += now - self._last_valid_t
        events: List[Dict[str, Any]] = []

        def _event(state: str) -> Dict[str, Any]:
            return {"signal": self.name, "state": state,
                    "t": round(now, 3), "value": round(value, 6),
                    "threshold": self.threshold,
                    "direction": self.direction,
                    "metric": self.metric, "kind": self.kind,
                    "held_s": round(self._held_s, 3)}

        if self.state == SIGNAL_IDLE:
            if self._breaches(value):
                self.state = SIGNAL_HOLDING
                self._held_s = 0.0
                events.append(_event("armed"))
        elif self.state == SIGNAL_HOLDING:
            if self._disarms(value):
                self.state = SIGNAL_IDLE
                self._held_s = 0.0
                events.append(_event("cleared"))
                self._last_valid_t = now
                return events
            if not self._breaches(value):
                # hysteresis band: dip resets the hold clock but the
                # signal stays watching (no cleared event)
                self._held_s = 0.0
        if self.state == SIGNAL_HOLDING \
                and self._held_s >= self.min_hold_s \
                and self._breaches(value):
            self.state = SIGNAL_FIRED
            self.firings += 1
            self._fired_at = now
            events.append(_event("fired"))
        elif self.state == SIGNAL_FIRED:
            if self._disarms(value):
                self.state = SIGNAL_IDLE
                self._held_s = 0.0
                self._fired_at = None
                events.append(_event("cleared"))
        self._last_valid_t = now
        return events

    def report(self) -> Dict[str, Any]:
        return {"signal": self.name, "metric": self.metric,
                "kind": self.kind, "threshold": self.threshold,
                "direction": self.direction,
                "disarm_below": self.disarm_below,
                "disarm_above": self.disarm_above,
                "min_hold_s": self.min_hold_s,
                "window_s": self.window_s,
                "state": self.state, "firings": self.firings,
                "value": (round(self.value, 6)
                          if self.value is not None else None)}


class TimeSeriesRing:
    """Bounded ring of periodic registry snapshots + signal evaluation.

    ``source`` is anything with ``snapshot_state(prefix=...)`` — the
    process :data:`~nnstreamer_tpu.obs.metrics.REGISTRY` by default, or
    a federation :class:`~nnstreamer_tpu.obs.federation.MetricsCollector`
    (whose snapshots already carry ``origin`` labels, so one ring serves
    fleet-wide signals).

    ``capture(now=...)`` is the injectable-clock tick (tests drive it
    directly; production uses :class:`RingSampler`).  Capacity is
    ``retention_s / interval_s`` samples; windows larger than retention
    degrade to "data so far" exactly like the SLO evaluator's warm-up.
    """

    def __init__(self, source: Any = None, interval_s: float = 1.0,
                 retention_s: float = 120.0, prefix: str = "nns_",
                 registry=None) -> None:
        self.source = source if source is not None else REGISTRY
        self.interval_s = max(1e-3, float(interval_s))
        self.retention_s = max(self.interval_s, float(retention_s))
        self.prefix = prefix
        capacity = int(self.retention_s / self.interval_s) + 2
        self._lock = make_lock("obs.timeseries")
        #: [mono_s, wall_us, snapshot_state, flat-or-None] samples,
        #: oldest first; slot 3 memoizes flatten_state per capture (a
        #: capture is immutable, and re-flattening the whole retention
        #: window per dashboard refresh is O(retention × metrics))
        self._samples: "deque[list]" = deque(maxlen=max(8, capacity))
        self.captures = 0
        self.bus = SignalBus()
        self._signals: List[SustainedSignal] = []
        #: lazy gauges exporting each signal's state (registered on the
        #: metrics registry so scrapes/federation carry signal states);
        #: None source registries (collector facades) skip the export
        self._signal_gauges: List[Any] = []
        self._registry = registry if registry is not None else (
            self.source if hasattr(self.source, "gauge") else None)

    # -- signals -------------------------------------------------------------
    def add_signal(self, signal: SustainedSignal) -> SustainedSignal:
        with self._lock:
            self._signals.append(signal)
        reg = self._registry
        if reg is not None:
            g = reg.gauge(
                "nns_signal_state",
                fn=lambda s=signal: _SIGNAL_STATE_NUM[s.state],
                signal=signal.name)
            self._signal_gauges.append(g)
        return signal

    def signals(self) -> List[SustainedSignal]:
        with self._lock:
            return list(self._signals)

    def signal_report(self) -> Dict[str, Any]:
        """Verdict-ready signal summary (tools/soak.py embeds this)."""
        sigs = self.signals()
        return {"signals": [s.report() for s in sigs],
                "firings": sum(s.firings for s in sigs),
                "fired": sorted(s.name for s in sigs if s.firings),
                "events": list(self.bus.events)}

    # -- capture -------------------------------------------------------------
    def capture(self, now: Optional[float] = None,
                state: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Take one snapshot (``now`` injectable for tests), evaluate
        every signal against it, publish transitions, return the
        snapshot."""
        if now is None:
            now = mono_ns() / 1e9
        if state is None:
            # snapshot OUTSIDE the ring lock: gauge providers may take
            # element locks and must not nest under obs.timeseries
            state = self.source.snapshot_state(prefix=self.prefix)
        with self._lock:
            prev = self._samples[-1][2] if self._samples else None
            self._samples.append([now, wall_us(), state, None])
            self.captures += 1
            signals = list(self._signals)
        events: List[Dict[str, Any]] = []
        if signals:
            # adjacent-sample reset detection: a restart is only
            # visible across the boundary it happened at — a windowed
            # base-to-newest diff can net out positive right across it
            reset_keys = frozenset()
            if prev is not None:
                step = state_delta(state, prev)
                reset_keys = frozenset(
                    k for k, st in step.items() if st.get("reset"))
            deltas: Dict[float, Tuple[float, Dict[str, Any]]] = {}
            for sig in signals:
                # one windowed diff per DISTINCT window, not per
                # signal: the soak's standard watch list shares one
                # window, and a federated snapshot is thousands of keys
                if sig.window_s not in deltas:
                    deltas[sig.window_s] = self.window(sig.window_s,
                                                      now=now)
                span_s, delta = deltas[sig.window_s]
                events.extend(sig.evaluate(now, state, delta, span_s,
                                           reset_keys=reset_keys))
        for event in events:
            self.bus.publish(event)
        return state

    # -- windows -------------------------------------------------------------
    def _base_at_locked(self, now: float, window_s: float
                        ) -> Tuple[float, Dict[str, Any]]:
        cutoff = now - window_s
        base = self._samples[0]
        for sample in self._samples:
            if sample[0] <= cutoff:
                base = sample
            else:
                break
        return base[0], base[2]

    def window(self, window_s: float, now: Optional[float] = None
               ) -> Tuple[float, Dict[str, Any]]:
        """``(span_s, state_delta)`` between the newest sample and the
        newest sample at-or-before ``now - window_s`` (falls back to
        the oldest stored — warm-up covers "data so far")."""
        with self._lock:
            if not self._samples:
                return 0.0, {}
            t_new, newest = self._samples[-1][0], self._samples[-1][2]
            if now is None:
                now = t_new
            t_base, base = self._base_at_locked(now, window_s)
        if t_base >= t_new:
            return 0.0, state_delta(newest, newest)
        return t_new - t_base, state_delta(newest, base)

    def rate(self, family: str, window_s: float,
             match: Optional[str] = None) -> float:
        """Summed matching counter deltas / window span (events/s)."""
        span_s, delta = self.window(window_s)
        if span_s <= 0:
            return 0.0
        total = sum(st["value"] for key, st in delta.items()
                    if st.get("kind") == "counter"
                    and _key_match(key, family, match))
        return total / span_s

    def quantile(self, family: str, q: float, window_s: float,
                 match: Optional[str] = None) -> float:
        """Windowed histogram quantile (summed matching buckets)."""
        _span, delta = self.window(window_s)
        counts: Optional[List[int]] = None
        for key, st in delta.items():
            if st.get("kind") != "histogram" \
                    or not _key_match(key, family, match):
                continue
            if counts is None:
                counts = list(st["counts"])
            else:
                for i, c in enumerate(st["counts"]):
                    counts[i] += c
        if counts is None:
            return 0.0
        return quantile_from_counts(counts, q)

    def latest(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._samples[-1][2] if self._samples else None

    def series(self, key: str, window_s: Optional[float] = None
               ) -> List[Tuple[float, float]]:
        """``(t, value)`` points for ONE exact key: gauge/counter values
        per capture (counters stay cumulative — diff adjacent points
        for rates), histogram counts.  The dashboard's sparkline feed."""
        with self._lock:
            samples = list(self._samples)
        if not samples:
            return []
        cutoff = samples[-1][0] - window_s if window_s else None
        out: List[Tuple[float, float]] = []
        for sample in samples:
            t, snap = sample[0], sample[2]
            if cutoff is not None and t < cutoff:
                continue
            st = snap.get(key)
            if st is None:
                continue
            val = st.get("count") if st.get("kind") == "histogram" \
                else st.get("value")
            if val is not None:
                out.append((t, float(val)))
        return out

    def flat_samples(self, window_s: Optional[float] = None
                     ) -> List[Tuple[float, Dict[str, float]]]:
        """Per-capture flattened ``{key: float}`` maps — counters and
        gauges as values, histograms as ``_count`` plus rendered
        ``quantile`` keys (the same shape a /metrics scrape parses to,
        so the dashboard consumes rings and scrapes identically).
        Flattening is memoized per capture (slot 3 of the sample):
        only NEW captures pay the histogram-quantile work on a
        refresh, not the whole retention window."""
        with self._lock:
            samples = list(self._samples)
        if not samples:
            return []
        cutoff = samples[-1][0] - window_s if window_s else None
        out: List[Tuple[float, Dict[str, float]]] = []
        for sample in samples:
            t = sample[0]
            if cutoff is not None and t < cutoff:
                continue
            if sample[3] is None:
                sample[3] = flatten_state(sample[2])
            out.append((t, sample[3]))
        return out

    def close(self) -> None:
        reg = self._registry
        if reg is not None:
            for g in self._signal_gauges:
                reg.unregister(g)
        self._signal_gauges = []


def flatten_state(state: Dict[str, Any]) -> Dict[str, float]:
    """One snapshot_state map flattened to ``{key: float}`` in the
    shape a Prometheus scrape parses to: counters/gauges keep their
    key, a histogram ``name{l}`` yields ``name_count{l}``,
    ``name_sum{l}`` and ``name{l,quantile="0.5|0.95|0.99"}`` keys
    (quantiles from the cumulative bucket vector)."""
    out: Dict[str, float] = {}
    for key, st in state.items():
        kind = st.get("kind")
        if kind == "histogram":
            name, brace, labels = key.partition("{")
            inner = labels[:-1] if brace else ""
            sep = "," if inner else ""
            out[f"{name}_count{{{inner}}}" if brace
                else f"{name}_count"] = float(st["count"])
            out[f"{name}_sum{{{inner}}}" if brace
                else f"{name}_sum"] = float(st["total"])
            for q in (0.5, 0.95, 0.99):
                qkey = (f"{name}{{{inner}{sep}quantile=\"{q}\"}}"
                        if brace else f"{name}{{quantile=\"{q}\"}}")
                out[qkey] = (quantile_from_counts(st["counts"], q)
                             if st["count"] else 0.0)
        else:
            try:
                out[key] = float(st["value"])
            except (TypeError, ValueError):
                continue
    return out


class DeadlineLoop:
    """Generic absolute-deadline background loop: ``Event.wait`` pacing
    (drift-free; an overrunning pass realigns rather than bunching —
    the SLOMonitor discipline), every registered fn called per pass, a
    raising fn logged once and survived (a dead maintenance loop would
    read as a clean pass).  Shared engine of :class:`RingSampler` and
    the fleet's maintenance loop
    (:class:`~nnstreamer_tpu.fleet.pool.FleetLoop`)."""

    def __init__(self, fns, interval_s: float,
                 name: str = "nns-loop") -> None:
        self.fns = list(fns)
        self.interval_s = max(1e-3, float(interval_s))
        self.name = name
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "DeadlineLoop":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True,
                                            name=self.name)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10)

    def _loop(self) -> None:
        logged = False
        deadline = mono_ns() / 1e9 + self.interval_s
        while not self._stop.is_set():
            wait = deadline - mono_ns() / 1e9
            if wait > 0 and self._stop.wait(wait):
                return
            for fn in list(self.fns):
                try:
                    fn()
                except Exception:   # noqa: BLE001 — one bad pass
                    # (torn-down source, poisoned federated state)
                    # must not silently kill the loop for the rest of
                    # the run
                    if not logged:
                        logged = True
                        from ..utils.log import ml_logw

                        ml_logw("%s: pass failed (continuing)",
                                self.name, exc_info=True)
            now = mono_ns() / 1e9
            deadline += self.interval_s
            if deadline < now:      # overran: realign, don't bunch
                deadline = now + self.interval_s


class RingSampler(DeadlineLoop):
    """Background capture loop for a :class:`TimeSeriesRing` (one
    :class:`DeadlineLoop` pass = one ``ring.capture()``)."""

    def __init__(self, ring: TimeSeriesRing,
                 interval_s: Optional[float] = None) -> None:
        self.ring = ring
        super().__init__([ring.capture],
                         interval_s if interval_s is not None
                         else ring.interval_s,
                         name="nns-ts-sampler")

    def start(self) -> "RingSampler":
        super().start()
        return self

    def stop(self, final_capture: bool = True) -> None:
        super().stop()
        if final_capture:
            self.ring.capture()
