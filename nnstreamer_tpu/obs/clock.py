"""Observability clock helpers: the ONE place chain-path code reads time.

Two clocks matter to the tracing layer and they must not be mixed:

- ``mono_ns()`` — monotonic nanoseconds, the span/proctime clock.  Never
  jumps, meaningless across processes.
- ``wall_us()`` — unix-epoch microseconds, optionally NTP-aligned (the
  reference's ntputil role, utils/ntp.py).  Comparable across hosts
  once the peer offset is estimated; used only to anchor a process's
  monotonic timeline onto the shared wall clock.

``nnslint``'s ``wallclock-in-chain`` rule flags direct ``time.time()``
reads in chain paths and points here instead: a wall-clock read in a
span/latency computation silently breaks under NTP slew, while these
helpers keep the monotonic/wall split explicit.

:class:`OffsetEstimator` is the cross-process half: feed it
``(t_send_us, t_recv_us, peer_wall_us)`` request samples — the SNTP
midpoint method of utils/ntp.py applied to the query wire — and it
keeps the estimate from the minimum-RTT sample, which bounds the error
by that sample's asymmetry (at most rtt/2).
"""

from __future__ import annotations

import time
from typing import Optional


def mono_ns() -> int:
    """Monotonic nanoseconds — the span clock."""
    return time.monotonic_ns()


def wall_us() -> int:
    """Unix-epoch microseconds from the local clock.  (NTP alignment,
    when configured, happens at the element layer via
    ``utils.ntp.stream_origin_epoch_us`` — this helper stays cheap
    enough for per-reply stamping.)"""
    return time.time_ns() // 1000


class OffsetEstimator:
    """Peer wall-clock offset from request/reply stamps.

    One sample per query round trip: the client stamps ``t_send`` and
    ``t_recv`` (local wall µs) and the peer's reply carries its wall
    clock ``peer_wall_us`` at send time.  Assuming symmetric paths the
    peer clock read happened at the local midpoint, so::

        offset = peer_wall - (t_send + t_recv) / 2

    with error bounded by rtt/2.  The estimator keeps the sample with
    the smallest RTT seen (the classic NTP filter: congestion inflates
    RTT and asymmetry together, the fastest sample is the most
    symmetric one).
    """

    __slots__ = ("_offset_us", "_rtt_us", "samples")

    def __init__(self) -> None:
        self._offset_us: Optional[int] = None
        self._rtt_us: Optional[int] = None
        self.samples = 0

    def add_sample(self, t_send_us: int, t_recv_us: int,
                   peer_wall_us: int) -> None:
        if not peer_wall_us or t_recv_us < t_send_us:
            return
        rtt = t_recv_us - t_send_us
        if self._rtt_us is not None and rtt >= self._rtt_us:
            self.samples += 1
            return
        self._rtt_us = rtt
        self._offset_us = peer_wall_us - (t_send_us + t_recv_us) // 2
        self.samples += 1

    @property
    def offset_us(self) -> Optional[int]:
        """Peer wall clock minus local wall clock (µs); None until the
        first sample."""
        return self._offset_us

    @property
    def rtt_us(self) -> Optional[int]:
        return self._rtt_us

    def to_local_us(self, peer_wall_us: int) -> int:
        """Re-base a peer wall-clock stamp onto the local wall clock."""
        return peer_wall_us - (self._offset_us or 0)
