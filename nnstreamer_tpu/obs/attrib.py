"""Wait-state attribution: where every nanosecond of a frame went.

The ROADMAP's dominant open lever is the streaming-vs-batched MFU gap
(BENCH: ~0.0002 streaming vs 0.126 at batch 256 — the TPU is ~99.9 %
idle in per-frame mode), and the PR 5 span layer records per-element
proctime but cannot *say where the idle time goes*.  This module closes
that: it decomposes a traced frame's end-to-end wall time into a CLOSED
set of states, so the blame table for a streaming run names the exact
states a batching PR must shrink (StreamTensor, arXiv:2509.13694, makes
"keep the accelerator fed" the design objective; you cannot close a
feed gap you cannot measure).

**The state set** (closed — every elementary interval of a frame's
lifetime maps to exactly one):

========================  ==================================================
``source-pacing``         birth stamp → first element span (source thread
                          handoff, rate-limiter sleep, appsrc starvation)
``element-compute``       inside a non-device element's ``chain()``
``serialize``             wire framing / tensor decode (protocol.py
                          annotations)
``queue-wait``            inside a ``queue`` element's chain (full-queue
                          backpressure), the residency gap crossing a
                          queue thread boundary, a frame's residency
                          in a COLLECTING batch bucket (tensor_filter
                          micro-batch collect→dispatch, and the
                          cross-stream bucket behind a batching
                          tensor_query_serversrc — query/server.py),
                          or the fuse-xla double-buffer residency (a
                          finished frame held one slot so downstream's
                          D2H overlaps the next frame's compute —
                          pipeline/schedule.py)
``admission-wait``        server side: frame sat in the bounded incoming
                          queue before the serving pipeline picked it up
``wire``                  inside ``tensor_query_client``'s round trip,
                          minus everything the server's merged timeline
                          accounts for (transfer + protocol time)
``device-invoke``         jitted executable dispatch (_jitexec
                          annotation).  Under cross-stream batching the
                          window is SHARED: every frame of a bucket
                          annotates the same dispatch+materialization
                          interval — per-frame wall-clock truth, not a
                          1/n share.  Under fuse-xla the window covers
                          the WHOLE segment's single jitted
                          computation: the per-element serialize/
                          dispatch shares the lowering collapsed
``device-compile``        first-call JIT compilation (split from invoke)
``reorder-wait``          a finished result holding for stream order
                          (filter worker pool's strict-seq pusher)
``llm-prefill``           KV-cache prompt prefill: the full-prompt
                          forward that seeds a session's cache slot
                          (``nnstreamer_tpu/llm`` decode engine;
                          annotated under the REQUEST's trace id, so a
                          client timeline shows its prompt's one-time
                          cost apart from the per-token stream)
``llm-prefill-chunk``     one BOUNDED prefill chunk interleaved into the
                          decode loop (paged pool, ``prefill-chunk``
                          > 0): a long prompt's one-time cost shows as
                          many small slices time-sharing the decode
                          thread instead of one monolithic
                          ``llm-prefill`` stall — the interleave proof
                          the PhaseClock's share mirrors
``llm-decode``            one continuous-batching decode step's shared
                          window — like the cross-stream
                          ``device-invoke``, every resident sequence of
                          the step annotates the SAME interval under
                          its own trace id (per-token wall-clock truth,
                          not a 1/n share)
``sink``                  inside the sink element's chain
``dispatch``              inter-element scheduling glue (gaps not
                          explained by any state above)
``unattributed``          conservation residue (clock-resolution noise;
                          ~0 by construction)
========================  ==================================================

**Conservation is the correctness spine**: a frame's window
``[birth, last-span-end]`` is partitioned into elementary intervals,
each assigned exactly one state ("innermost span wins" — spans nest
because dataflow is synchronous within a streaming thread), so the
state durations sum to the end-to-end wall time exactly.  Tests pin
this on the interpreted and fused executors, locally and across a
query round trip.

**Cross-process refinement**: a ``tensor_query_client`` element span
covers send → reply.  Remote spans harvested over the T_TRACE piggyback
(re-based onto the local clock, pipeline/tracing.py) are matched into
the covering client span by containment and carve the server's states
out of it — what remains of the client span is genuine ``wire`` time.

**Device accounting**: :func:`estimate_jit_cost` extracts per-frame
FLOPs / bytes from the compiled executable (XLA cost analysis over the
negotiated shapes — the matmul/conv dims the caps pinned); together
with :func:`device_peaks` it feeds the live ``nns_mfu`` /
``nns_device_bytes_per_s`` / ``nns_device_mem_bytes`` gauges
(registered by ``tensor_filter`` for the jit-exec backend family) and
uses the SAME per-chip peak tables bench.py's batched-vs-streaming MFU
math imports — the two numbers cannot drift apart.

Nothing here runs on the dataflow hot path: attribution is a post-hoc
pass over a span ring, the gauges are lazy callables evaluated at
scrape time, and the cost analysis is computed once, lazily, at the
first scrape that wants it.
"""

from __future__ import annotations

import os
from bisect import bisect_left
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

#: the closed wait-state set (order = display order in blame tables)
STATES = (
    "source-pacing", "element-compute", "serialize", "queue-wait",
    "admission-wait", "wire", "device-invoke", "device-compile",
    "reorder-wait", "llm-prefill", "llm-prefill-chunk", "llm-decode",
    "sink", "dispatch", "unattributed",
)

#: span-name prefix for explicit state annotations
#: (``pipeline/tracing.py annotate()``)
STATE_PREFIX = "state:"
#: span-name prefix for the zero-duration birth marker a traced Source
#: appends per frame (the frame window's left edge)
SRC_PREFIX = "src:"

# -- per-chip peaks (the single source bench.py imports) ---------------------
#: bf16 peak FLOP/s per chip, keyed by device_kind substring; unknown
#: TPU kinds assume v5e, non-TPU platforms make no MFU claim (0.0).
PEAK_FLOPS: Dict[str, float] = {
    "v5e": 197e12, "v5litepod": 197e12, "v5p": 459e12,
    "v4": 275e12, "v6e": 918e12}
#: HBM bandwidth (bytes/s) per chip
PEAK_BW: Dict[str, float] = {
    "v5e": 819e9, "v5litepod": 819e9, "v5p": 2765e9,
    "v4": 1228e9, "v6e": 1640e9}


def _peak_lookup(device, table: Dict[str, float]) -> float:
    kind = (getattr(device, "device_kind", "") or "").lower()
    kind = kind.replace(" ", "")
    for key, peak in table.items():
        if key in kind:
            return peak
    plat = getattr(device, "platform", "")
    return table["v5e"] if plat == "tpu" else 0.0


def device_peaks(device) -> Tuple[float, float]:
    """(peak FLOP/s, peak HBM bytes/s) for ``device`` — the bench.py
    MFU denominators.  ``NNS_PEAK_FLOPS`` / ``NNS_PEAK_BW`` override
    (e.g. to compute an *assumed-chip* MFU on a CPU-only host; the
    override is an explicit assumption, surfaced by callers)."""
    env_f = os.environ.get("NNS_PEAK_FLOPS")
    env_b = os.environ.get("NNS_PEAK_BW")
    flops = float(env_f) if env_f else _peak_lookup(device, PEAK_FLOPS)
    bw = float(env_b) if env_b else _peak_lookup(device, PEAK_BW)
    return flops, bw


def estimate_jit_cost(fw) -> Tuple[float, float]:
    """Per-frame (flops, bytes_accessed) of a jit-exec backend's
    forward, from XLA cost analysis over the negotiated input shapes.
    Computed ONCE per backend instance (cached on the instance) and
    only when something asks (a gauge scrape, a profile report) — never
    on the dataflow path.  (0.0, 0.0) when the backend exposes no cost
    analysis: no MFU claim, mirroring bench.py's honesty rule."""
    if fw is None:   # element already stopped (fw attr cleared)
        return (0.0, 0.0)
    cached = getattr(fw, "_nns_cost_cache", None)
    if cached is not None:
        return cached
    if getattr(fw, "_annot_cold", False):
        # the executable cache is COLD (no warmup, or set_postprocess
        # just swapped the forward): computing cost now would run a
        # full XLA compile inside the scrape thread.  No claim yet —
        # uncached, so the first scrape after the executable warms
        # computes it for real.
        return (0.0, 0.0)
    flops = nbytes = 0.0
    try:
        import jax
        import numpy as np

        in_info, _ = fw.get_model_info()
        zeros = [np.zeros(i.np_shape, i.np_dtype) for i in in_info]
        # the backend's own jitted wrapper is preferred: its executable
        # cache was warmed at open, so lower().compile() here is a
        # cache hit, not a second multi-second XLA compile at scrape
        jitted = getattr(fw, "_jitted", None) or jax.jit(fw._forward_fn)
        cost = jitted.lower(
            fw._params_dev, *zeros).compile().cost_analysis()
        if isinstance(cost, list):
            cost = cost[0] if cost else {}
        cost = cost or {}
        flops = float(cost.get("flops", 0.0))
        nbytes = float(cost.get("bytes accessed", 0.0))
    except Exception:   # noqa: BLE001 — no cost model, no claim
        pass
    fw._nns_cost_cache = (flops, nbytes)
    return flops, nbytes


# -- span classification -----------------------------------------------------

def guess_element_state(name: str) -> str:
    """Heuristic element-name → state map, for span sets with no
    pipeline at hand (flight-recorder bundles, remote serving-pipeline
    spans piggybacked over the wire).  A live :class:`Profiler` passes
    an exact factory-derived map instead."""
    low = name.lower()
    if "queue" in low:
        return "queue-wait"
    if "query_client" in low or "query_cli" in low:
        return "wire"
    if "sink" in low:
        return "sink"
    return "element-compute"


def classify_span(name: str,
                  element_states: Optional[Dict[str, str]] = None) -> str:
    """State of one span: explicit ``state:*`` annotations win, then the
    exact element map, then the name heuristic."""
    if name.startswith(STATE_PREFIX):
        state = name[len(STATE_PREFIX):]
        return state if state in STATES else "element-compute"
    if element_states is not None:
        state = element_states.get(name)
        if state is not None:
            return state
    return guess_element_state(name)


# -- frame grouping ----------------------------------------------------------

class FrameSpans:
    """One frame's raw material: ``(name, start_ns, end_ns)`` triples
    plus the window ``[t0, t1]`` they will be attributed over."""

    __slots__ = ("seq", "t0", "t1", "spans")

    def __init__(self, seq: int) -> None:
        self.seq = seq
        self.t0: Optional[int] = None     # birth (src: marker), else min
        self.t1 = 0
        self.spans: List[Tuple[str, int, int]] = []


def group_frames(spans: Iterable[Any],
                 ambiguous: Optional[List[int]] = None
                 ) -> List[FrameSpans]:
    """Group local spans by buffer seq.  Spans with ``seq < 0``
    (annotations recorded off-frame, e.g. server admission-wait before
    the serving source stamped a seq) are matched afterwards by
    interval containment.  ``src:`` markers set the frame's left edge
    (birth); without one the first span's start is the edge
    (source-pacing then reads 0).

    Seqs are per-SOURCE: in a multi-source graph (mux/join) two
    sources both stamp seq 0, 1, 2… under one tracer, and their spans
    cannot be told apart by seq alone.  A seq that carries more than
    one ``src:`` birth marker is therefore AMBIGUOUS and dropped —
    loudly (appended to ``ambiguous`` when given, surfaced as
    ``ambiguous_frames`` in profile reports) rather than silently
    blending two unrelated frames into one corrupted window."""
    frames: Dict[int, FrameSpans] = {}
    loose: List[Tuple[str, int, int]] = []
    markers: Dict[int, int] = {}
    for s in spans:
        name, start, end = s.name, s.start_ns, s.start_ns + s.dur_ns
        if s.seq < 0:
            loose.append((name, start, end))
            continue
        fr = frames.get(s.seq)
        if fr is None:
            fr = frames[s.seq] = FrameSpans(s.seq)
        if name.startswith(SRC_PREFIX):
            markers[s.seq] = markers.get(s.seq, 0) + 1
            fr.t0 = start
        else:
            fr.spans.append((name, start, end))
        fr.t1 = max(fr.t1, end)
    for seq, n in markers.items():
        if n > 1:
            frames.pop(seq, None)
            if ambiguous is not None:
                ambiguous.append(seq)
    out = []
    for fr in frames.values():
        if not fr.spans:
            continue
        earliest = min(st for _, st, _ in fr.spans)
        if fr.t0 is None:
            fr.t0 = earliest
        else:
            # a span can START before the birth marker: a serving
            # pipeline's admission-wait covers arrival → dequeue, and
            # the serversrc only stamps birth after the dequeue.  The
            # frame's server-side lifetime begins at arrival.
            fr.t0 = min(fr.t0, earliest)
        out.append(fr)
    out.sort(key=lambda f: f.t0)
    if loose:
        loose.sort(key=lambda s: s[1])
        starts = [s[1] for s in loose]
        for fr in out:
            # loose spans whose start falls inside the frame window
            # belong to it (admission-wait starts at enqueue, which may
            # precede the window; clipped during attribution)
            for i in range(bisect_left(starts, fr.t0 - 5_000_000),
                           len(loose)):
                name, st, en = loose[i]
                if st >= fr.t1:
                    break
                if en > fr.t0 and st < fr.t1:
                    fr.spans.append((name, st, en))
    return out


def match_remote(frame: FrameSpans, wire_windows: List[Tuple[int, int]],
                 remote_sorted: List[Tuple[str, int, int]],
                 remote_starts: List[int]) -> None:
    """Carve a frame's wire windows with the server's re-based spans:
    a remote span whose midpoint falls inside a client round-trip span
    is that frame's server work (offset-estimation error stays below
    rtt/2, so midpoint containment is robust; spans are clipped to the
    window so conservation survives residual skew)."""
    for ws, we in wire_windows:
        lo = bisect_left(remote_starts, ws - (we - ws))
        for i in range(lo, len(remote_sorted)):
            name, st, en = remote_sorted[i]
            if st >= we:
                break
            mid = (st + en) // 2
            if ws <= mid < we:
                frame.spans.append((name, max(st, ws), min(en, we)))


# -- the attribution engine --------------------------------------------------

def _frame_sweep(frame: FrameSpans):
    """The ONE elementary-interval sweep both the blame attribution and
    the folded-stacks export consume (a second copy would let the two
    artifacts disagree about the same snapshot): yields ``(a, b,
    covering)`` per elementary interval, ``covering`` sorted outermost →
    innermost (empty = gap), plus the gap-classification inputs."""
    t0, t1 = frame.t0, frame.t1
    if t1 <= t0:
        return [], [], t1
    spans = [(name, max(st, t0), min(en, t1))
             for name, st, en in frame.spans if min(en, t1) > max(st, t0)]
    bounds = {t0, t1}
    for _, st, en in spans:
        bounds.add(st)
        bounds.add(en)
    edges = sorted(bounds)
    starts_sorted = sorted(spans, key=lambda s: s[1])
    first_start = starts_sorted[0][1] if spans else t1
    intervals = []
    for a, b in zip(edges, edges[1:]):
        covering = sorted((s for s in spans if s[1] <= a and s[2] >= b),
                          key=lambda s: (s[1], -s[2]))
        intervals.append((a, b, covering))
    return intervals, starts_sorted, first_start


def _gap_state(b: int, starts_sorted, first_start: int,
               transit: Optional[Dict[str, str]]) -> str:
    """State of an uncovered gap ending at ``b``: before the first span
    = source-pacing; otherwise the transit state of the edge being
    crossed (the next-starting span's element — queue-wait for elements
    fed by a queue), ``dispatch`` by default; a trailing gap past the
    last span (possible only through clock skew) = unattributed."""
    if b <= first_start:
        return "source-pacing"
    for name, st, _ in starts_sorted:
        if st >= b:
            if transit is not None:
                return transit.get(name, "dispatch")
            return "dispatch"
    return "unattributed"


def attribute_frame(frame: FrameSpans,
                    element_states: Optional[Dict[str, str]] = None,
                    transit: Optional[Dict[str, str]] = None
                    ) -> Dict[str, int]:
    """Partition ``[t0, t1]`` into per-state nanoseconds.

    Elementary intervals between span boundaries are assigned the state
    of the INNERMOST covering span (latest start wins — synchronous
    dataflow nests an element's span inside its caller's).  Uncovered
    gaps classify structurally via :func:`_gap_state`.  The partition
    is exact: state sums equal ``t1 - t0``."""
    out: Dict[str, int] = {}
    intervals, starts_sorted, first_start = _frame_sweep(frame)
    for a, b, covering in intervals:
        if covering:
            state = classify_span(covering[-1][0], element_states)
        else:
            state = _gap_state(b, starts_sorted, first_start, transit)
        out[state] = out.get(state, 0) + (b - a)
    return out


def attribute_frames(spans: Iterable[Any],
                     element_states: Optional[Dict[str, str]] = None,
                     transit: Optional[Dict[str, str]] = None,
                     remote_spans: Optional[Iterable[Any]] = None,
                     ambiguous: Optional[List[int]] = None
                     ) -> List[Tuple[FrameSpans, Dict[str, int]]]:
    """Group → (optionally) merge remote → attribute, per frame."""
    frames = group_frames(spans, ambiguous=ambiguous)
    if remote_spans:
        remote = sorted(((s.name, s.start_ns, s.start_ns + s.dur_ns)
                         for s in remote_spans), key=lambda s: s[1])
        rstarts = [s[1] for s in remote]
        for fr in frames:
            wire = [(st, en) for name, st, en in fr.spans
                    if classify_span(name, element_states) == "wire"
                    and not name.startswith(STATE_PREFIX)]
            if wire:
                match_remote(fr, wire, remote, rstarts)
    return [(fr, attribute_frame(fr, element_states, transit))
            for fr in frames]


# -- aggregation: the blame report -------------------------------------------

def _quantile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


def blame(attributed: List[Tuple[FrameSpans, Dict[str, int]]],
          top_n: int = 6) -> Dict[str, Any]:
    """Aggregate per-frame attributions into the blame report:

    - ``states``: per-state totals, share of summed e2e, mean per
      frame, and ``dominant_frames`` — the critical-path count (frames
      whose single largest state this is: the per-frame dominant edge);
    - ``top``: the top-N states by share — the rows a perf PR must
      shrink;
    - ``conservation``: attributed share of e2e (≈ 100 % by
      construction; the correctness spine the tests pin);
    - ``e2e_us``: end-to-end wall-time distribution over frames.
    """
    n = len(attributed)
    if n == 0:
        return {"frames": 0, "states": {}, "top": [],
                "conservation": {"attributed_pct": 0.0}, "e2e_us": {}}
    e2e = sorted((fr.t1 - fr.t0) / 1e3 for fr, _ in attributed)
    total_e2e_ns = sum(fr.t1 - fr.t0 for fr, _ in attributed)
    totals: Dict[str, int] = {}
    dominant: Dict[str, int] = {}
    for _, states in attributed:
        for state, ns in states.items():
            totals[state] = totals.get(state, 0) + ns
        if states:
            top = max(states.items(), key=lambda kv: kv[1])[0]
            dominant[top] = dominant.get(top, 0) + 1
    states_out = {}
    for state in STATES:
        ns = totals.get(state, 0)
        if ns == 0 and state not in dominant:
            continue
        states_out[state] = {
            "total_ms": round(ns / 1e6, 3),
            "pct": round(100.0 * ns / max(1, total_e2e_ns), 2),
            "per_frame_us": round(ns / 1e3 / n, 2),
            "dominant_frames": dominant.get(state, 0),
        }
    ranked = sorted(states_out.items(), key=lambda kv: -kv[1]["pct"])
    attributed_ns = sum(ns for s, ns in totals.items()
                        if s != "unattributed")
    return {
        "frames": n,
        "e2e_us": {"mean": round(sum(e2e) / n, 1),
                   "p50": round(_quantile(e2e, 0.50), 1),
                   "p95": round(_quantile(e2e, 0.95), 1),
                   "max": round(e2e[-1], 1)},
        "states": states_out,
        "top": [[s, row["pct"]] for s, row in ranked[:top_n]],
        "conservation": {
            "attributed_pct": round(
                100.0 * attributed_ns / max(1, total_e2e_ns), 2),
            "unattributed_pct": round(
                100.0 * totals.get("unattributed", 0)
                / max(1, total_e2e_ns), 2)},
    }


def blame_from_spans(spans: Iterable[Any],
                     element_states: Optional[Dict[str, str]] = None,
                     transit: Optional[Dict[str, str]] = None,
                     remote_spans: Optional[Iterable[Any]] = None,
                     top_n: int = 6) -> Dict[str, Any]:
    """One-call convenience over raw span iterables (flight-recorder
    bundles, soak verdicts): heuristic classification unless exact maps
    are supplied."""
    return blame(attribute_frames(spans, element_states, transit,
                                  remote_spans), top_n=top_n)


def queueing_evidence(metrics_report: Dict[str, Any]) -> Dict[str, Any]:
    """Cross-check against PR 6's coordinated-omission split: the
    divergence of ``nns_slo_latency_us`` (scheduled-arrival latency)
    from ``nns_query_service_us`` (send→reply) IS queueing.  Returns
    the two p99s and their gap when both histograms are present in a
    registry report — the blame table's ``queue-wait``/``wire`` rows
    should explain this gap."""
    slo = service = None
    for key, row in metrics_report.items():
        if not isinstance(row, dict):
            continue
        if key.startswith("nns_slo_latency_us") and row.get("count"):
            slo = row
        elif key.startswith("nns_query_service_us") and row.get("count"):
            service = row
    if slo is None or service is None:
        return {}
    return {"slo_latency_p99_us": slo.get("p99"),
            "service_p99_us": service.get("p99"),
            "queueing_p99_us": round(
                (slo.get("p99") or 0.0) - (service.get("p99") or 0.0), 2)}


def folded_stacks(frames: List[FrameSpans],
                  element_states: Optional[Dict[str, str]] = None,
                  transit: Optional[Dict[str, str]] = None
                  ) -> Dict[str, int]:
    """Folded-stack lines (``a;b;leaf weight_us`` semantics, the
    flamegraph.pl / speedscope input format): each elementary interval
    contributes its covering-span nesting path, leaf-annotated with the
    attributed state; gaps contribute their wait state as a root frame.
    Returns ``{stack_line: total_us}``."""
    out: Dict[str, int] = {}
    for fr in frames:
        intervals, starts_sorted, first_start = _frame_sweep(fr)
        for a, b, covering in intervals:
            if covering:
                parts = [name for name, _, _ in covering]
                state = classify_span(parts[-1], element_states)
                if not parts[-1].startswith(STATE_PREFIX):
                    parts.append(state)
            else:
                parts = [_gap_state(b, starts_sorted, first_start,
                                    transit)]
            line = ";".join(parts)
            out[line] = out.get(line, 0) + (b - a) // 1000
    return {k: v for k, v in out.items() if v > 0}


# -- occupancy ---------------------------------------------------------------

def busy_fraction(spans: Iterable[Any], name: str, now_ns: int,
                  window_ns: int) -> float:
    """Fraction of ``[now - window, now]`` during which element
    ``name`` had a span active (interval union, so nested or
    overlapping spans never exceed 1.0) — the per-element occupancy
    gauge's math.  A device feeding at 0.001 occupancy on the filter
    row is the measured idle-gap evidence.

    A filter running worker or micro-batch mode records its real work
    under ``<name>:invoke`` spans on worker threads — ``chain()`` only
    covers the submit — so those count as the element's busy time too;
    without them the async configurations the profiler targets would
    read near-zero occupancy while saturated."""
    lo = now_ns - window_ns
    names = (name, name + ":invoke")
    ivs = sorted((max(s.start_ns, lo), min(s.start_ns + s.dur_ns, now_ns))
                 for s in spans if s.name in names
                 and s.start_ns + s.dur_ns > lo and s.start_ns < now_ns)
    busy = 0
    cur_s = cur_e = None
    for s, e in ivs:
        if e <= s:
            continue
        if cur_e is None or s > cur_e:
            if cur_e is not None:
                busy += cur_e - cur_s
            cur_s, cur_e = s, e
        elif e > cur_e:
            cur_e = e
    if cur_e is not None:
        busy += cur_e - cur_s
    return min(1.0, busy / max(1, window_ns))


class RingSnapshotCache:
    """Short-TTL shared snapshot of a span ring, so one /metrics scrape
    evaluating N occupancy gauges copies the (up to 65536-entry) ring
    ONCE under the ring lock instead of N times — N full copies per
    scrape would inject periodic append stalls into the very streaming
    threads being profiled."""

    __slots__ = ("tracer", "ttl_ns", "_at_ns", "_spans")

    def __init__(self, tracer, ttl_s: float = 0.25) -> None:
        self.tracer = tracer
        self.ttl_ns = int(ttl_s * 1e9)
        self._at_ns = 0
        self._spans: List[Any] = []

    def get(self, now_ns: int) -> List[Any]:
        if now_ns - self._at_ns > self.ttl_ns:
            ring = self.tracer.ring
            self._spans = ring.snapshot() if ring is not None else []
            self._at_ns = now_ns
        return self._spans


def make_occupancy_fn(tracer, name: str, window_s: float = 5.0,
                      cache: Optional[RingSnapshotCache] = None
                      ) -> Callable[[], float]:
    """Lazy-gauge provider: busy fraction of element ``name`` over the
    trailing window, computed from the tracer's span ring AT SCRAPE
    TIME (obs/metrics.py pull contract — zero per-buffer cost).  Pass
    one shared :class:`RingSnapshotCache` for a pipeline's whole gauge
    set so a scrape snapshots the ring once."""
    window_ns = int(window_s * 1e9)

    def _fn() -> float:
        import time as _t

        now = _t.monotonic_ns()
        if cache is not None:
            spans = cache.get(now)
        else:
            ring = tracer.ring
            if ring is None:
                return 0.0
            spans = ring.snapshot()
        return busy_fraction(spans, name, now, window_ns)

    return _fn
