"""Per-buffer timeline spans, wire trace-context, Chrome trace export.

A **span** is one element's processing of one buffer: ``(element,
thread id, start mono-ns, duration ns, buffer seq, trace id)``.  Spans
land in a bounded :class:`SpanRing` (overwrite-oldest, so a long run
keeps the tail instead of OOMing) and export as Chrome ``trace_event``
JSON — ``chrome://tracing`` / Perfetto render streaming threads, queue
handoffs and filter-worker overlap directly.

The **trace context** is the compact distributed-tracing triple that
rides the query wire header (query/protocol.py rev 4): ``trace_id``
names the whole distributed trace, ``span_id`` the sender-side parent
span, ``origin_us`` the source stamp (sender wall clock µs) that makes
cross-process interlatency computable after clock-offset estimation
(obs/clock.py).  The same triple rides the MQTT header's pad region and
a magic'd trailer on the shm-ring payload, so every among-device path
PR 1-2 built propagates the trace.
"""

from __future__ import annotations

import os
import struct
from typing import Any, Dict, Iterable, List, NamedTuple, Optional


def new_trace_id() -> int:
    """Random nonzero 63-bit trace id (0 = "no trace" on the wire)."""
    while True:
        tid = int.from_bytes(os.urandom(8), "little") & 0x7FFFFFFFFFFFFFFF
        if tid:
            return tid


class TraceContext(NamedTuple):
    """Compact wire trace-context (all zeros = absent)."""

    trace_id: int = 0
    span_id: int = 0
    #: source stamp: sender wall clock µs when the buffer was born
    origin_us: int = 0

    def __bool__(self) -> bool:
        return self.trace_id != 0


class Span(NamedTuple):
    name: str
    tid: int           # thread ident (or remote pseudo-tid)
    start_ns: int      # mono_ns in THIS process's timeline
    dur_ns: int
    seq: int           # buffer sequence number (-1 = unknown)
    trace_id: int


#: shm/mqtt trace trailer: magic + trace_id + span_id + origin_us
_TRAILER = struct.Struct("<4sQQq")
_TRAILER_MAGIC = b"TRCE"
TRAILER_SIZE = _TRAILER.size


def pack_ctx_trailer(ctx: TraceContext) -> bytes:
    """Trace context as a self-identifying 28-byte blob, appended after
    the tensor payload on transports whose framing has no header room
    (shm ring slots) or spare pad (the MQTT 1024-byte header).
    ``decode_tensors`` reads exactly the declared tensors, so a trailer
    after them is invisible to context-unaware consumers."""
    return _TRAILER.pack(_TRAILER_MAGIC, ctx.trace_id, ctx.span_id,
                         ctx.origin_us)


def unpack_ctx_trailer(payload, end: Optional[int] = None
                       ) -> Optional[TraceContext]:
    """Trace context from the trailing bytes of ``payload`` (bytes or
    memoryview), or None when no trailer is present."""
    n = len(payload) if end is None else end
    if n < TRAILER_SIZE:
        return None
    raw = bytes(payload[n - TRAILER_SIZE:n])
    if raw[:4] != _TRAILER_MAGIC:
        return None
    _, trace_id, span_id, origin_us = _TRAILER.unpack(raw)
    return TraceContext(trace_id, span_id, origin_us)


class SpanRing:
    """Bounded per-buffer span store (overwrite-oldest).

    Appends come from every streaming thread; a plain lock per append
    is acceptable because span recording is opt-in (``Tracer(spans=
    True)``) — the untraced and metrics-only modes never construct one.
    """

    def __init__(self, capacity: int = 65536) -> None:
        from ..analysis.sanitizer import make_lock

        self.capacity = max(16, int(capacity))
        self._buf: List[Optional[Span]] = [None] * self.capacity
        self._next = 0          # total appends (mod capacity = slot)
        self._lock = make_lock("obs.ring")

    def append(self, span: Span) -> None:
        with self._lock:
            self._buf[self._next % self.capacity] = span
            self._next += 1

    @property
    def dropped(self) -> int:
        """Spans overwritten because the ring wrapped."""
        return max(0, self._next - self.capacity)

    def snapshot(self) -> List[Span]:
        """Spans in append order (oldest surviving first)."""
        return self.snapshot_since(0)[0]

    def snapshot_since(self, start: int) -> "tuple[List[Span], int]":
        """Spans with append index >= ``start`` (clamped to what the
        ring still holds), plus the next cursor — the incremental-drain
        primitive for the T_TRACE wire piggyback."""
        with self._lock:
            n = self._next
            lo = max(int(start), n - self.capacity, 0)
            out = []
            for i in range(lo, n):
                s = self._buf[i % self.capacity]
                if s is not None:
                    out.append(s)
            return out, n


def chrome_trace_events(spans: Iterable[Span], pid: int = 1,
                        process_name: str = "pipeline",
                        offset_ns: int = 0) -> List[Dict[str, Any]]:
    """Chrome ``trace_event`` dicts ("X" complete events + process/thread
    metadata) for one process's spans.  ``offset_ns`` shifts remote
    timelines onto the local one after clock-offset estimation."""
    events: List[Dict[str, Any]] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": process_name},
    }]
    tids = set()
    for s in spans:
        tids.add(s.tid)
        events.append({
            "name": s.name, "cat": "element", "ph": "X", "pid": pid,
            "tid": s.tid, "ts": (s.start_ns + offset_ns) / 1000.0,
            "dur": s.dur_ns / 1000.0,
            "args": {"seq": s.seq, "trace_id": f"{s.trace_id:x}"},
        })
    for tid in sorted(tids):
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": f"thread-{tid}"}})
    # Perfetto tolerates any order, but a monotone stream makes the
    # export diff-able and lets tests assert ordering cheaply
    events.sort(key=lambda e: (e["ph"] != "M", e.get("ts", 0.0)))
    return events
