"""The utilization profiler: one object that answers "where did the
time go" for a playing pipeline.

:class:`Profiler` composes the pieces the rest of the obs layer
provides — a span-recording tracer (pipeline/tracing.py), the
wait-state attribution engine (obs/attrib.py), the metrics registry —
into the profile surfaces:

- a **blame report** (``report()``) attributing every frame's
  end-to-end wall time to the closed state set, with the per-frame
  dominant-edge (critical path) counts, conservation evidence and the
  PR 6 queueing cross-check;
- a rendered **blame table** (``blame_table()`` — what
  ``launch.py --profile`` prints);
- a **folded-stacks flamegraph** file (``export_folded()`` —
  flamegraph.pl / speedscope input) and the Chrome trace
  (``export_chrome()``, delegated to the tracer so merged remote
  processes ride along);
- per-element **occupancy gauges** (``nns_element_occupancy`` —
  busy-fraction over a trailing window, computed from the span ring at
  scrape time).

State maps are derived from the live pipeline graph (element factory →
state; sink-pad feeders → gap transit states), so classification is
exact — the heuristic name fallback in attrib.py is only for span sets
with no pipeline at hand (flight-recorder bundles, remote spans).

Cost discipline: constructing a Profiler enables span recording (that
is the point — profiling IS the opt-in); everything else is post-hoc
or scrape-time.  ``close()`` unregisters the gauges; an untraced
pipeline never constructs one and keeps zero obs references in its
compiled plans (tools/hotpath_bench.py ``--stage profile`` gate).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from . import attrib
from .metrics import REGISTRY, Gauge


def pipeline_maps(pipeline) -> Tuple[Dict[str, str], Dict[str, str]]:
    """(element→state, element→gap-transit-state) maps from the graph.

    Span-time states: ``queue`` elements' chain time is queue-wait
    (a blocking put on a full queue IS queueing), ``tensor_query_client``
    is wire (refined by merged server spans), sinks are sink, all other
    elements are element-compute (their annotations carve out
    serialize/device states).  Transit states classify the *uncovered
    gap* a frame spends crossing into an element: an element fed by a
    queue gets its residency gap attributed as queue-wait; every other
    edge is dispatch glue."""
    states: Dict[str, str] = {}
    transit: Dict[str, str] = {}
    for el in pipeline.elements:
        fac = getattr(el, "FACTORY", "") or ""
        if fac == "queue":
            states[el.name] = "queue-wait"
        elif fac == "tensor_query_client":
            states[el.name] = "wire"
        elif not el.src_pads:
            states[el.name] = "sink"
        else:
            states[el.name] = "element-compute"
        if fac == "tensor_filter":
            # worker-pool invoke spans record under "<name>:invoke"
            states[el.name + ":invoke"] = "element-compute"
        for pad in el.sink_pads:
            peer = pad.peer
            if peer is not None and \
                    getattr(peer.element, "FACTORY", "") == "queue":
                transit[el.name] = "queue-wait"
    return states, transit


class Profiler:
    """Attach to a pipeline, run the workload, read the blame.

    Usage::

        p = parse_launch("videotestsrc num-buffers=600 ! ... ! tensor_sink")
        prof = Profiler(p)          # enables span tracing on p
        p.run()
        report = prof.report()
        print(prof.blame_table(report))
        prof.export_folded("flame.folded")
        prof.close()
    """

    def __init__(self, pipeline, tracer=None,
                 occupancy_window_s: float = 5.0,
                 register_gauges: bool = True) -> None:
        self.pipeline = pipeline
        tracer = tracer or pipeline.tracer
        if tracer is None or tracer.ring is None:
            tracer = pipeline.enable_tracing(spans=True)
        self.tracer = tracer
        self.element_states, self.transit = pipeline_maps(pipeline)
        self._gauges: List[Gauge] = []
        self._frames_cache: Optional[List[Any]] = None
        if register_gauges:
            pname = getattr(pipeline, "name", "") or ""
            # one shared ring snapshot per scrape across every gauge —
            # N elements must not mean N full ring copies under the
            # append lock per /metrics pull
            snap_cache = attrib.RingSnapshotCache(tracer)
            for el in pipeline.elements:
                self._gauges.append(REGISTRY.register(Gauge(
                    "nns_element_occupancy",
                    {"element": el.name, "pipeline": pname},
                    fn=attrib.make_occupancy_fn(tracer, el.name,
                                                occupancy_window_s,
                                                cache=snap_cache))))

    def close(self) -> None:
        for g in self._gauges:
            REGISTRY.unregister(g)
        self._gauges = []

    # -- attribution ---------------------------------------------------------
    def _remote_spans(self) -> List[Any]:
        out: List[Any] = []
        for spans in getattr(self.tracer, "_remote", {}).values():
            out.extend(spans)
        return out

    def attributed(self, ambiguous: Optional[List[int]] = None,
                   spans: Optional[List[Any]] = None):
        """Per-frame ``(FrameSpans, {state: ns})`` over the current
        span ring, remote (server) spans carved into the wire windows."""
        if spans is None:
            spans = self.tracer.ring.snapshot()
        return attrib.attribute_frames(
            spans, self.element_states, self.transit,
            remote_spans=self._remote_spans(), ambiguous=ambiguous)

    def report(self, metrics_report: Optional[Dict[str, Any]] = None,
               top_n: int = 8) -> Dict[str, Any]:
        """The profile artifact body: blame + occupancy + device
        accounting + queueing cross-check."""
        ambiguous: List[int] = []
        spans = self.tracer.ring.snapshot()
        attributed = self.attributed(ambiguous=ambiguous, spans=spans)
        # keep the attributed frame set: export_folded reuses it so the
        # committed flame.folded describes the SAME span snapshot as
        # profile.json (and the O(frames x spans) pass runs once)
        self._frames_cache = [fr for fr, _ in attributed]
        out: Dict[str, Any] = {
            "blame": attrib.blame(attributed, top_n=top_n)}
        if ambiguous:
            # multi-source graphs stamp per-source seqs: colliding
            # frames are EXCLUDED from the blame, not blended (see
            # attrib.group_frames) — this is how many were dropped
            out["ambiguous_frames"] = len(ambiguous)
        if self.tracer.ring.dropped:
            # the ring wrapped: the blame covers the TAIL of the run
            out["spans_dropped"] = self.tracer.ring.dropped
        # per-element busy time over the SAME ring snapshot the blame
        # used — the tracer's proctime counters cover the whole run,
        # and mixing windows after a ring wrap would inflate shares
        # past 100%.  occupancy = interval-union busy / the snapshot's
        # wall window: the filter row IS the device-feed idleness.
        elements: Dict[str, Any] = {}
        el_spans = [s for s in spans
                    if not s.name.startswith(attrib.STATE_PREFIX)
                    and not s.name.startswith(attrib.SRC_PREFIX)]
        if el_spans:
            w0 = min(s.start_ns for s in el_spans)
            w1 = max(s.start_ns + s.dur_ns for s in el_spans)
            window_ns = max(1, w1 - w0)
            for name in sorted({s.name for s in el_spans
                                if not s.name.endswith(":invoke")}):
                frac = attrib.busy_fraction(el_spans, name, w1,
                                            window_ns)
                elements[name] = {
                    "busy_ms": round(frac * window_ns / 1e6, 3),
                    "occupancy": round(frac, 4),
                    "buffers": sum(s.name == name for s in el_spans)}
            out["window_ms"] = round(window_ns / 1e6, 3)
        out["elements"] = elements
        if metrics_report is None:
            metrics_report = REGISTRY.report()
        evidence = attrib.queueing_evidence(metrics_report)
        if evidence:
            out["queueing_evidence"] = evidence
        # segment-lowering evidence next to the blame: which elements
        # run fused, at which tier, and whether the fuse-xla executable
        # cache is serving warm (steady-state compiles are the
        # recompile-churn smell the hotpath gate pins).  A profile of a
        # fuse-xla pipeline is judged BY this pairing: the collapsed
        # per-element shares in the blame table, the plan rows naming
        # what collapsed them.
        planner = getattr(self.pipeline, "planner", None)
        if planner is not None:
            plans = planner.plans()
            if plans:
                out["plans"] = plans
                out["lowering"] = getattr(self.pipeline, "fuse_tier",
                                          "python")
        # device gauges read RAW (snapshot_state), not through the
        # report's 4-decimal rounding: a streaming MFU of 5e-6 is the
        # entire point of the measurement, not a rounding victim
        device = {}
        for k, row in REGISTRY.snapshot_state(prefix="nns_").items():
            if k.startswith(("nns_mfu", "nns_device_",
                             "nns_element_occupancy")) \
                    and row.get("kind") == "gauge":
                device[k] = float(f"{row['value']:.6g}")
        if device:
            out["device"] = device
        return out

    # -- rendering -----------------------------------------------------------
    def blame_table(self, report: Optional[Dict[str, Any]] = None) -> str:
        report = report or self.report()
        blame = report["blame"]
        lines = [
            f"profile: {blame['frames']} frames, e2e mean "
            f"{blame['e2e_us'].get('mean', 0)} us (p50 "
            f"{blame['e2e_us'].get('p50', 0)}, p95 "
            f"{blame['e2e_us'].get('p95', 0)}), attributed "
            f"{blame['conservation']['attributed_pct']}%",
            f"{'state':<18} {'pct':>7} {'us/frame':>10} "
            f"{'total_ms':>10} {'dominant':>9}"]
        for state, _pct in blame["top"]:
            row = blame["states"][state]
            lines.append(
                f"{state:<18} {row['pct']:>6.2f}% "
                f"{row['per_frame_us']:>10.1f} {row['total_ms']:>10.2f} "
                f"{row['dominant_frames']:>9}")
        ev = report.get("queueing_evidence")
        if ev:
            lines.append(
                f"queueing evidence: slo p99 {ev['slo_latency_p99_us']} "
                f"us vs service p99 {ev['service_p99_us']} us "
                f"(queueing {ev['queueing_p99_us']} us)")
        mfu = next((v for k, v in report.get("device", {}).items()
                    if k.startswith("nns_mfu")), None)
        if mfu is not None:
            lines.append(f"nns_mfu: {mfu}")
        return "\n".join(lines)

    def export_folded(self, path: str) -> None:
        """Folded stacks (``flamegraph.pl`` / speedscope input): one
        ``stack weight_us`` line per distinct nesting path.  Reuses the
        frame set of the last :meth:`report` when one exists, so the
        two artifacts describe one snapshot."""
        frames = self._frames_cache
        if frames is None:
            frames = [fr for fr, _ in self.attributed()]
        folded = attrib.folded_stacks(frames, self.element_states,
                                      self.transit)
        with open(path, "w", encoding="utf-8") as fh:
            for line, us in sorted(folded.items(), key=lambda kv: -kv[1]):
                fh.write(f"{line} {us}\n")

    def export_chrome(self, path: str) -> None:
        self.tracer.export_chrome(path)


def compact_blame(blame: Dict[str, Any]) -> Dict[str, Any]:
    """THE compact attribution-summary shape (``attribution`` blocks in
    bench rows, soak verdicts, flight-recorder bundles — and the shape
    tools/perf_diff.py reads state deltas from).  One constructor so
    every producer and consumer stays in sync."""
    if not blame.get("frames"):
        return {}
    return {"frames": blame["frames"],
            "e2e_us": blame["e2e_us"],
            "top": blame["top"],
            "states": {s: row["pct"]
                       for s, row in blame["states"].items()},
            "attributed_pct":
                blame["conservation"]["attributed_pct"]}


def attribution_block(tracer, top_n: int = 5) -> Dict[str, Any]:
    """Compact attribution summary from a bare span-recording tracer
    (no pipeline at hand — soak verdicts, flight-recorder bundles):
    heuristic element classification, remote spans merged.  Empty dict
    when the tracer records no spans."""
    if tracer is None or getattr(tracer, "ring", None) is None:
        return {}
    remote: List[Any] = []
    for spans in getattr(tracer, "_remote", {}).values():
        remote.extend(spans)
    report = attrib.blame_from_spans(tracer.ring.snapshot(),
                                     remote_spans=remote, top_n=top_n)
    return compact_blame(report)
