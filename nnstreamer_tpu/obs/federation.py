"""Cross-process metric federation: N worker registries, ONE endpoint.

Every :class:`~nnstreamer_tpu.obs.metrics.MetricsRegistry` is
process-local, so an N-worker serving fleet is N blind spots: no single
``/metrics`` scrape sees the fleet's queue depths, no single
``/healthz`` answers "is the fleet ready", and fleet-wide sustained
signals ("occupancy high across workers for 30 s") are not computable
anywhere.  This module closes that gap with a push model riding the
existing query wire:

- :class:`MetricsPublisher` (worker side) periodically snapshots its
  registry and pushes *deltas* — the keys whose state changed since the
  last push, each carrying its CUMULATIVE state — as ``T_METRICS``
  messages (query/protocol.py).  Cumulative-state deltas make the
  stream self-healing: a lost or duplicated push never corrupts
  counts, and a reconnect (collector restart, network blip) resends
  the FULL state.  Publisher wall stamps ride each push; the publisher
  estimates the collector's clock offset over ``T_PING``/``T_PONG``
  (the PR 5 :class:`~nnstreamer_tpu.obs.clock.OffsetEstimator`) and
  sends it along, so the collector re-bases every origin's timeline
  onto its own wall clock.

- :class:`MetricsCollector` (collector side) merges origin states under
  ``origin="host:pid"`` labels, drops duplicate/out-of-order pushes by
  sequence number, evicts origins that stop pushing
  (``stale_after_s``), and re-renders ONE federated ``/metrics``
  (its ``render_prometheus`` makes it a drop-in registry for
  ``start_metrics_server``) plus a worst-of-origins health source for
  ``/healthz``.  Its ``snapshot_state`` facade means a
  :class:`~nnstreamer_tpu.obs.timeseries.TimeSeriesRing` — and
  therefore every :class:`SustainedSignal` — runs unchanged over the
  federated view.

- :class:`CollectorServer` is the standalone wire endpoint (accept
  loop over the protocol framing); alternatively any
  :class:`~nnstreamer_tpu.query.server.QueryServer` accepts
  ``T_METRICS`` on its existing data connections once a collector is
  attached (``server.collector = collector``) — workers already
  connected to a front-end push telemetry on the same socket.

StreamTensor's (arXiv:2509.13694) framing applies: the dataflow plane
and its utilization evidence travel together — the same wire that
carries tensors carries the proof of how well it is being used.
"""

from __future__ import annotations

import json
import os
import socket
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..analysis.sanitizer import make_lock
from .clock import OffsetEstimator, mono_ns, wall_us
from .metrics import (REGISTRY, MetricsRegistry, _escape_label_value,
                      quantile_from_counts)

#: default staleness horizon: an origin silent for this long is evicted
#: from the federated view (its worker died without a BYE, or its
#: publisher wedged — either way its last-known gauges are lies now)
DEFAULT_STALE_AFTER_S = 15.0
#: every Nth push is a FULL snapshot even without a reconnect, so keys
#: that disappeared from a worker's registry (unregistered gauges) age
#: out of the federated view within full_every x interval
DEFAULT_FULL_EVERY = 15

_HEALTH_SEVERITY = {"starting": 0, "serving": 1, "degraded": 2,
                    "draining": 3}


def origin_id() -> str:
    """This process's origin key: ``host:pid``."""
    return f"{socket.gethostname()}:{os.getpid()}"


def _valid_entry(entry: Any) -> bool:
    """Shape check for one pushed metric entry: exactly what every
    downstream consumer (state_delta, quantile_from_counts, the
    federated renderer) will read must be present and numeric."""
    if not isinstance(entry, dict):
        return False
    kind = entry.get("kind")
    if kind in ("counter", "gauge"):
        return isinstance(entry.get("value"), (int, float))
    if kind == "histogram":
        counts = entry.get("counts")
        return (isinstance(entry.get("count"), int)
                and isinstance(entry.get("total"), (int, float))
                and isinstance(counts, (list, tuple))
                and all(isinstance(c, int) for c in counts))
    return False


def _with_origin(key: str, origin: str) -> str:
    """Inject ``origin="…"`` into a ``name{labels}`` metric key (the
    federation label: one merged namespace, per-process series)."""
    esc = _escape_label_value(origin)
    name, brace, labels = key.partition("{")
    if not brace:
        return f'{name}{{origin="{esc}"}}'
    inner = labels[:-1]
    sep = "," if inner else ""
    return f'{name}{{{inner}{sep}origin="{esc}"}}'


class _Origin:
    """One worker's federated state."""

    __slots__ = ("key", "state", "last_seq", "epoch", "prev_epochs",
                 "last_push_mono", "last_push_wall_us", "offset_us",
                 "health", "meta", "pushes", "rejected")

    def __init__(self, key: str) -> None:
        self.key = key
        self.state: Dict[str, Any] = {}
        self.last_seq = -1
        self.epoch = None
        #: superseded incarnations (bounded): once an epoch has been
        #: replaced, NOTHING from it merges again — a dying worker's
        #: straggler full push must not resurrect dead state
        self.prev_epochs: "deque" = deque(maxlen=8)
        self.last_push_mono = 0.0
        self.last_push_wall_us = 0
        self.offset_us = 0
        self.health = "starting"
        self.meta: Dict[str, Any] = {}
        self.pushes = 0
        self.rejected = 0


class MetricsCollector:
    """Merges per-origin registry snapshots into one federated view.

    The LOCAL process's registry participates as its own origin (the
    collector host is usually also a worker — the soak's demo server,
    a fleet front-end), snapshotted live at read time so local gauges
    are never stale.

    Registry facade: ``render_prometheus()`` / ``report()`` /
    ``snapshot_state(prefix=)`` make the collector a drop-in for the
    httpd endpoint and the time-series ring; ``health()`` is the
    worst-of-origins readiness source (a stale-but-not-yet-evicted
    origin reads ``degraded`` — silence is not health).
    """

    def __init__(self, registry: Optional[MetricsRegistry] = REGISTRY,
                 local_origin: Optional[str] = None,
                 stale_after_s: float = DEFAULT_STALE_AFTER_S) -> None:
        self.registry = registry
        self.local_origin = local_origin or origin_id()
        self.stale_after_s = float(stale_after_s)
        self._lock = make_lock("obs.federation")
        self._origins: Dict[str, _Origin] = {}

    # -- ingest --------------------------------------------------------------
    def ingest(self, payload: Any, now: Optional[float] = None) -> bool:
        """One ``T_METRICS`` payload (bytes/str JSON or a dict); returns
        False when rejected (malformed, duplicate or out-of-order seq).

        Ordering discipline: pushes carry ``(epoch, seq)`` — ``epoch``
        identifies one publisher incarnation, ``seq`` its push counter.
        Within an epoch, only strictly increasing seqs merge (each
        key's pushed state is CUMULATIVE, so dropping a duplicate or a
        late-arriving older push loses nothing — the newer push already
        superseded it).  A new epoch (worker restarted) or a ``full``
        push REPLACES the origin's state outright — key tombstoning for
        free, and the counter-reset that comes with a restart is then
        caught downstream by ``state_delta``'s reset marking."""
        if isinstance(payload, (bytes, bytearray, memoryview)):
            try:
                payload = json.loads(bytes(payload).decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                return False
        if not isinstance(payload, dict):
            return False
        key = payload.get("origin")
        state = payload.get("state")
        if not key or not isinstance(key, str) \
                or not isinstance(state, dict):
            return False
        try:
            seq = int(payload.get("seq", 0))
            offset_us = int(payload.get("offset_us") or 0)
            wall_us_in = int(payload.get("wall_us") or 0)
        except (TypeError, ValueError):
            # malformed-but-valid-JSON push (buggy or version-skewed
            # publisher): reject it — it must never raise out of the
            # serving connection's reader thread
            return False
        # drop malformed metric entries rather than merging them: one
        # poisoned value (a None gauge, a histogram missing its bucket
        # vector) would crash every later snapshot_state / state_delta
        # / render consumer (the ring sampler, the federated scrape) —
        # and a dead fleet view is a worse failure than a dropped key
        state = {k: v for k, v in state.items()
                 if isinstance(k, str) and _valid_entry(v)}
        epoch = payload.get("epoch")
        full = bool(payload.get("full"))
        if now is None:
            now = mono_ns() / 1e9
        with self._lock:
            org = self._origins.get(key)
            if org is None:
                org = self._origins[key] = _Origin(key)
            elif epoch == org.epoch and seq <= org.last_seq:
                # duplicate or out-of-order within one incarnation:
                # the newer (already-merged) push supersedes it
                org.rejected += 1
                return False
            elif epoch != org.epoch and \
                    (not full or epoch in org.prev_epochs):
                # a LATE push from a superseded incarnation (its
                # SIGTERM final full push landing after the restart's
                # first push), or an epoch change carried by a DELTA
                # (a genuinely new incarnation always opens full —
                # reconnect forces one): merging either would
                # resurrect stale state and flip epoch tracking back
                org.rejected += 1
                return False
            if full or epoch != org.epoch:
                org.state = dict(state)
            else:
                org.state.update(state)
            if epoch != org.epoch and org.epoch is not None:
                org.prev_epochs.append(org.epoch)
            org.epoch = epoch
            org.last_seq = seq
            org.last_push_mono = now
            org.offset_us = offset_us
            # re-base the publisher's wall stamp onto OUR wall clock
            # (offset_us = collector_wall - publisher_wall, estimated
            # publisher-side over T_PING round trips)
            org.last_push_wall_us = wall_us_in + org.offset_us
            org.health = str(payload.get("health") or "serving")
            org.meta = {k: payload[k] for k in ("host", "pid")
                        if k in payload}
            org.pushes += 1
        return True

    def evict_stale(self, now: Optional[float] = None) -> List[str]:
        """Drop origins silent past ``stale_after_s``; returns the
        evicted origin keys."""
        if now is None:
            now = mono_ns() / 1e9
        horizon = now - self.stale_after_s
        with self._lock:
            victims = [k for k, o in self._origins.items()
                       if o.last_push_mono < horizon]
            for k in victims:
                del self._origins[k]
        return victims

    def forget(self, origin: str) -> bool:
        with self._lock:
            return self._origins.pop(origin, None) is not None

    # -- read side -----------------------------------------------------------
    def _origin_states(self, now: Optional[float] = None
                       ) -> List[Tuple[str, Dict[str, Any]]]:
        """(origin, state) pairs: evict first, then remote origins +
        the live local registry snapshot."""
        self.evict_stale(now)
        with self._lock:
            out = [(o.key, o.state) for o in self._origins.values()]
        if self.registry is not None:
            out.append((self.local_origin,
                        self.registry.snapshot_state()))
        return out

    def origins(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Per-origin summary rows (dashboard / flight-recorder feed)."""
        if now is None:
            now = mono_ns() / 1e9
        self.evict_stale(now)
        with self._lock:
            rows = [{"origin": o.key, "health": o.health,
                     "age_s": round(now - o.last_push_mono, 3),
                     "pushes": o.pushes, "rejected": o.rejected,
                     "last_seq": o.last_seq,
                     "offset_us": o.offset_us,
                     "keys": len(o.state), **o.meta}
                    for o in self._origins.values()]
        if self.registry is not None:
            rows.append({"origin": self.local_origin, "health": "local",
                         "age_s": 0.0, "pushes": 0, "rejected": 0,
                         "last_seq": -1, "offset_us": 0,
                         "keys": None, "pid": os.getpid(),
                         "host": socket.gethostname()})
        return rows

    def snapshot_state(self, prefix: str = "") -> Dict[str, Any]:
        """Federated ``snapshot_state``: every origin's keys, origin
        label injected — the time-series ring's substrate, so sustained
        signals evaluate over the whole fleet."""
        out: Dict[str, Any] = {}
        for origin, state in self._origin_states():
            for key, st in state.items():
                if prefix and not key.startswith(prefix):
                    continue
                out[_with_origin(key, origin)] = st
        return out

    def health(self) -> str:
        """Worst-of readiness across origins (the /healthz source):
        remote states as pushed, the local registry's own health rides
        the process's other sources; a stale origin inside the eviction
        horizon reads ``degraded`` — a worker that stopped pushing is
        not known-good."""
        now = mono_ns() / 1e9
        self.evict_stale(now)
        worst = "starting"
        with self._lock:
            for o in self._origins.values():
                state = o.health
                if state not in _HEALTH_SEVERITY:
                    continue
                if now - o.last_push_mono > max(2.0,
                                                self.stale_after_s / 3):
                    state = max(state, "degraded",
                                key=lambda s: _HEALTH_SEVERITY[s])
                if _HEALTH_SEVERITY[state] > _HEALTH_SEVERITY[worst]:
                    worst = state
        return worst

    def register_health(self, label: str = "federation") -> int:
        """Contribute the worst-of-origins state to this process's
        ``/healthz`` (obs/httpd.py health sources); returns the token
        for ``unregister_health_source``.  A federated endpoint then
        answers 503 when ANY worker reports draining/degraded or goes
        silent — load balancers see the fleet, not just this
        process."""
        from .httpd import register_health_source

        return register_health_source(self.health, label=label)

    def report(self) -> Dict[str, Any]:
        """JSON-friendly federated snapshot (flight-recorder timeline
        rows): per-origin flattened metrics + summary."""
        from .timeseries import flatten_state

        out: Dict[str, Any] = {}
        for origin, state in self._origin_states():
            flat = flatten_state(state)
            out[origin] = {k: round(v, 4) for k, v in flat.items()}
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition of the FEDERATED view: every
        origin's series under its origin label, one family header per
        name (the httpd endpoint serves this when handed the collector
        as its registry)."""
        lines: List[str] = []
        seen = set()

        def family(name: str, kind: str) -> None:
            if name not in seen:
                seen.add(name)
                lines.append(f"# HELP {name} federated {kind}")
                lines.append(f"# TYPE {name} {kind}")

        for origin, state in self._origin_states():
            for key, st in sorted(state.items()):
                kind = st.get("kind")
                fkey = _with_origin(key, origin)
                name = key.partition("{")[0]
                if kind == "counter":
                    family(name, "counter")
                    lines.append(f"{fkey} {st['value']}")
                elif kind == "gauge":
                    v = st["value"]
                    family(name, "gauge")
                    val = "NaN" if v != v else repr(round(float(v), 6))
                    lines.append(f"{fkey} {val}")
                elif kind == "histogram":
                    family(name, "summary")
                    fname, brace, rest = fkey.partition("{")
                    inner = rest[:-1] if brace else ""
                    sep = "," if inner else ""
                    for q in (0.5, 0.95, 0.99):
                        qv = (quantile_from_counts(st["counts"], q)
                              if st["count"] else 0.0)
                        lines.append(
                            f'{fname}{{{inner}{sep}quantile="{q}"}} '
                            f"{round(qv, 3)}")
                    lines.append(f"{fname}_sum{{{inner}}} "
                                 f"{round(st['total'], 3)}")
                    lines.append(f"{fname}_count{{{inner}}} "
                                 f"{st['count']}")
        return "\n".join(lines) + "\n"


class CollectorServer:
    """Standalone wire endpoint for metric pushes: accepts protocol
    connections, ingests ``T_METRICS``, answers ``T_PING`` with a
    wall-stamped ``T_PONG`` (the publisher's clock-offset samples) and
    ``T_HELLO`` with an empty hello.  Everything else is ignored — this
    is a telemetry drain, not a serving plane."""

    def __init__(self, collector: MetricsCollector,
                 host: str = "127.0.0.1", port: int = 0) -> None:
        self.collector = collector
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self.host = host
        self.port = self._sock.getsockname()[1]
        self._sock.listen(16)
        self._stop = threading.Event()
        self._lock = make_lock("obs.federation")
        self._conns: List[socket.socket] = []
        self._accept = threading.Thread(target=self._accept_loop,
                                        daemon=True,
                                        name="nns-collector-accept")
        self._accept.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            with self._lock:
                self._conns.append(conn)
            threading.Thread(target=self._conn_loop, args=(conn,),
                             daemon=True,
                             name="nns-collector-conn").start()

    def _conn_loop(self, conn: socket.socket) -> None:
        from ..query.protocol import (Message, T_BYE, T_HELLO,
                                      T_METRICS, T_PING, T_PONG,
                                      recv_msg, send_msg)

        send_lock = make_lock("query.send")
        try:
            while not self._stop.is_set():
                try:
                    msg = recv_msg(conn)
                except (TimeoutError, ValueError):
                    break
                if msg is None or msg.type == T_BYE:
                    break
                if msg.type == T_METRICS:
                    self.collector.ingest(msg.payload)
                elif msg.type == T_PING:
                    # wall-stamped pong: the publisher's offset sample
                    # (obs/clock.py NTP-midpoint over the push wire)
                    with send_lock:
                        send_msg(conn, Message(T_PONG, seq=msg.seq,
                                               epoch_us=wall_us(),
                                               payload=msg.payload))
                elif msg.type == T_HELLO:
                    with send_lock:
                        send_msg(conn, Message(T_HELLO))
        except OSError:
            pass
        finally:
            from ..query.protocol import shutdown_close

            with self._lock:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    pass
            shutdown_close(conn)

    def close(self) -> None:
        from ..query.protocol import shutdown_close

        self._stop.set()
        # shutdown-then-close on the LISTENER too: a plain close does
        # not wake the blocked accept() on every platform, and a live
        # accept keeps squatting on the port so a restarted collector
        # cannot rebind (the protocol.shutdown_close lesson applied to
        # the listening socket)
        shutdown_close(self._sock)
        with self._lock:
            conns, self._conns = self._conns, []
        for conn in conns:
            shutdown_close(conn)


class MetricsPublisher:
    """Worker-side push loop: one background thread snapshotting the
    local registry every ``interval_s`` and pushing changed keys to the
    collector as ``T_METRICS``.

    Delta discipline: each push carries only the keys whose state
    changed since the last SENT snapshot — but every key's state is
    CUMULATIVE, so the stream tolerates loss and reordering by
    construction.  A reconnect (collector restarted, link dropped)
    resends FULL state; so does every ``full_every``-th push, bounding
    how long a deleted key survives in the federated view.

    Clock discipline: the publisher pings the collector every
    ``offset_every`` pushes and keeps the min-RTT offset estimate
    (obs/clock.py); each push carries the estimate so the collector
    re-bases this origin's wall stamps without trusting cross-host
    clock agreement.
    """

    def __init__(self, host: str, port: int,
                 registry: MetricsRegistry = REGISTRY,
                 interval_s: float = 1.0, prefix: str = "nns_",
                 origin: Optional[str] = None,
                 full_every: int = DEFAULT_FULL_EVERY,
                 offset_every: int = 5,
                 health_fn=None) -> None:
        from .span import new_trace_id

        self.host, self.port = host, int(port)
        self.registry = registry
        self.interval_s = max(1e-3, float(interval_s))
        self.prefix = prefix
        self.origin = origin or origin_id()
        self.full_every = max(1, int(full_every))
        self.offset_every = max(1, int(offset_every))
        #: one publisher incarnation: a restarted worker's pushes must
        #: not be sequenced against its previous life's
        self.epoch = new_trace_id()
        self.health_fn = health_fn
        self.offset = OffsetEstimator()
        self.pushes = 0
        self.send_errors = 0
        self._seq = 0
        self._last_sent: Dict[str, Any] = {}
        self._sock: Optional[socket.socket] = None
        self._send_lock = make_lock("query.send")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- wire ----------------------------------------------------------------
    def _connect(self) -> None:
        from ..query.protocol import (Message, T_HELLO, create_connection,
                                      recv_msg, send_msg)

        sock = create_connection((self.host, self.port), timeout=5.0)
        sock.settimeout(5.0)
        send_msg(sock, Message(T_HELLO))
        # drain the hello reply (the collector answers; a QueryServer
        # answers with its caps string — either way it is not ours to
        # interpret).  Sequential request/reply: nothing unsolicited
        # ever comes back on this wire, so no reader thread is needed.
        recv_msg(sock)
        self._sock = sock
        self._last_sent = {}        # force a FULL push after (re)connect

    def _disconnect(self) -> None:
        from ..query.protocol import shutdown_close

        sock, self._sock = self._sock, None
        if sock is not None:
            shutdown_close(sock)

    def _sample_offset(self) -> None:
        """One T_PING round trip → one offset sample (min-RTT filtered
        by the estimator).  Failures are ignored: offset refinement
        must never cost a push."""
        from ..query.protocol import (Message, T_PING, T_PONG, recv_msg,
                                      send_msg)

        sock = self._sock
        if sock is None:
            return
        self._seq += 1
        seq = self._seq
        try:
            t_send = wall_us()
            with self._send_lock:
                send_msg(sock, Message(T_PING, seq=seq))
            while True:
                msg = recv_msg(sock)
                if msg is None:
                    return
                if msg.type == T_PONG and msg.seq == seq:
                    if msg.epoch_us:
                        self.offset.add_sample(t_send, wall_us(),
                                               msg.epoch_us)
                    return
        except (TimeoutError, OSError, ValueError):
            return

    def push(self) -> bool:
        """One push now (the loop's tick; callable directly in tests).
        Returns True when a payload went out."""
        from ..query.protocol import Message, T_METRICS, send_msg

        state = self.registry.snapshot_state(prefix=self.prefix)
        full = (self._sock is None or not self._last_sent
                or self.pushes % self.full_every == 0)
        if self._sock is None:
            try:
                self._connect()
            except (OSError, ValueError):
                self.send_errors += 1
                return False
            full = True
        if full:
            changed = state
        else:
            # an all-quiet registry still pushes an EMPTY delta: the
            # push is the liveness heartbeat, so collector staleness
            # means a dead worker, never an idle one
            changed = {k: v for k, v in state.items()
                       if self._last_sent.get(k) != v}
        self._seq += 1
        health = "serving"
        if self.health_fn is not None:
            try:
                health = str(self.health_fn())
            except Exception:   # noqa: BLE001 — dead provider
                pass
        payload = {"origin": self.origin,
                   "host": socket.gethostname(), "pid": os.getpid(),
                   "epoch": self.epoch, "seq": self._seq,
                   "full": full, "wall_us": wall_us(),
                   "offset_us": self.offset.offset_us,
                   "health": health, "state": changed}
        try:
            with self._send_lock:
                send_msg(self._sock, Message(
                    T_METRICS, seq=self._seq, epoch_us=wall_us(),
                    payload=json.dumps(payload).encode()))
        except (OSError, AttributeError):
            self.send_errors += 1
            self._disconnect()      # next tick reconnects + resends full
            return False
        self._last_sent = state
        self.pushes += 1
        if self.pushes == 1 or self.pushes % self.offset_every == 0:
            self._sample_offset()
        return True

    # -- loop ----------------------------------------------------------------
    def start(self) -> "MetricsPublisher":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True,
                                            name="nns-metrics-push")
            self._thread.start()
        return self

    def stop(self, final_push: bool = True) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10)
        if final_push and self._sock is not None:
            self.push()
        self._disconnect()

    def _loop(self) -> None:
        deadline = mono_ns() / 1e9 + self.interval_s
        while not self._stop.is_set():
            wait = deadline - mono_ns() / 1e9
            if wait > 0 and self._stop.wait(wait):
                return
            self.push()
            now = mono_ns() / 1e9
            deadline += self.interval_s
            if deadline < now:      # overran (reconnect): realign
                deadline = now + self.interval_s
