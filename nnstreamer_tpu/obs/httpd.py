"""Live metrics endpoint: ``GET /metrics`` in Prometheus text format.

Pull-based by design (the zero-hot-path-cost contract of obs/metrics.py
only holds when evaluation happens at scrape time): a tiny
``ThreadingHTTPServer`` on ``NNS_METRICS_PORT`` serves

- ``/metrics`` — Prometheus text exposition of the process registry
  (plus the PR 1 resilience counters), and
- ``/healthz`` — ``200 ok`` liveness.

Activation is explicit (``start_metrics_server``) or environmental
(``maybe_start_from_env`` — called once from ``Pipeline.play()`` and
``launch.py``): an unset ``NNS_METRICS_PORT`` costs one cached getenv
per process, nothing per pipeline.
"""

from __future__ import annotations

import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..analysis.sanitizer import make_lock
from .metrics import REGISTRY, MetricsRegistry

_STATE_LOCK = make_lock("leaf")
_SERVER: Optional[ThreadingHTTPServer] = None
_ENV_TRIED = False


class _Handler(BaseHTTPRequestHandler):
    registry: MetricsRegistry = REGISTRY

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        if self.path.split("?", 1)[0] == "/metrics":
            body = self.registry.render_prometheus().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif self.path == "/healthz":
            body, ctype = b"ok\n", "text/plain"
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # silence per-scrape stderr spam
        pass


def start_metrics_server(port: int, host: str = "127.0.0.1",
                         registry: MetricsRegistry = REGISTRY
                         ) -> ThreadingHTTPServer:
    """Start the endpoint on ``host:port`` (port 0 = ephemeral; read the
    bound port from ``server.server_address[1]``).  Idempotent per
    process: a second call returns the running server."""
    global _SERVER
    with _STATE_LOCK:
        if _SERVER is not None:
            return _SERVER
        handler = type("_BoundHandler", (_Handler,),
                       {"registry": registry})
        server = ThreadingHTTPServer((host, int(port)), handler)
        server.daemon_threads = True
        threading.Thread(target=server.serve_forever, daemon=True,
                         name="nns-metrics").start()
        _SERVER = server
        return server


def stop_metrics_server() -> None:
    global _SERVER
    with _STATE_LOCK:
        server, _SERVER = _SERVER, None
    if server is not None:
        server.shutdown()
        server.server_close()


def maybe_start_from_env() -> Optional[ThreadingHTTPServer]:
    """Start the endpoint when ``NNS_METRICS_PORT`` is set (once per
    process; a malformed value logs and disables rather than killing
    the pipeline that happened to trigger the first check)."""
    global _ENV_TRIED
    if _ENV_TRIED:
        return _SERVER
    _ENV_TRIED = True
    raw = os.environ.get("NNS_METRICS_PORT")
    if not raw:
        return None
    try:
        return start_metrics_server(int(raw))
    except (ValueError, OSError) as exc:
        from ..utils.log import ml_logw

        ml_logw("NNS_METRICS_PORT=%r: metrics endpoint disabled (%s)",
                raw, exc)
        return None
