"""Live metrics endpoint: ``GET /metrics`` in Prometheus text format.

Pull-based by design (the zero-hot-path-cost contract of obs/metrics.py
only holds when evaluation happens at scrape time): a tiny
``ThreadingHTTPServer`` on ``NNS_METRICS_PORT`` serves

- ``/metrics`` — Prometheus text exposition of the process registry
  (plus the PR 1 resilience counters), and
- ``/healthz`` — readiness JSON: the worst state across every
  registered health source (``starting < serving < degraded <
  draining``), HTTP 200 while ``starting``/``serving`` and 503 while
  ``degraded``/``draining`` — load-balancer-pollable without parsing.

Health sources are callables returning a state string; pipelines
register one at ``play()`` (lifecycle + per-element degradation — a
``tensor_query_client`` with an OPEN circuit breaker reports
``degraded``) and unregister at ``stop()``.  With no sources the
process reports ``starting``: up, serving nothing yet.

Activation is explicit (``start_metrics_server``) or environmental
(``maybe_start_from_env`` — called once from ``Pipeline.play()`` and
``launch.py``): an unset ``NNS_METRICS_PORT`` costs one cached getenv
per process, nothing per pipeline.
"""

from __future__ import annotations

import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..analysis.sanitizer import make_lock
from .metrics import REGISTRY, MetricsRegistry

_STATE_LOCK = make_lock("leaf")
_SERVER: Optional[ThreadingHTTPServer] = None
_ENV_TRIED = False

#: readiness states ordered by severity: /healthz reports the WORST
#: state any registered source claims (a process serving one healthy
#: and one degraded pipeline is degraded)
HEALTH_STATES = ("starting", "serving", "degraded", "draining")
_SEVERITY = {s: i for i, s in enumerate(HEALTH_STATES)}
#: states the endpoint answers 200 for; degraded/draining answer 503
#: so a load balancer drains traffic without parsing the JSON body
_READY_STATES = frozenset({"starting", "serving"})

_HEALTH_LOCK = make_lock("leaf")
_HEALTH_SOURCES: dict = {}      # token -> (label, provider callable)
_HEALTH_NEXT = 1


def register_health_source(provider, label: str = "") -> int:
    """Register a readiness provider (a callable returning one of
    :data:`HEALTH_STATES`); returns a token for unregistration.
    Pipelines call this from ``play()``."""
    global _HEALTH_NEXT
    with _HEALTH_LOCK:
        token = _HEALTH_NEXT
        _HEALTH_NEXT += 1
        _HEALTH_SOURCES[token] = (label or f"source-{token}", provider)
        return token


def unregister_health_source(token: int) -> None:
    with _HEALTH_LOCK:
        _HEALTH_SOURCES.pop(token, None)


def health_report() -> dict:
    """Aggregate readiness: worst state across sources, plus the
    per-source breakdown.  A provider that raises (element stopped
    under the scrape) is skipped rather than failing the probe."""
    with _HEALTH_LOCK:
        sources = list(_HEALTH_SOURCES.values())
    per = {}
    worst = "starting"
    for label, provider in sources:
        try:
            state = str(provider())
        except Exception:   # noqa: BLE001 — dead provider, skip
            continue
        if state not in _SEVERITY:
            continue
        per[label] = state
        if _SEVERITY[state] > _SEVERITY[worst]:
            worst = state
    return {"state": worst, "ready": worst in _READY_STATES,
            "sources": per}


class _Handler(BaseHTTPRequestHandler):
    registry: MetricsRegistry = REGISTRY

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        import json as _json

        status = 200
        path = self.path.split("?", 1)[0]
        # the render walks live element state (lazy gauge providers)
        # that a concurrent Pipeline.stop() is tearing down: dead
        # providers yield dropped samples (obs/metrics.py Gauge
        # contract), and anything that still escapes answers 503 —
        # a scrape must never 500 or leak an exception into this
        # serving thread
        try:
            if path == "/metrics":
                body = self.registry.render_prometheus().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/healthz":
                report = health_report()
                body = (_json.dumps(report) + "\n").encode()
                ctype = "application/json"
                if not report["ready"]:
                    status = 503
            else:
                self.send_error(404)
                return
        except Exception:   # noqa: BLE001 — teardown race backstop
            status = 503
            body = b"scrape raced teardown; retry\n"
            ctype = "text/plain; charset=utf-8"
        try:
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except OSError:
            pass   # client hung up mid-reply: nothing to serve

    def log_message(self, fmt, *args):  # silence per-scrape stderr spam
        pass


def start_metrics_server(port: int, host: str = "127.0.0.1",
                         registry: MetricsRegistry = REGISTRY
                         ) -> ThreadingHTTPServer:
    """Start the endpoint on ``host:port``.  Idempotent per process: a
    second call returns the running server.

    ``port=0`` binds an EPHEMERAL port — the multi-process contract: a
    fleet of worker processes sharing one machine (or one test suite)
    must never collide on a fixed 9090-style port.  The chosen port is
    logged, readable via :func:`bound_metrics_port` (and
    ``server.server_address[1]``), and exported as
    ``NNS_METRICS_BOUND_PORT`` so subprocess tooling can discover it
    from the environment."""
    global _SERVER
    with _STATE_LOCK:
        if _SERVER is not None:
            return _SERVER
        handler = type("_BoundHandler", (_Handler,),
                       {"registry": registry})
        server = ThreadingHTTPServer((host, int(port)), handler)
        server.daemon_threads = True
        threading.Thread(target=server.serve_forever, daemon=True,
                         name="nns-metrics").start()
        _SERVER = server
        bound = server.server_address[1]
        os.environ["NNS_METRICS_BOUND_PORT"] = str(bound)
        if int(port) == 0:
            from ..utils.log import logger

            logger.info("metrics endpoint on ephemeral port: "
                        "http://%s:%d/metrics", host, bound)
        return server


def bound_metrics_port() -> Optional[int]:
    """Port the running metrics endpoint is bound to (the answer to
    "where did port 0 land"); None when no endpoint is running."""
    with _STATE_LOCK:
        if _SERVER is None:
            return None
        return _SERVER.server_address[1]


def stop_metrics_server() -> None:
    global _SERVER
    with _STATE_LOCK:
        server, _SERVER = _SERVER, None
    if server is not None:
        os.environ.pop("NNS_METRICS_BOUND_PORT", None)
        server.shutdown()
        server.server_close()


def maybe_start_from_env() -> Optional[ThreadingHTTPServer]:
    """Start the endpoint when ``NNS_METRICS_PORT`` is set (once per
    process; a malformed value logs and disables rather than killing
    the pipeline that happened to trigger the first check)."""
    global _ENV_TRIED
    if _ENV_TRIED:
        return _SERVER
    _ENV_TRIED = True
    raw = os.environ.get("NNS_METRICS_PORT")
    if not raw:
        return None
    try:
        return start_metrics_server(int(raw))
    except (ValueError, OSError) as exc:
        from ..utils.log import ml_logw

        ml_logw("NNS_METRICS_PORT=%r: metrics endpoint disabled (%s)",
                raw, exc)
        return None
