"""Continuous-batching decode engine: one padded device invoke per step
over every resident sequence, flash-path prefill, conserved wall-time
attribution.

The decode loop's economics are the PR 9 bucket economics applied to
token generation: B single-token GEMV steps become ONE GEMM-shaped
``decode_step_pooled`` invoke, and the padded-lane quantization
(:meth:`~nnstreamer_tpu.filter.backends._jitexec.JitExecMixin.pad_rows`)
bounds the executable set so sequences joining and leaving the bucket
every step NEVER recompile — the same discipline that made partial
cross-stream buckets free.  Prompt prefill runs the full-sequence
forward (``models/streamformer_lm.prefill_kv``) with the Pallas
flash-attention path length-gated in, so long prompts never materialize
(T, T) scores; prompt lengths quantize to powers of two for the same
bounded-executables reason.

**Attribution is conserved by construction**: the engine's
:class:`PhaseClock` assigns every nanosecond of the decode thread's
life to exactly one of ``idle`` / ``admit`` / ``prefill`` / ``decode``
/ ``egress`` (state transitions stamp a monotonic clock; there are no
gaps and no overlaps), so the profiler's prefill-vs-decode shares sum
to 100 % of loop wall time exactly — the PR 8 conservation spine,
applied to the one thread the frame-window partitioner cannot see
inside.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..analysis import compileledger
from ..analysis.compileledger import compile_budget
from ..filter.backends._jitexec import JitExecMixin
from .pool import KVCachePool, Session

#: PhaseClock states (closed set; every decode-thread nanosecond lands
#: in exactly one).  ``llm-prefill-chunk`` is the paged tier's
#: interleaved-prefill share: time spent advancing ONE bounded prompt
#: chunk between decode steps — its presence (and the decode share
#: staying alive next to it) is the proof a long prompt no longer
#: stalls resident token streams.  ``compile`` is the cold-executable
#: share: a dispatch whose executable was not yet in this engine's warm
#: set charges its whole device call here instead of decode/prefill, so
#: a mid-serve XLA compile is NAMED in the attribution (and the
#: zero-steady-state-compiles discipline shows up as this share being
#: exactly the warmup, never growing after).
PHASES = ("idle", "admit", "prefill", "llm-prefill-chunk", "decode",
          "egress", "compile")


def quantize_pages(n: int, table_max: int) -> int:
    """Padded block-table WIDTH for a paged dispatch: next power of two
    capped at ``table_max`` (= ``max_seq // page_size``) — the
    ``quantize_prompt`` discipline applied to the page axis, so block
    tables of every length land on a bounded ``log2``-ish executable
    set.  Padding entries point at the scratch page."""
    cap = max(1, int(table_max))
    q = 1
    while q < n:
        q <<= 1
    return min(q, cap)


def _cfg_key(cfg) -> tuple:
    # arity is fixed: cfg is a frozen StreamFormerConfig dataclass, so
    # the field set is a compile-time constant of the class
    # nnsjit: allow(unbounded-signature)
    return tuple(sorted((k, str(v)) for k, v in vars(cfg).items()))


#: process-wide jitted-callable memo: engines with the SAME model
#: config share one jit object per executable family (jax re-
#: specializes per operand shape inside it), so a test suite or fleet
#: restarting elements does not re-trace identical math.  Per-engine
#: ``compiles`` counters still count warm-set entries per engine — the
#: bounded-executables evidence is unchanged.
_EXEC_MEMO: Dict[tuple, Any] = {}


def _memo_jit(key: tuple, make):
    fn = _EXEC_MEMO.get(key)
    if fn is None:
        fn = make()
        _EXEC_MEMO[key] = fn
    return fn


class PhaseClock:
    """Exact wall-time attribution for one thread: ``enter(state)``
    transitions stamp ``mono_ns`` once, accumulate the outgoing state's
    interval, and by construction the per-state sums partition the
    thread's total wall time — conservation is an identity, not a
    measurement."""

    def __init__(self, clock_ns=None) -> None:
        from ..obs.clock import mono_ns

        self._clock_ns = clock_ns if clock_ns is not None else mono_ns
        self.ns: Dict[str, int] = {p: 0 for p in PHASES}
        self._state = "idle"
        self._t0 = self._clock_ns()
        self._born = self._t0

    def enter(self, state: str) -> str:
        """Transition; returns the OUTGOING state so nested phases
        (engine prefill/decode inside the element's admit/egress) can
        restore their caller's state on exit."""
        now = self._clock_ns()
        self.ns[self._state] += now - self._t0
        prev, self._state = self._state, state
        self._t0 = now
        return prev

    def totals_ns(self) -> Dict[str, int]:
        """Integer per-state totals INCLUDING the in-progress state's
        open interval — the per-session blame-snapshot primitive: two
        snapshots subtract into an EXACT integer partition of the wall
        time between them (sum of per-state deltas == clock delta, the
        same identity :meth:`report` rounds for humans), so a session's
        accumulated blame reconciles with its admit→terminal window to
        the nanosecond."""
        now = self._clock_ns()
        ns = dict(self.ns)
        ns[self._state] += now - self._t0
        return ns

    def report(self) -> Dict[str, Any]:
        """Per-state seconds + shares; ``conserved_pct`` is exactly 100
        by construction (asserted: the identity IS the contract)."""
        now = self._clock_ns()
        ns = dict(self.ns)
        ns[self._state] += now - self._t0
        total = max(1, now - self._born)
        attributed = sum(ns.values())
        return {
            "total_s": total / 1e9,
            "states_s": {p: round(v / 1e9, 6) for p, v in ns.items()},
            "states_pct": {p: round(100.0 * v / total, 3)
                           for p, v in ns.items()},
            "conserved_pct": round(100.0 * attributed / total, 3),
        }


def quantize_prompt(t: int, max_seq: int) -> int:
    """Padded prompt length for one prefill executable: next power of
    two from 8, capped at ``max_seq`` — a bounded ``log2(max_seq)``-ish
    executable set over arbitrary client prompt lengths (the decode
    lanes' ``pad_rows`` policy, applied to the sequence axis)."""
    cap = max(1, int(max_seq))
    q = 8
    while q < t:
        q <<= 1
    return min(q, cap)


class DecodeEngine:
    """The device half of the ``tensor_llm`` element: compiled prefill
    and pooled-decode executables over a :class:`KVCachePool`, plus the
    live accounting (tokens, step EWMA, phase attribution) the
    observability tier reads.

    Single-threaded by contract: exactly one decode thread calls
    :meth:`prefill` / :meth:`step` (the element's loop), so the pool
    arrays mutate without locks.  The jitted executables are cached per
    padded shape — sequences joining/leaving between steps change only
    the LANE COUNT, which quantizes onto the same warm set.

    The pooled cache arrays are DONATED into the step and prefill
    executables (``donate_argnums``): XLA updates the pool in place
    instead of materializing an input+output copy per step — without
    donation the per-step cost scales with POOL size (the whole cache
    copies to scatter one row per layer), which taxed a lone session by
    >50 % for merely sharing a big pool.  Every call site reassigns
    ``pool.k``/``pool.v`` from the outputs (a donated input buffer is
    dead).
    """

    def __init__(self, params, cfg, pool: KVCachePool,
                 capacity: int, prefill_mode: str = "auto",
                 clock=None, chunk: int = 0) -> None:
        import jax

        self.params = params
        self.cfg = cfg
        self.pool = pool
        self.capacity = max(1, int(capacity))
        if prefill_mode not in ("auto", "flash", "naive", "step"):
            raise ValueError(f"prefill mode {prefill_mode!r} "
                             "(want auto | flash | naive | step)")
        self.prefill_mode = prefill_mode
        self._clock = clock if clock is not None else time.monotonic
        self._jax = jax
        #: paged pool?  (block-paged arena + tables instead of slots)
        self.paged = getattr(pool, "page_size", 0) > 0
        #: interleaved-prefill chunk size in tokens (paged only;
        #: 0 = whole remaining prompt in one chunk executable)
        self.chunk = max(0, int(chunk)) if self.paged else 0
        self._step_jit: Dict[Any, Any] = {}      # padded B[, W] -> exec
        self._prefill_jit: Dict[Any, Any] = {}   # padded T / (C, W)
        self.phases = PhaseClock()
        # live accounting the gauges read.  tokens_total counts every
        # GENERATED token (incl. each session's first, argmaxed from
        # the prefill logits); step_tokens only the decode-step ones —
        # the honest numerator for mean bucket fill.
        self.tokens_total = 0
        self.step_tokens = 0
        self.steps_total = 0
        self.prefills_total = 0
        self.prefill_chunks_total = 0
        self.last_fill = 0
        self.ewma_step_s = 0.0
        self.compiles = 0
        #: set by the executable getters on a per-engine warm-set miss,
        #: consumed by the next dispatch (:meth:`_enter_cold`): that
        #: dispatch's device call charges the ``compile`` phase instead
        #: of decode/prefill.  Per-ENGINE coldness on purpose — the
        #: process-wide ``_EXEC_MEMO`` may make the call cheap, but the
        #: attribution question is "did THIS engine meet a cold
        #: executable", which after :meth:`warmup` must never happen.
        self._cold_exec = False

    # -- executables -----------------------------------------------------
    @compile_budget(16, site="llm.engine.step")
    def _step_fn(self, padded: int):
        fn = self._step_jit.get(padded)
        if fn is None:
            compileledger.record("llm.engine.step",
                                 (("padded", padded),))
            cfg = self.cfg

            def _make():
                from ..models.streamformer_lm import decode_step_pooled

                def _step(params, k, v, tokens, pos, slots):
                    return decode_step_pooled(params, k, v, tokens,
                                              pos, slots, cfg)

                return self._jax.jit(_step, donate_argnums=(1, 2))

            fn = _memo_jit(("step", _cfg_key(cfg)), _make)
            self._step_jit[padded] = fn
            self.compiles += 1
            self._cold_exec = True
        return fn

    @compile_budget(64, site="llm.engine.pstep")
    def _pstep_fn(self, padded: int, width: int):
        """Paged decode executable: one per ``(padded B, table width)``
        pair — both axes quantized, so the warm set stays a bounded
        ``|pad_rows| x |quantize_pages|`` grid."""
        key = (padded, width)
        fn = self._step_jit.get(key)
        if fn is None:
            compileledger.record("llm.engine.pstep",
                                 (("padded", padded),
                                  ("width", width)))
            cfg = self.cfg
            ps = self.pool.page_size

            def _make():
                from ..models.streamformer_lm import decode_step_paged

                def _step(params, k, v, tokens, pos, tables):
                    return decode_step_paged(params, k, v, tokens, pos,
                                             tables, cfg, ps)

                return self._jax.jit(_step, donate_argnums=(1, 2))

            fn = _memo_jit(("pstep", _cfg_key(cfg), ps), _make)
            self._step_jit[key] = fn
            self.compiles += 1
            self._cold_exec = True
        return fn

    @compile_budget(64, site="llm.engine.chunk")
    def _chunk_fn(self, padded_c: int, width: int):
        """Paged prefill-chunk executable per ``(padded C, table
        width)``; chunk origin and real length ride as traced operands,
        so ONE executable serves every chunk of every prompt at every
        prefix-hit offset under its quantized bucket."""
        key = ("chunk", padded_c, width)
        fn = self._prefill_jit.get(key)
        if fn is None:
            compileledger.record("llm.engine.chunk",
                                 (("padded_c", padded_c),
                                  ("width", width)))
            cfg = self.cfg
            ps = self.pool.page_size

            def _make():
                from ..models.streamformer_lm import prefill_chunk_paged

                def _chunk(params, k, v, tokens, table, start, true_len,
                           scratch):
                    return prefill_chunk_paged(params, k, v, tokens,
                                               table, start, true_len,
                                               cfg, ps, scratch)

                return self._jax.jit(_chunk, donate_argnums=(1, 2))

            fn = _memo_jit(("chunk", _cfg_key(cfg), ps), _make)
            self._prefill_jit[key] = fn
            self.compiles += 1
            self._cold_exec = True
        return fn

    @compile_budget(32, site="llm.engine.prefill")
    def _prefill_fn(self, padded_t: int):
        fn = self._prefill_jit.get(padded_t)
        if fn is None:
            compileledger.record("llm.engine.prefill",
                                 (("padded_t", padded_t),))
            cfg = self.cfg
            flash = {"auto": None, "flash": True,
                     "naive": False}[self.prefill_mode]
            jax = self._jax

            def _make():
                from ..models.streamformer_lm import prefill_kv

                def _prefill(params, k_pool, v_pool, tokens, slot,
                             true_len):
                    logits, ks, vs = prefill_kv(params, tokens, cfg,
                                                flash=flash)
                    # install the whole padded K/V run into the slot:
                    # rows past true_len are garbage the decode mask
                    # never reads (valid = arange <= pos), so one
                    # static-shape update serves every real length
                    # under this quantized bucket
                    k_pool = jax.lax.dynamic_update_slice(
                        k_pool, ks[None], (slot, 0, 0, 0, 0))
                    v_pool = jax.lax.dynamic_update_slice(
                        v_pool, vs[None], (slot, 0, 0, 0, 0))
                    last = jax.lax.dynamic_index_in_dim(
                        logits, true_len - 1, axis=0, keepdims=False)
                    return last, k_pool, v_pool

                return jax.jit(_prefill, donate_argnums=(1, 2))

            fn = _memo_jit(("prefill", _cfg_key(cfg), flash), _make)
            self._prefill_jit[padded_t] = fn
            self.compiles += 1
            self._cold_exec = True
        return fn

    def _enter_cold(self) -> Optional[str]:
        """Consume the cold-executable flag: when the last getter
        missed this engine's warm set, move the PhaseClock to
        ``compile`` and return the phase to restore after the dispatch
        (None when warm — the hot path pays one attribute read)."""
        if not self._cold_exec:
            return None
        self._cold_exec = False
        return self.phases.enter("compile")

    def warmup(self) -> None:
        """Pre-compile every executable live serving can dispatch (the
        PR 9 warmup_stacked discipline): the padded decode-lane shapes
        AND the pow2-quantized prefill lengths.  Both sets are small
        and enumerable; without this, each shape's first live use
        stalls the SINGLE decode thread for a full XLA compile —
        token emission for every resident session stops for seconds,
        exactly the mid-soak latency spike warmup exists to prevent
        (prefills were the gap a code-review pass caught: a fresh
        prompt-length bucket compiled mid-serve)."""
        # the whole warmup charges the ``compile`` phase: it IS the
        # compile cost, paid up front — after it the share must never
        # grow (the zero-steady-state-compiles gate, made visible in
        # the attribution instead of only the ledger)
        cprev = self.phases.enter("compile")
        try:
            self._warmup_impl()
        finally:
            self.phases.enter(cprev)
            self._cold_exec = False

    def _warmup_impl(self) -> None:
        import jax.numpy as jnp

        if self.paged:
            self._warmup_paged()
            return
        shapes = sorted({JitExecMixin.pad_rows(n, self.capacity)
                         for n in range(1, self.capacity + 1)})
        for rows in shapes:
            toks = jnp.zeros((rows,), jnp.int32)
            pos = jnp.zeros((rows,), jnp.int32)
            slots = jnp.full((rows,), self.pool.scratch, jnp.int32)
            fn = self._step_fn(rows)
            # donated operands: the pool arrays MUST be reassigned from
            # the outputs (the inputs' buffers are dead after the call)
            logits, self.pool.k, self.pool.v = fn(
                self.params, self.pool.k, self.pool.v, toks, pos, slots)
            self._jax.block_until_ready(logits)
        if self.prefill_mode == "step":
            return   # prompt decode rides the step executables above
        lengths, t = [], 8
        while True:
            lengths.append(min(t, self.cfg.max_seq))
            if t >= self.cfg.max_seq:
                break
            t <<= 1
        for padded in sorted(set(lengths)):
            fn = self._prefill_fn(padded)
            last, self.pool.k, self.pool.v = fn(
                self.params, self.pool.k, self.pool.v,
                jnp.zeros((padded,), jnp.int32),
                jnp.int32(self.pool.scratch), jnp.int32(1))
            self._jax.block_until_ready(last)
        # scratch writes during warmup are garbage by design; zero the
        # scratch lane is unnecessary (no session ever reads it)

    def _widths(self):
        """The pow2-quantized block-table widths live dispatch can
        produce — a bounded ``log2(table_max)``-ish set."""
        table_max = self.pool.table_max
        out, w = set(), 1
        while True:
            out.add(min(w, table_max))
            if w >= table_max:
                break
            w <<= 1
        return sorted(out)

    def _chunk_lengths(self):
        """Padded chunk sizes the paged prefill path can dispatch:
        the fixed chunk when interleaving, else the pow2 prompt
        quantization (one whole-suffix chunk per bucket)."""
        if self.chunk > 0:
            return [self.chunk]
        lengths, t = [], 8
        while True:
            lengths.append(min(t, self.cfg.max_seq))
            if t >= self.cfg.max_seq:
                break
            t <<= 1
        return sorted(set(lengths))

    def _warmup_paged(self) -> None:
        """Paged warm set: the ``pad_rows x quantize_pages`` decode
        grid plus every ``(chunk length, width)`` prefill pair whose
        width can cover the chunk — all dispatched at the scratch page,
        so live serving never meets a cold executable (the
        zero-steady-state-compiles acceptance)."""
        import jax.numpy as jnp

        pool = self.pool
        widths = self._widths()
        rows_set = sorted({JitExecMixin.pad_rows(n, self.capacity)
                           for n in range(1, self.capacity + 1)})
        for rows in rows_set:
            for w in widths:
                toks = jnp.zeros((rows,), jnp.int32)
                pos = jnp.zeros((rows,), jnp.int32)
                tables = jnp.full((rows, w), pool.scratch, jnp.int32)
                fn = self._pstep_fn(rows, w)
                logits, pool.k, pool.v = fn(
                    self.params, pool.k, pool.v, toks, pos, tables)
                self._jax.block_until_ready(logits)
        if self.prefill_mode == "step":
            return   # prompt decode rides the paged step grid above
        ps = pool.page_size
        for c in self._chunk_lengths():
            min_w = quantize_pages(-(-c // ps), pool.table_max)
            for w in widths:
                if w < min_w:
                    continue
                fn = self._chunk_fn(c, w)
                last, pool.k, pool.v = fn(
                    self.params, pool.k, pool.v,
                    jnp.zeros((c,), jnp.int32),
                    jnp.full((w,), pool.scratch, jnp.int32),
                    jnp.int32(0), jnp.int32(1),
                    jnp.int32(pool.scratch))
                self._jax.block_until_ready(last)

    # -- prefill ---------------------------------------------------------
    def prefill(self, sess: Session, prompt: np.ndarray) -> int:
        """Seed ``sess``'s cache slot from its prompt and return the
        session's FIRST generated token (greedy argmax of the last
        prompt position's logits — :func:`generate`'s semantics).

        ``prefill_mode="step"`` decodes the prompt token-by-token
        through the pooled step instead (the decode-without-prefill
        path the verifier warns about: correct, but T GEMV steps and no
        flash win)."""
        import jax.numpy as jnp

        prev = self.phases.enter("prefill")
        t = int(prompt.shape[0])
        if self.paged:
            try:
                return self._prefill_paged(sess)
            finally:
                self.phases.enter(prev)
        if self.prefill_mode == "step":
            logits = None
            for i in range(t):
                rows = self._lane_arrays([(sess.slot, i,
                                           int(prompt[i]))])
                logits = self._dispatch(*rows)[0]
            sess.pos = t
        else:
            padded = quantize_prompt(t, self.cfg.max_seq)
            buf = np.zeros((padded,), np.int32)
            buf[:t] = prompt
            fn = self._prefill_fn(padded)
            cold = self._enter_cold()
            try:
                last, self.pool.k, self.pool.v = fn(
                    self.params, self.pool.k, self.pool.v,
                    jnp.asarray(buf), jnp.int32(sess.slot),
                    jnp.int32(t))
                logits = np.asarray(last)
            finally:
                if cold is not None:
                    self.phases.enter(cold)
            sess.pos = t
        self.prefills_total += 1
        self.tokens_total += 1
        sess.last_step_s = self._clock()
        self.phases.enter(prev)
        return int(np.argmax(logits))

    # -- paged prefill ---------------------------------------------------
    def _prefill_paged(self, sess) -> int:
        """Whole-prompt paged prefill: walk :meth:`_advance_chunk` to
        completion inline (the non-interleaved path — ``chunk == 0``
        makes it ONE whole-suffix chunk).  ``prefill_mode="step"``
        instead decodes the prompt token-by-token through the paged
        step grid (the decode-without-prefill misconfig path, paged)."""
        if self.prefill_mode == "step":
            pool = self.pool
            prompt = sess.prompt
            first = None
            for i in range(sess.prefill_pos, sess.plen):
                pool.grow(sess, i + 1)
                logits = self._dispatch_paged(
                    [(sess.table, i, int(prompt[i]))])
                first = int(np.argmax(logits[0]))
            pool.note_prefill(sess, sess.plen)
            sess.pos = sess.plen
            self.prefills_total += 1
            self.tokens_total += 1
            sess.last_step_s = self._clock()
            return first
        while True:
            first = self._advance_chunk(sess)
            if first is not None:
                return first

    def prefill_chunk_step(self, sess) -> Optional[int]:
        """Advance ``sess``'s prefill by ONE bounded chunk — the
        element's decode loop interleaves these between decode steps so
        a long prompt cannot stall resident token streams.  Returns the
        session's first generated token when the prompt completes,
        ``None`` while chunks remain.  Attributed to the PhaseClock's
        ``llm-prefill-chunk`` share (the interleaving proof)."""
        prev = self.phases.enter("llm-prefill-chunk")
        try:
            return self._advance_chunk(sess)
        finally:
            self.phases.enter(prev)

    def _advance_chunk(self, sess) -> Optional[int]:
        """One paged prefill chunk: grow the table over the chunk's
        real positions, dispatch the ``(padded C, width)`` executable
        (origin and real length as traced operands), register any
        newly-full prompt pages with the prefix cache.  Returns the
        first generated token on the FINAL chunk (argmax of position
        ``plen - 1``'s logits), else ``None``."""
        import jax.numpy as jnp

        pool = self.pool
        ps = pool.page_size
        cfg = self.cfg
        start = sess.prefill_pos
        remaining = sess.plen - start
        if remaining <= 0:
            raise RuntimeError(f"session {sess.key!r} is not prefilling")
        c_real = remaining if self.chunk <= 0 \
            else min(self.chunk, remaining)
        c_pad = self.chunk if self.chunk > 0 \
            else quantize_prompt(c_real, cfg.max_seq)
        pool.grow(sess, start + c_real)
        span = min(start + c_pad, cfg.max_seq)
        w = quantize_pages(-(-span // ps), pool.table_max)
        toks = np.zeros((c_pad,), np.int32)
        toks[:c_real] = sess.prompt[start:start + c_real]
        table = np.full((w,), pool.scratch, np.int32)
        m = min(len(sess.table), w)
        table[:m] = sess.table[:m]
        fn = self._chunk_fn(c_pad, w)
        cold = self._enter_cold()
        try:
            last, pool.k, pool.v = fn(
                self.params, pool.k, pool.v, jnp.asarray(toks),
                jnp.asarray(table), jnp.int32(start),
                jnp.int32(c_real), jnp.int32(pool.scratch))
        finally:
            if cold is not None:
                self.phases.enter(cold)
        pool.note_prefill(sess, start + c_real)
        self.prefill_chunks_total += 1
        sess.last_step_s = self._clock()
        if sess.prefilling:
            return None
        sess.pos = sess.plen
        self.prefills_total += 1
        self.tokens_total += 1
        return int(np.argmax(np.asarray(last)))

    # -- decode ----------------------------------------------------------
    def _dispatch_paged(self, lanes):
        """(table, pos, token) lanes → one paged step dispatch.  The
        table width is the max lane's page count pow2-quantized;
        padding lanes and padding table entries point at the scratch
        page, so their scatter-appends can never touch a live page."""
        import jax.numpy as jnp

        pool = self.pool
        ps = pool.page_size
        n = len(lanes)
        padded = JitExecMixin.pad_rows(n, self.capacity)
        w = quantize_pages(max(-(-(p + 1) // ps)
                               for _, p, _ in lanes), pool.table_max)
        toks = np.zeros((padded,), np.int32)
        pos = np.zeros((padded,), np.int32)
        tables = np.full((padded, w), pool.scratch, np.int32)
        for i, (table, p, tok) in enumerate(lanes):
            pos[i], toks[i] = p, tok
            m = min(len(table), w)
            tables[i, :m] = table[:m]
        fn = self._pstep_fn(padded, w)
        cold = self._enter_cold()
        try:
            logits, pool.k, pool.v = fn(
                self.params, pool.k, pool.v, jnp.asarray(toks),
                jnp.asarray(pos), jnp.asarray(tables))
            return np.asarray(logits)[:n]
        finally:
            if cold is not None:
                self.phases.enter(cold)

    def _lane_arrays(self, lanes: Sequence[Tuple[int, int, int]]):
        """(slot, pos, token) lanes → padded device operands.  Padding
        lanes point at the pool's scratch slot, position 0 — their
        scatter writes land in scratch, their gathered logits are
        sliced away."""
        import jax.numpy as jnp

        n = len(lanes)
        padded = JitExecMixin.pad_rows(n, self.capacity)
        slots = np.full((padded,), self.pool.scratch, np.int32)
        pos = np.zeros((padded,), np.int32)
        toks = np.zeros((padded,), np.int32)
        for i, (slot, p, tok) in enumerate(lanes):
            slots[i], pos[i], toks[i] = slot, p, tok
        return (jnp.asarray(toks), jnp.asarray(pos), jnp.asarray(slots),
                padded, n)

    def _dispatch(self, toks, pos, slots, padded: int, n: int):
        fn = self._step_fn(padded)
        cold = self._enter_cold()
        try:
            logits, self.pool.k, self.pool.v = fn(
                self.params, self.pool.k, self.pool.v, toks, pos,
                slots)
            return np.asarray(logits)[:n]
        finally:
            if cold is not None:
                self.phases.enter(cold)

    def step(self, sessions: Sequence[Session]) -> List[int]:
        """One continuous-batching decode step over ``sessions`` (≤
        ``capacity``; the element's round-robin pick): consumes each
        session's ``next_token``, advances its cache position, returns
        the greedily-sampled NEXT token per session (the caller emits
        it and decides stop-token/max-new completion)."""
        if not sessions:
            return []
        t0 = self._clock()
        prev = self.phases.enter("decode")
        if self.paged:
            for s in sessions:
                self.pool.grow(s, s.pos + 1)   # lazy tail-page alloc
            logits = self._dispatch_paged(
                [(s.table, s.pos, s.next_token) for s in sessions])
        else:
            lanes = [(s.slot, s.pos, s.next_token) for s in sessions]
            logits = self._dispatch(*self._lane_arrays(lanes))
        out = np.argmax(logits, axis=1).astype(np.int32)
        now = self._clock()
        for s in sessions:
            s.pos += 1
            s.last_step_s = now
        self.steps_total += 1
        self.tokens_total += len(sessions)
        self.step_tokens += len(sessions)
        self.last_fill = len(sessions)
        dt = now - t0
        self.ewma_step_s = (dt if self.ewma_step_s == 0.0
                            else 0.8 * self.ewma_step_s + 0.2 * dt)
        self.phases.enter(prev)
        return [int(t) for t in out]

    # -- hints / report --------------------------------------------------
    def retry_after_hint(self) -> float:
        """Retry-after for a no-free-slot shed: the soonest-finishing
        resident session's expected remaining wall time under the live
        step EWMA (floored — a hint of 0 would invite an instant
        re-offer into the same full pool)."""
        sessions = self.pool.sessions()
        step_s = self.ewma_step_s or 0.01
        if not sessions:
            return max(0.05, step_s)
        remaining = min(max(1, s.max_new - s.emitted) for s in sessions)
        return max(0.05, remaining * step_s)

    def report(self) -> Dict[str, Any]:
        phases = self.phases.report()
        out = {
            "tokens": self.tokens_total,
            "steps": self.steps_total,
            "prefills": self.prefills_total,
            "mean_fill": round(self.step_tokens
                               / max(1, self.steps_total), 2),
            "ewma_step_ms": round(self.ewma_step_s * 1e3, 3),
            "compiles": self.compiles,
            "cache_bytes": self.pool.cache_bytes(),
            "phases": phases,
        }
        if self.paged:
            out["prefill_chunks"] = self.prefill_chunks_total
            out["paged"] = self.pool.stats()
        return out
