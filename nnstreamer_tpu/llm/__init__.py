"""Token-streaming LLM serving tier: session-keyed KV-cache pool +
continuous-batching decode plane.

The first STATEFUL workload the framework serves (ROADMAP item 5, the
sharpest test of the PR 9 cross-stream batcher): vLLM-style continuous
batching of token-streaming LLM inference, where variable-length
sequences join and leave the device bucket every decode step — the
inter-kernel streaming-dataflow framing of StreamTensor
(arXiv:2509.13694) applied to the decode loop, and the user-schedulable
non-MatMul-adjacent scheduling "Pushing Tensor Accelerators Beyond
MatMul" (arXiv:2512.02371) argues accelerators need.

Everything REUSES the existing serving plane rather than forking it:

- **pool.py** — :class:`KVCachePool`: fixed ``max_seq`` static-shape
  cache slots (the ``models/streamformer_lm.py`` decode contract)
  allocated per live stream; slot admission rides the PR 7
  :class:`~nnstreamer_tpu.query.overload.AdmissionController` (no free
  slot ⇒ explicit ``T_SHED`` with retry-after, never unbounded memory),
  LRU/deadline eviction on client disconnect or EOS.
- **engine.py** — :class:`DecodeEngine`: the continuous-batching decode
  core.  Each step gathers the per-session position indices and cache
  slot ids of every resident sequence and runs ONE padded
  ``decode_step_pooled`` invoke over the active set (the PR 9
  ``pad_rows`` quantization: a bounded set of warm executables serves
  every fill).  Prefill routes through ``ops/flash_attention.py`` so
  long prompts never materialize (T, T) scores.  Exact, conserved
  prefill-vs-decode-vs-idle wall-time attribution.
- **element.py** — the stateful ``tensor_llm`` filter element: prompt
  request frames in, per-token ``[1, 1]`` reply frames out through
  ``tensor_query_serversink`` in exact per-client order, with the
  existing trace-context piggyback (one merged Chrome timeline shows
  prefill, per-step decode windows, and queue-wait per token).
- **paged.py** — :class:`PagedKVCachePool`: the block-paged arena
  (vLLM/PagedAttention layout) behind the same pool contract — memory
  proportional to what a session USES, content-hash prefix reuse
  (copy-on-write, refcounted), commitment-based page admission.
- **client.py** — :class:`TokenStreamClient`: the client half of the
  streaming reply contract over the unchanged query wire protocol.
"""

from .client import TokenStreamClient, TokenTimeoutError
from .engine import DecodeEngine, PhaseClock
from .paged import PagedKVCachePool
from .pool import KVCachePool, slot_admission_controller

__all__ = ["DecodeEngine", "KVCachePool", "PagedKVCachePool",
           "PhaseClock", "TokenStreamClient", "TokenTimeoutError",
           "slot_admission_controller"]
