"""Client half of the token-streaming reply contract.

The query wire protocol is UNCHANGED: a token-stream request is one
ordinary ``T_DATA`` frame, and the answer is MANY ``T_REPLY`` frames
sharing the request's seq — each carrying one ``[1, 1] int32`` token
with ``pts`` = token index.  The stream ends by the stop-token
contract: after ``max_new`` frames, or earlier at the first frame whose
token equals the request's ``stop_token`` (that frame is delivered and
IS the end marker); a NEGATIVE token is unconditionally terminal (the
server's refusal/eviction markers — real vocab tokens are never
negative).  ``T_SHED`` for the seq surfaces as
:class:`~nnstreamer_tpu.query.overload.ShedError` exactly like the
request/response path — slot exhaustion is an explicit, retryable
refusal.

Built over :class:`~nnstreamer_tpu.query.client.QueryConnection`'s
transport internals (socket, reader thread, reply queue, seq
allocation) so HELLO/QoS negotiation, clock-offset sampling and the
T_TRACE piggyback all apply unchanged.  One outstanding stream per
connection (the synchronous QueryConnection discipline).
"""

from __future__ import annotations

import queue as _queue
import time
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..query.client import QueryConnection
from ..query.overload import ShedError
from ..query.protocol import (T_DATA, T_REPLY, T_SHED, decode_tensors,
                              parse_retry_after, send_tensors)
from ..tensor.buffer import TensorBuffer
from .element import REQ_HEADER


class TokenTimeoutError(TimeoutError):
    """The next token missed the per-token inactivity deadline.

    Raised by :meth:`TokenStreamClient.stream` with the undelivered
    reply queue already DRAINED (leased wire slabs released): the
    caller sees a named, catchable verdict and the slab pool sees its
    memory back immediately — an abandoned stream must not hold pooled
    slabs hostage until garbage collection.
    """

    def __init__(self, msg: str, got: int = 0,
                 timeout_s: float = 0.0) -> None:
        super().__init__(msg)
        self.got = got               # tokens delivered before the stall
        self.timeout_s = timeout_s   # the deadline that fired


def encode_request(prompt: Sequence[int], max_new: int,
                   stop_token: int = -1,
                   frame_len: Optional[int] = None) -> np.ndarray:
    """The ``tensor_llm`` request framing: ``(N,) int32`` =
    ``[prompt_len, max_new, stop_token, prompt...]``, zero-padded to
    ``frame_len`` (the serving caps' fixed tensor length)."""
    prompt = np.asarray(prompt, np.int32).reshape(-1)
    n = REQ_HEADER + prompt.shape[0]
    total = int(frame_len) if frame_len else n
    if total < n:
        raise ValueError(f"frame_len={frame_len} cannot hold a "
                         f"{prompt.shape[0]}-token prompt")
    out = np.zeros((total,), np.int32)
    out[0] = prompt.shape[0]
    out[1] = int(max_new)
    out[2] = int(stop_token)
    out[REQ_HEADER:n] = prompt
    return out


class TokenStreamClient:
    """One token-streaming connection to a ``tensor_llm`` server."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 qos: Optional[str] = None,
                 model: Optional[str] = None,
                 token_timeout: Optional[float] = None) -> None:
        self._conn = QueryConnection(host, port, timeout=timeout,
                                     qos=qos, model=model)
        self.timeout = float(timeout)
        #: per-token inactivity deadline (seconds) — how long a stream
        #: may go WITHOUT a next token before it is declared stalled
        #: (:class:`TokenTimeoutError`); ``None`` inherits the
        #: transport timeout, but a serving caller should set it from
        #: its own latency budget: the transport default is a connect/
        #: request deadline and says nothing about inter-token gaps
        self.token_timeout = (float(token_timeout)
                              if token_timeout is not None
                              else self.timeout)
        #: per-token receive stamps (``mono_ns``) of the CURRENT /
        #: most recent stream, reset at each :meth:`stream` send — the
        #: wire-side half of the token-latency contract: the loadgen
        #: measures coordinated-omission-free TTFT as ``stamps_ns[0] -
        #: scheduled arrival`` and ITL from consecutive stamps, so a
        #: stalled server cannot hide behind a late send
        self.stamps_ns: List[int] = []

    def connect(self) -> "TokenStreamClient":
        self._conn.connect()
        return self

    def close(self) -> None:
        self._conn.close()
        self._drain_replies()

    def _drain_replies(self) -> None:
        """Release every undelivered reply's leased wire slab: a
        stream abandoned mid-flight (disconnect, shed, stall, caller
        bailed) leaves leased token frames queued — their pooled slabs
        must return to the pool NOW, not whenever the queue object
        happens to be collected."""
        while True:
            try:
                msg = self._conn.replies.get_nowait()
            except _queue.Empty:
                break
            if msg is not None and msg.lease is not None:
                msg.payload = b""
                msg.lease.release()

    @property
    def connection(self) -> QueryConnection:
        return self._conn

    def stream(self, prompt: Sequence[int], max_new: int,
               stop_token: int = -1,
               frame_len: Optional[int] = None,
               token_timeout: Optional[float] = None
               ) -> Iterator[Tuple[int, int]]:
        """Send one request; yield ``(index, token)`` pairs as reply
        frames arrive, ending by the stop-token contract.  Raises
        :class:`ShedError` on an explicit slot shed,
        :class:`TokenTimeoutError` when the next token misses the
        per-token inactivity deadline (``token_timeout`` here, the
        client's ``token_timeout`` otherwise — raised with the reply
        queue drained and its leased slabs released), and
        ``ValueError`` on an out-of-order token index (the exact
        per-client order gate — ``pts`` must count 0, 1, 2, …)."""
        conn = self._conn
        gap = (float(token_timeout) if token_timeout is not None
               else self.token_timeout)
        req = encode_request(prompt, max_new, stop_token, frame_len)
        from ..obs.clock import mono_ns

        self.stamps_ns = stamps = []
        with conn._waiters_lock:
            conn._seq += 1
            seq = conn._seq
        with conn._send_lock:
            send_tensors(conn._sock, T_DATA,
                         TensorBuffer(tensors=[req]), seq=seq)
        got = 0
        while got < max_new:
            deadline = time.monotonic() + gap
            reply = None
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._drain_replies()
                    raise TokenTimeoutError(
                        f"no token within {gap}s "
                        f"(received {got}/{max_new})",
                        got=got, timeout_s=gap)
                try:
                    reply = conn.replies.get(timeout=remaining)
                except _queue.Empty:
                    continue
                if reply is None:
                    raise ConnectionError(
                        "server closed connection mid-stream")
                if reply.seq == seq:
                    break
                # stale reply of an earlier timed-out request: discard
            if reply.type == T_SHED:
                raise ShedError(parse_retry_after(reply.payload),
                                qos=conn.qos or "default")
            assert reply.type == T_REPLY
            tok = int(np.asarray(decode_tensors(
                reply.payload)[0]).reshape(-1)[0])
            idx = int(reply.pts or 0)
            if idx != got:
                raise ValueError(
                    f"token order violated: expected index {got}, "
                    f"got {idx}")
            got += 1
            stamps.append(mono_ns())
            yield idx, tok
            if tok < 0 or (stop_token >= 0 and tok == stop_token):
                # a NEGATIVE token is unconditionally terminal: real
                # vocab tokens are >= 0, so the element's refusal /
                # eviction markers (emitted as the request's stop_token,
                # -1 when none was set) must end the stream even for
                # callers that set no stop token — without this the
                # "deterministic refusal" would read as a hang until
                # the per-token timeout
                return

    def generate(self, prompt: Sequence[int], max_new: int,
                 stop_token: int = -1,
                 frame_len: Optional[int] = None) -> List[int]:
        """Collect a whole stream (order-checked by :meth:`stream`)."""
        return [tok for _, tok in self.stream(prompt, max_new,
                                              stop_token, frame_len)]
