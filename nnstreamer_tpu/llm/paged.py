"""Block-paged KV cache: memory-proportional session state with
content-hash prefix reuse — the vLLM/PagedAttention layout over the
PR 15 pool contract.

The dense :class:`~nnstreamer_tpu.llm.pool.KVCachePool` reserves one
``max_seq`` lane per session, so a 30-token chat pins the same cache
memory as a 2048-token one.  Here the arena is ONE fixed ``(num_pages
+ 1, layers, page_size, heads, head_dim)`` K/V allocation (the last
page is scratch for padding lanes), and a session's cache is a chain
of pages named by its BLOCK TABLE — page ``j`` holds positions
``[j*page_size, (j+1)*page_size)``.  Memory now scales with what a
session actually uses: ``ceil((prompt + max_new)/page_size)`` pages,
not ``max_seq``, which is the whole ≥2×-resident-sessions headline.

**Admission is commitment-based** (the PR 7 no-unbounded-memory
doctrine, page-grained): a session admits only when the arena can
cover its worst case — ``ceil((prompt_len + max_new)/page_size)``
pages minus whatever a prefix hit shares — against every live
session's outstanding commitment.  Pages then allocate LAZILY as the
stream crosses page boundaries, and the reservation guarantees the
tail-page allocation can never fail mid-stream (no vLLM-style
preemption needed: an admitted stream always runs to completion).

**Prefix caching**: full prompt pages are content-addressed by a CHAIN
hash (``h_j = H(h_{j-1} || tokens[j*ps:(j+1)*ps])``), so a hash hit
certifies the page's entire history, not just its own tokens —
position embeddings bake absolute positions into K/V, which is exactly
why only position-0-anchored chains are shareable.  Sessions sharing a
system prompt map the registered pages copy-on-write (shared pages are
FULL prompt pages and therefore never written again — the only writes
a paged stream makes land at ``pos >= prompt_len``), refcounted; a
released prefix stays registered at refcount 0 as a RECLAIMABLE page
(free for allocation, still a future hit until reclaimed LRU-first).
At least one suffix token is always left to compute, so a 100 % prefix
hit still produces the last-position logits the first emitted token is
argmaxed from.
"""

from __future__ import annotations

import dataclasses
import hashlib
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import numpy as np

from ..analysis.sanitizer import make_lock
from ..query.overload import AdmissionController
from .pool import Session, slot_admission_controller


def chain_hashes(prompt: np.ndarray, page_size: int) -> List[bytes]:
    """Chain hash per FULL prompt page: ``h_j`` digests pages ``0..j``'s
    tokens, so equal ``h_j`` ⇒ equal position-anchored history (the
    prefix-share safety proof).  Only full pages hash — a partial tail
    page will still be written by this session's own suffix/decode."""
    ps = int(page_size)
    out: List[bytes] = []
    prev = b""
    arr = np.asarray(prompt, np.int32)
    for j in range(int(arr.shape[0]) // ps):
        h = hashlib.blake2b(digest_size=16)
        h.update(prev)
        h.update(arr[j * ps:(j + 1) * ps].tobytes())
        prev = h.digest()
        out.append(prev)
    return out


@dataclasses.dataclass
class PagedSession(Session):
    """A :class:`~nnstreamer_tpu.llm.pool.Session` whose cache is a
    block table instead of a slot (``slot`` stays ``-1``)."""

    table: List[int] = dataclasses.field(default_factory=list)
    plen: int = 0                 # prompt length (positions 0..plen-1)
    prefill_pos: int = 0          # prompt positions already computed
    prompt: Optional[np.ndarray] = None   # dropped when prefill ends
    reserve: int = 0              # pages this session may still take
    n_reg: int = 0                # leading table pages we hold refs on
    hashes: List[bytes] = dataclasses.field(default_factory=list)
    shared_tokens: int = 0        # prefix-hit tokens (never re-prefilled)

    @property
    def prefilling(self) -> bool:
        return self.prefill_pos < self.plen


class PagedKVCachePool:
    """Bounded page arena + block-table bookkeeping + prefix registry.

    Same consumer contract as the dense pool (``live`` / ``occupancy``
    / ``sessions()`` / ``admit`` / ``acquire`` / ``release`` / ``touch``
    / ``lru_key`` / ``aged_keys`` / ``cache_bytes``), so the element,
    engine and observability tier swap pools without forking; the
    paged-only surface (``grow`` / ``note_prefill`` / ``free_pages``)
    is what the decode engine's paged executables drive.  Array access
    stays single-decode-threaded and lock-free; bookkeeping rides one
    small lock like the dense pool.
    """

    def __init__(self, cfg, pages: int, page_size: int, slots: int,
                 admission: Optional[AdmissionController] = None,
                 clock=None, prefix_cache: bool = True) -> None:
        import time as _time

        import jax.numpy as jnp

        ps = int(page_size)
        if ps < 1:
            raise ValueError(f"page_size must be >= 1 (got {page_size})")
        if cfg.max_seq % ps != 0 or ps > cfg.max_seq:
            raise ValueError(
                f"page_size={ps} must tile max_seq={cfg.max_seq} evenly "
                "(block tables map position j to page j//page_size; a "
                "ragged last page would alias positions)")
        if int(pages) < 1:
            raise ValueError(f"need >= 1 page (got {pages})")
        if int(slots) < 1:
            raise ValueError(f"need >= 1 session slot (got {slots})")
        self.cfg = cfg
        self.page_size = ps
        self.pages = int(pages)
        self.slots = int(slots)            # max resident SESSIONS
        self.table_max = cfg.max_seq // ps
        self.scratch = self.pages          # scratch PAGE id
        self.prefix_cache = bool(prefix_cache)
        self.admission = (admission if admission is not None
                          else slot_admission_controller())
        self._clock = clock if clock is not None else _time.monotonic
        shape = (self.pages + 1, cfg.layers, ps, cfg.heads, cfg.head_dim)
        self.k = jnp.zeros(shape, cfg.dtype)
        self.v = jnp.zeros(shape, cfg.dtype)
        self._free: List[int] = list(range(self.pages))
        self._live: Dict[Any, PagedSession] = {}
        self._order = 0
        self._reserved = 0                 # sum of live sess.reserve
        self._page_refs = [0] * self.pages
        self._page_hash: List[Optional[bytes]] = [None] * self.pages
        self._reg: Dict[bytes, int] = {}   # chain hash -> page id
        #: registered pages at refcount 0 — allocatable, LRU-first
        self._reclaim: "OrderedDict[bytes, int]" = OrderedDict()
        self._lock = make_lock("llm.pool")
        # prefix accounting (the soak's hit evidence)
        self.prefix_hits = 0               # sessions admitted onto a hit
        self.prefix_misses = 0
        self.prefix_tokens_reused = 0      # prompt tokens never prefilled
        self.pages_reclaimed = 0           # cached pages repurposed

    # -- sizing ----------------------------------------------------------
    def cache_bytes(self) -> int:
        """Device bytes of the page arena — CONSTANT for the pool's
        life (the bounded-memory evidence the soak gates on), and with
        the element's default sizing EQUAL to the dense pool's bytes at
        the same ``slots`` — the apples-to-apples residency claim."""
        return int(self.k.nbytes) + int(self.v.nbytes)

    @property
    def live(self) -> int:
        with self._lock:
            return len(self._live)

    @property
    def free_pages(self) -> int:
        """Pages allocatable RIGHT NOW: the free list plus reclaimable
        (refcount-0 registered) prefix pages.  Equals ``pages`` when no
        session is live and nothing leaked — the fragmentation-churn
        invariant the property test pins."""
        with self._lock:
            return len(self._free) + len(self._reclaim)

    @property
    def occupancy(self) -> float:
        """Committed fraction of the arena: allocated + pinned +
        outstanding reservations over total pages — what the watermark
        shed policy watches (the real resource is pages, not slots)."""
        with self._lock:
            usable = len(self._free) + len(self._reclaim)
            return (self.pages - usable + self._reserved) / self.pages

    def sessions(self) -> List[PagedSession]:
        with self._lock:
            return sorted(self._live.values(), key=lambda s: s.order)

    def get(self, key) -> Optional[PagedSession]:
        with self._lock:
            return self._live.get(key)

    # -- prefix matching -------------------------------------------------
    def _match(self, hashes: List[bytes], plen: int):
        """Longest registered chain usable for a ``plen``-token prompt
        (capped so >= 1 suffix token remains to compute).  Returns
        ``(n_pages, resurrect)`` — ``resurrect`` counts hit pages
        currently reclaimable (a hit pins them, shrinking the
        allocatable set).  Lock held by caller."""
        if not self.prefix_cache:
            return 0, 0
        cap = (plen - 1) // self.page_size
        n = 0
        resurrect = 0
        for h in hashes[:cap]:
            pg = self._reg.get(h)
            if pg is None:
                break
            if self._page_refs[pg] == 0:
                resurrect += 1
            n += 1
        return n, resurrect

    def _need_pages(self, plen: int, max_new: int) -> int:
        # positions written: prompt 0..plen-1 plus at most max_new - 1
        # consumed continuation tokens (the final emitted token is
        # never fed back) — ceil((plen + max_new)/ps) covers it
        total = plen + max(1, int(max_new))
        return -(-total // self.page_size)

    # -- admission -------------------------------------------------------
    def admit(self, qos: str, no_slot_retry_s: float = 0.25,
              prompt: Optional[np.ndarray] = None,
              max_new: int = 0) -> Optional[float]:
        """Page-admission decision BEFORE allocation: ``None`` admits,
        a float sheds with that retry-after hint.  Policy first (QoS
        watermarks over page commitment + drain mode), then the two
        hard boundaries: the session-count bound and the page
        commitment bound (this request's worst-case private pages, net
        of its prefix hit, against what the arena still has)."""
        plen = int(np.asarray(prompt).shape[0]) if prompt is not None \
            else 1
        with self._lock:
            usable = len(self._free) + len(self._reclaim)
            depth = self.pages - usable + self._reserved
            n_live = len(self._live)
            hashes = chain_hashes(prompt, self.page_size) \
                if prompt is not None else []
            hit, resurrect = self._match(hashes, plen)
        verdict = self.admission.admit(qos or "silver", depth, self.pages)
        if verdict is not None:
            return verdict
        need = self._need_pages(plen, max_new) - hit
        if n_live >= self.slots \
                or usable - resurrect - self._reserved < need:
            return max(float(no_slot_retry_s), 0.01)
        return None

    def acquire(self, key, qos: str = "silver",
                extra: Optional[Dict[str, Any]] = None,
                prompt: Optional[np.ndarray] = None,
                max_new: int = 0) -> PagedSession:
        """Admit ``key``: pin its prefix-hit pages (refcount++), seed
        the block table with them, and reserve the private remainder.
        Caller must have gotten ``None`` from :meth:`admit` (both run
        on the single decode thread, so the check cannot go stale)."""
        if prompt is None:
            raise ValueError("paged acquire needs the prompt "
                             "(prefix match + page reservation)")
        arr = np.asarray(prompt, np.int32)
        plen = int(arr.shape[0])
        now = self._clock()
        with self._lock:
            if key in self._live:
                raise ValueError(f"session {key!r} already live")
            if len(self._live) >= self.slots:
                raise RuntimeError("no free session slot")
            hashes = chain_hashes(arr, self.page_size)
            hit, _ = self._match(hashes, plen)
            need = self._need_pages(plen, max_new) - hit
            usable = len(self._free) + len(self._reclaim)
            if usable - self._reserved < need + sum(
                    1 for h in hashes[:hit]
                    if self._page_refs[self._reg[h]] == 0):
                raise RuntimeError("no free cache pages")
            table: List[int] = []
            for h in hashes[:hit]:
                pg = self._reg[h]
                if self._page_refs[pg] == 0:
                    self._reclaim.pop(h, None)
                self._page_refs[pg] += 1
                table.append(pg)
            self._order += 1
            sess = PagedSession(
                key=key, slot=-1, qos=qos or "silver",
                extra=dict(extra or {}), born_s=now, last_step_s=now,
                order=self._order, table=table, plen=plen,
                prefill_pos=hit * self.page_size, prompt=arr,
                reserve=need, n_reg=hit, hashes=hashes,
                shared_tokens=hit * self.page_size)
            self._reserved += need
            self._live[key] = sess
            if hit:
                self.prefix_hits += 1
                self.prefix_tokens_reused += hit * self.page_size
            else:
                self.prefix_misses += 1
            return sess

    # -- page allocation -------------------------------------------------
    def _take_page(self) -> int:
        """Pop a free page, reclaiming the LRU refcount-0 prefix page
        when the free list is dry (its registry entry drops — orphaned
        chain descendants age out the same way).  Lock held."""
        if self._free:
            return self._free.pop()
        if self._reclaim:
            h, pg = self._reclaim.popitem(last=False)
            self._reg.pop(h, None)
            self._page_hash[pg] = None
            self.pages_reclaimed += 1
            return pg
        raise RuntimeError(
            "page arena exhausted despite commitment accounting "
            "(reservation invariant breached)")

    def grow(self, sess: PagedSession, positions: int) -> None:
        """Ensure ``sess``'s table covers cache positions
        ``[0, positions)`` — the lazy tail-page allocation the decode
        step and each prefill chunk call before dispatch.  Draws on the
        session's reservation, which admission guaranteed."""
        with self._lock:
            while len(sess.table) * self.page_size < positions:
                if sess.reserve < 1:
                    raise RuntimeError(
                        f"session {sess.key!r} outgrew its page "
                        f"reservation ({len(sess.table)} pages, "
                        f"needs position {positions})")
                sess.table.append(self._take_page())
                sess.reserve -= 1
                self._reserved -= 1

    def note_prefill(self, sess: PagedSession, upto: int) -> None:
        """Record prefill progress through position ``upto`` and
        REGISTER any prompt page that just became full (content-hash →
        page, refcount 1 held by the owner) so later — or concurrent —
        sessions with the same position-0 chain hit it.  A hash already
        registered to a DIFFERENT page (two identical prompts racing
        their prefills) leaves this session's copy private."""
        sess.prefill_pos = max(sess.prefill_pos, int(upto))
        if not self.prefix_cache:
            if not sess.prefilling:
                sess.prompt = None
            return
        with self._lock:
            while sess.n_reg < len(sess.hashes) \
                    and (sess.n_reg + 1) * self.page_size \
                    <= sess.prefill_pos:
                h = sess.hashes[sess.n_reg]
                pg = sess.table[sess.n_reg]
                if h not in self._reg and self._page_hash[pg] is None:
                    self._reg[h] = pg
                    self._page_hash[pg] = h
                    self._page_refs[pg] = 1
                # else: raced duplicate (two identical prompts
                # prefilling concurrently) — our copy stays private;
                # release tells them apart by the page's hash mark
                sess.n_reg += 1
        if not sess.prefilling:
            sess.prompt = None   # slab-free: the prompt copy served

    # -- release ---------------------------------------------------------
    def release(self, key) -> Optional[PagedSession]:
        """Return ``key``'s pages: registered prefix pages decref (at 0
        they become reclaimable but STAY registered — the next session
        with this system prompt still hits), private pages go straight
        to the free list, the unspent reservation returns to the arena.
        Device memory is untouched, stale positions masked as ever."""
        with self._lock:
            sess = self._live.pop(key, None)
            if sess is None:
                return None
            for i, pg in enumerate(sess.table):
                h = self._page_hash[pg]
                if i < sess.n_reg and h is not None:
                    self._page_refs[pg] -= 1
                    if self._page_refs[pg] == 0:
                        self._reclaim[h] = pg
                        self._reclaim.move_to_end(h)
                else:
                    self._free.append(pg)
            self._reserved -= sess.reserve
            sess.reserve = 0
            sess.table = []
            sess.prompt = None
            return sess

    def reset_prefix_cache(self) -> int:
        """Drop every RECLAIMABLE registered page back to the free list
        (live sessions' pinned prefixes stay).  Returns pages freed —
        the cold-run lever benches use."""
        with self._lock:
            n = 0
            while self._reclaim:
                h, pg = self._reclaim.popitem(last=False)
                self._reg.pop(h, None)
                self._page_hash[pg] = None
                self._free.append(pg)
                n += 1
            return n

    # -- liveness --------------------------------------------------------
    def touch(self, key) -> None:
        sess = self.get(key)
        if sess is not None:
            sess.last_step_s = self._clock()

    def lru_key(self):
        with self._lock:
            if not self._live:
                return None
            return min(self._live.values(),
                       key=lambda s: s.last_step_s).key

    def aged_keys(self, max_age_s: float) -> List[Any]:
        if max_age_s <= 0:
            return []
        cutoff = self._clock() - max_age_s
        with self._lock:
            return [s.key for s in self._live.values()
                    if s.born_s < cutoff]

    # -- diagnostics -----------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "pages": self.pages,
                "page_size": self.page_size,
                "free": len(self._free),
                "reclaimable": len(self._reclaim),
                "registered": len(self._reg),
                "reserved": self._reserved,
                "live": len(self._live),
                "prefix_hits": self.prefix_hits,
                "prefix_misses": self.prefix_misses,
                "prefix_tokens_reused": self.prefix_tokens_reused,
                "pages_reclaimed": self.pages_reclaimed,
            }

    def check_leaks(self) -> List[str]:
        """Invariant audit (the fragmentation test's oracle): with no
        live sessions, every page must be free or reclaimable, every
        refcount zero, and the reservation ledger empty."""
        out = []
        with self._lock:
            if self._live:
                out.append(f"{len(self._live)} sessions still live")
            usable = len(self._free) + len(self._reclaim)
            if not self._live and usable != self.pages:
                out.append(f"free_pages={usable} != pages={self.pages}")
            if not self._live and self._reserved:
                out.append(f"reserved={self._reserved} with no sessions")
            for pg, r in enumerate(self._page_refs):
                if self._live:
                    break
                if r != 0:
                    out.append(f"page {pg} refcount {r} leaked")
            for h, pg in self._reg.items():
                if self._page_hash[pg] != h:
                    out.append(f"registry/page hash mismatch on {pg}")
        return out
