"""Session-keyed KV-cache slot pool: bounded, admission-controlled,
LRU/deadline-evicted.

A token-streaming session's device state is one STATIC-shape cache slot
(``(layers, max_seq, heads, head_dim)`` per K and V — the
``models/streamformer_lm.py`` decode contract), so the whole tier's
cache memory is fixed at construction: ``(slots + 1) × layers ×
max_seq × heads × head_dim × 2 × itemsize`` bytes, one scratch slot
included for padding lanes.  There is NO per-session allocation on the
admission path — a session either gets a pre-allocated slot or an
explicit shed with a retry-after hint, never unbounded memory (the
PR 7 overload doctrine applied to session state instead of queue
depth).

Slot admission composes the existing
:class:`~nnstreamer_tpu.query.overload.AdmissionController`: a
watermark policy over SLOT occupancy sheds bronze sessions before the
pool is full (so background traffic cannot take the last slots a gold
prompt needs), drain mode sheds everything, and "no free slot" is the
hard watermark underneath.  Eviction is explicit — client disconnect,
EOS, or a deadline on sessions that stopped making progress — and an
evicted slot returns to the free list with its device memory untouched
(the next session's prefill overwrites it; positions beyond the new
session's ``pos`` are masked by the decode math, so stale bytes can
never leak into another session's attention).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from ..analysis.sanitizer import make_lock
from ..query.overload import AdmissionController, WatermarkShedPolicy

#: slot-occupancy arm watermarks for the default slot shed policy:
#: bronze sessions shed at 80 % occupancy, silver at 95 %; gold only
#: sheds on the hard no-free-slot boundary (arm > 1 never arms).
#: Hysteresis (disarm at half the arm point) rides the policy unchanged.
SLOT_ARM = {"gold": 2.0, "silver": 0.95, "bronze": 0.80}


def slot_admission_controller(retry_after_s: float = 0.25
                              ) -> AdmissionController:
    """The default slot-admission controller: the PR 7 watermark policy
    re-pointed at slot occupancy (depth = live sessions, capacity =
    slots).  Same hysteresis, same drain-mode shed-everything."""
    return AdmissionController(
        policy=WatermarkShedPolicy(arm=dict(SLOT_ARM),
                                   retry_after_s=retry_after_s))


@dataclasses.dataclass
class Session:
    """One live token stream resident in the pool."""

    key: Any                    # (client_id, wire seq) — or a local id
    slot: int                   # cache slot id (stable for the life)
    pos: int = 0                # next cache write position
    next_token: int = 0         # token the next decode step consumes
    emitted: int = 0            # tokens answered so far
    max_new: int = 0            # granted continuation length
    stop_token: int = -1        # ends the stream when emitted (<0: none)
    truncated: bool = False     # granted < asked: end with a marker
    qos: str = "silver"
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)
    born_s: float = 0.0
    last_step_s: float = 0.0    # progress stamp (deadline eviction)
    order: int = 0              # admission order (stable round-robin)
    #: per-session lifecycle record (llm/tokenobs.SessionRecord) when
    #: the element's token-level observability is on; None when off —
    #: every hot-path hook gates on this single attribute test (the
    #: annotation_active() zero-cost discipline)
    obs: Any = None


class KVCachePool:
    """Bounded slot pool + the pooled device cache arrays.

    ``k``/``v`` are the ``(slots + 1, layers, max_seq, heads, head_dim)``
    pooled cache (``models/streamformer_lm.decode_step_pooled``'s
    operand); index ``slots`` is the SCRATCH slot padding lanes write
    into, never handed to a session.  The pool owns slot bookkeeping —
    free list, live sessions by key, LRU order, occupancy — under one
    small lock; the decode engine reads/writes the arrays themselves
    from the single decode thread, so array access needs no lock.
    """

    def __init__(self, cfg, slots: int,
                 admission: Optional[AdmissionController] = None,
                 clock=None) -> None:
        import time as _time

        import jax.numpy as jnp

        if int(slots) < 1:
            raise ValueError(f"KVCachePool needs >= 1 slot (got {slots})")
        self.cfg = cfg
        self.slots = int(slots)
        self.scratch = self.slots          # padding lanes' slot id
        self.admission = (admission if admission is not None
                          else slot_admission_controller())
        self._clock = clock if clock is not None else _time.monotonic
        shape = (self.slots + 1, cfg.layers, cfg.max_seq, cfg.heads,
                 cfg.head_dim)
        self.k = jnp.zeros(shape, cfg.dtype)
        self.v = jnp.zeros(shape, cfg.dtype)
        self._free: List[int] = list(range(self.slots))
        self._live: Dict[Any, Session] = {}
        self._order = 0
        self._lock = make_lock("llm.pool")

    # -- sizing ----------------------------------------------------------
    def cache_bytes(self) -> int:
        """Device bytes the pooled cache occupies — CONSTANT for the
        pool's life (the bounded-memory evidence the soak gates on)."""
        return int(self.k.nbytes) + int(self.v.nbytes)

    @property
    def live(self) -> int:
        with self._lock:
            return len(self._live)

    @property
    def occupancy(self) -> float:
        return self.live / self.slots

    def sessions(self) -> List[Session]:
        """Live sessions in admission order (the engine's stable
        round-robin basis)."""
        with self._lock:
            return sorted(self._live.values(), key=lambda s: s.order)

    def get(self, key) -> Optional[Session]:
        with self._lock:
            return self._live.get(key)

    # -- admission -------------------------------------------------------
    def admit(self, qos: str, no_slot_retry_s: float = 0.25,
              prompt=None, max_new: int = 0) -> Optional[float]:
        """Slot-admission decision BEFORE allocation: ``None`` admits
        (a free slot exists and the occupancy policy agrees), a float
        sheds with that retry-after hint.  Policy first (QoS-tiered
        occupancy watermarks + drain mode), the hard no-free-slot
        boundary second — its hint is ``no_slot_retry_s``, which the
        engine sizes from its live step-time EWMA (≈ when the
        soonest-finishing session should free a slot).  ``prompt`` /
        ``max_new`` are accepted for pool-interface parity (the paged
        pool admits on page commitment) and ignored here: a dense slot
        costs ``max_seq`` regardless of what the session uses."""
        with self._lock:
            depth = len(self._live)
            free = bool(self._free)
        verdict = self.admission.admit(qos or "silver", depth, self.slots)
        if verdict is not None:
            return verdict
        if not free:
            return max(float(no_slot_retry_s), 0.01)
        return None

    def acquire(self, key, qos: str = "silver",
                extra: Optional[Dict[str, Any]] = None,
                prompt=None, max_new: int = 0) -> Session:
        """Allocate a slot for ``key``.  Caller must have gotten a
        ``None`` from :meth:`admit`; raises when no slot is free (the
        admit/acquire pair runs on the single decode thread, so the
        check cannot go stale).  ``prompt`` / ``max_new`` are ignored
        (pool-interface parity with the paged pool)."""
        now = self._clock()
        with self._lock:
            if key in self._live:
                raise ValueError(f"session {key!r} already live")
            if not self._free:
                raise RuntimeError("no free cache slot")
            slot = self._free.pop()
            self._order += 1
            sess = Session(key=key, slot=slot, qos=qos or "silver",
                           extra=dict(extra or {}), born_s=now,
                           last_step_s=now, order=self._order)
            self._live[key] = sess
            return sess

    def release(self, key) -> Optional[Session]:
        """Return ``key``'s slot to the free list (EOS, stop token,
        disconnect, eviction).  Device memory is untouched — the next
        occupant's prefill overwrites it."""
        with self._lock:
            sess = self._live.pop(key, None)
            if sess is not None:
                self._free.append(sess.slot)
            return sess

    def touch(self, key) -> None:
        sess = self.get(key)
        if sess is not None:
            sess.last_step_s = self._clock()

    # -- eviction --------------------------------------------------------
    def lru_key(self):
        """Least-recently-progressed live session's key (None when
        empty) — the LRU eviction candidate."""
        with self._lock:
            if not self._live:
                return None
            return min(self._live.values(),
                       key=lambda s: s.last_step_s).key

    def aged_keys(self, max_age_s: float) -> List[Any]:
        """Sessions older (since admission) than ``max_age_s`` seconds —
        deadline-eviction candidates: a slot is a bounded LEASE, and a
        session that outlives its deadline (wedged egress, a client
        trickling an enormous continuation) is force-completed so the
        pool's turnover — and with it every retry-after hint the
        admission path hands out — stays honest."""
        if max_age_s <= 0:
            return []
        cutoff = self._clock() - max_age_s
        with self._lock:
            return [s.key for s in self._live.values()
                    if s.born_s < cutoff]
