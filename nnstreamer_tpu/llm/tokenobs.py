"""Token-level serving observability: per-session lifecycle records,
TTFT/ITL histograms, and decode-plane head-of-line blame.

The LLM tier's observability gap was granularity: PR 5 spans and PR 8
attribution see FRAMES, but a token stream's health lives between
frames — "time to first token for gold clients", "which prefill chunk
stalled whose tokens".  This module closes it with three surfaces, all
riding the existing registry/federation machinery (new metric FAMILIES,
zero wire changes):

- **Latency histograms** — ``nns_llm_ttft_us{class=}`` (admit → first
  emitted token, chunk interleave INCLUDED: TTFT is what the client
  waited, not what the prefill executable cost) and
  ``nns_llm_itl_us{class=}`` (inter-token gap between consecutive
  emitted tokens).  Shed / rejected / evicted streams never observe —
  a fast refusal must not flatter p50 and a reaped zombie must not
  poison p99; they land in the terminal-cause counters instead.
- **Terminal-cause counters** —
  ``nns_llm_session_terminal_total{cause=}`` with the closed cause set
  :data:`TERMINAL_CAUSES`: every stream ends exactly once, with a name.
- **Head-of-line blame** — each inter-token gap is attributed by
  diffing the engine's :class:`~nnstreamer_tpu.llm.engine.PhaseClock`
  integer totals (:meth:`~nnstreamer_tpu.llm.engine.PhaseClock.
  totals_ns`) at consecutive tokens and folding phases through
  :data:`PHASE_BLAME` (decode-compute | prefill-chunk-steal | compile |
  admission | egress | idle).  Because the snapshots partition the
  decode thread's wall time EXACTLY, a session's accumulated blame sums
  to its admit→terminal window by identity — conservation is
  arithmetic, not measurement (the PR 8 spine at token granularity).

Completed records land in a bounded ring the flight recorder drains
into per-session timeline lanes (:meth:`TokenObs.chrome_events` — the
same mono-ns timebase as the PR 5 tracer, so session lanes merge into
the client/server trace with no re-basing of their own).

Zero-cost-when-off discipline: the element only constructs a
:class:`TokenObs` when its ``token-obs`` property is on; every hot-path
hook site gates on one ``sess.obs is not None`` / ``self._tok_obs is
not None`` attribute test (the ``annotation_active()`` pattern, gated
<2 % by ``tools/hotpath_bench.py --stage llmobs --assert``).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional

from ..analysis.sanitizer import make_lock
from ..obs.metrics import REGISTRY, MetricsRegistry

#: token-latency histogram families (class-labeled by QoS)
TTFT_US = "nns_llm_ttft_us"
ITL_US = "nns_llm_itl_us"
#: every stream ends exactly once, with a cause
TERMINAL_TOTAL = "nns_llm_session_terminal_total"
#: aggregate blame, monotone ns per cause — federates like any counter
BLAME_NS_TOTAL = "nns_llm_blame_ns_total"
#: paged-cache churn counter the element mirrors from pool stats
PAGES_RECLAIMED_TOTAL = "nns_llm_pages_reclaimed_total"

#: the closed terminal-cause set: stop-token, granted length exhausted,
#: deadline eviction, client vanished, admission shed, deterministic
#: refusal (malformed / over-length).  ``shed``/``reject`` streams were
#: never admitted — counted here, NEVER observed in the histograms.
TERMINAL_CAUSES = ("stop", "max_new", "evict", "disconnect", "shed",
                   "reject")

#: head-of-line blame causes, and the PhaseClock phase → cause fold.
#: ``prefill`` and ``llm-prefill-chunk`` both fold to
#: ``prefill-chunk-steal``: from a WAITING session's point of view any
#: prefill occupying the single decode thread is stolen time (its own
#: pre-first-token prefill included — TTFT's cost, named).
BLAME_CAUSES = ("decode-compute", "prefill-chunk-steal", "compile",
                "admission", "egress", "idle")
PHASE_BLAME = {
    "decode": "decode-compute",
    "prefill": "prefill-chunk-steal",
    "llm-prefill-chunk": "prefill-chunk-steal",
    "compile": "compile",
    "admit": "admission",
    "egress": "egress",
    "idle": "idle",
}


class SessionRecord:
    """One session's lifecycle: admit → (chunks) → first token →
    steady decode → terminal, with integer blame accumulation."""

    __slots__ = ("key", "qos", "trace_id", "admit_ns", "first_ns",
                 "end_ns", "last_tok_ns", "tokens", "chunks", "cause",
                 "mark", "blame_ns", "itl_count", "itl_sum_us",
                 "itl_max_us")

    def __init__(self, key, qos: str, trace_id: int = 0) -> None:
        self.key = key
        self.qos = qos
        self.trace_id = trace_id
        self.admit_ns = 0
        self.first_ns = 0
        self.end_ns = 0
        self.last_tok_ns = 0
        self.tokens = 0
        self.chunks = 0
        self.cause = ""
        self.mark: Optional[Dict[str, int]] = None
        self.blame_ns: Dict[str, int] = {}
        self.itl_count = 0
        self.itl_sum_us = 0.0
        self.itl_max_us = 0.0

    def _absorb(self, totals: Dict[str, int]) -> None:
        """Fold the phase-total delta since the last mark into the
        blame accumulator.  Two marks partition the thread's wall time
        exactly, so over the record's life ``sum(blame_ns)`` equals the
        admit→terminal totals delta by integer identity."""
        mark = self.mark
        blame = self.blame_ns
        for phase, total in totals.items():
            d = total - (mark.get(phase, 0) if mark else 0)
            if d:
                cause = PHASE_BLAME.get(phase, phase)
                blame[cause] = blame.get(cause, 0) + d
        self.mark = totals

    def to_dict(self) -> Dict[str, Any]:
        wall_ns = max(0, self.end_ns - self.admit_ns)
        blame_sum = sum(self.blame_ns.values())
        out = {
            "key": str(self.key),
            "class": self.qos,
            "cause": self.cause,
            "tokens": self.tokens,
            "chunks": self.chunks,
            "admit_ns": self.admit_ns,
            "first_ns": self.first_ns,
            "end_ns": self.end_ns,
            "wall_ms": round(wall_ns / 1e6, 3),
            "blame_ns": dict(self.blame_ns),
            # conservation evidence: accumulated blame vs the session's
            # own admit→terminal window.  The snapshots are an exact
            # partition; the only slack is the independent clock reads
            # that stamp admit/end (sub-microsecond)
            "blame_conserved_pct": round(
                100.0 * blame_sum / wall_ns, 3) if wall_ns else 100.0,
        }
        if self.first_ns:
            out["ttft_us"] = round((self.first_ns - self.admit_ns)
                                   / 1e3, 1)
        if self.itl_count:
            out["itl_mean_us"] = round(self.itl_sum_us
                                       / self.itl_count, 1)
            out["itl_max_us"] = round(self.itl_max_us, 1)
        if self.trace_id:
            out["trace_id"] = f"{self.trace_id:x}"
        return out


class TokenObs:
    """The element's token-level recorder: one per ``tensor_llm``
    element, mutated only on the decode thread (the single-pusher
    contract); the bounded completed-record ring is the only
    cross-thread surface, under its own leaf lock."""

    def __init__(self, phases, clock_ns=None,
                 registry: MetricsRegistry = REGISTRY,
                 labels: Optional[Dict[str, str]] = None,
                 capacity: int = 256) -> None:
        from ..obs.clock import mono_ns

        self._phases = phases
        self._clock_ns = clock_ns if clock_ns is not None else mono_ns
        self._registry = registry
        self._labels = dict(labels or {})
        self._lock = make_lock("leaf")
        self._ring: "deque[SessionRecord]" = deque(maxlen=max(
            1, int(capacity)))
        self._hists: Dict[Any, Any] = {}
        self._ctrs: Dict[Any, Any] = {}
        #: published-so-far marks for the monotone blame counters
        self._blame_pub: Dict[str, int] = {}

    # -- metric plumbing -------------------------------------------------
    def _hist(self, family: str, qos: str):
        h = self._hists.get((family, qos))
        if h is None:
            h = self._registry.histogram(family, **{**self._labels,
                                                    "class": qos})
            self._hists[(family, qos)] = h
        return h

    def _ctr(self, family: str, **extra: str):
        key = (family, tuple(sorted(extra.items())))
        c = self._ctrs.get(key)
        if c is None:
            c = self._registry.counter(family, **{**self._labels,
                                                  **extra})
            self._ctrs[key] = c
        return c

    # -- lifecycle hooks (decode thread only) ----------------------------
    def on_admit(self, sess) -> None:
        ctx = sess.extra.get("nns_trace") if sess.extra else None
        rec = SessionRecord(sess.key, sess.qos or "silver",
                            trace_id=getattr(ctx, "trace_id", 0) or 0)
        rec.admit_ns = self._clock_ns()
        rec.mark = self._phases.totals_ns()
        sess.obs = rec

    def on_chunk(self, sess) -> None:
        rec = sess.obs
        if rec is not None:
            rec.chunks += 1

    def on_token(self, sess) -> None:
        """One emitted token: blame the gap, observe TTFT on the
        first, ITL on every later one.  Called AFTER the token frame's
        push, so first-token latency includes its egress — TTFT is what
        the wire saw, not what the executable cost."""
        rec = sess.obs
        if rec is None:
            return
        now = self._clock_ns()
        rec._absorb(self._phases.totals_ns())
        rec.tokens += 1
        if rec.first_ns == 0:
            rec.first_ns = now
            self._hist(TTFT_US, rec.qos).observe(
                max(0.0, (now - rec.admit_ns) / 1e3))
        else:
            itl = max(0.0, (now - rec.last_tok_ns) / 1e3)
            rec.itl_count += 1
            rec.itl_sum_us += itl
            if itl > rec.itl_max_us:
                rec.itl_max_us = itl
            self._hist(ITL_US, rec.qos).observe(itl)
        rec.last_tok_ns = now

    def on_terminal(self, sess, cause: str) -> None:
        """Close the stream's record under ``cause`` and count it.
        Only counting happens for latency purposes: an evicted /
        disconnected stream's terminal marker frame is NOT a token and
        must not observe ITL."""
        self._ctr(TERMINAL_TOTAL, cause=cause,
                  **{"class": sess.qos or "silver"}).inc()
        rec = sess.obs
        if rec is None:
            return
        sess.obs = None
        rec.end_ns = self._clock_ns()
        rec._absorb(self._phases.totals_ns())
        rec.mark = None
        rec.cause = cause
        with self._lock:
            self._ring.append(rec)

    def on_refused(self, qos: str, cause: str) -> None:
        """A stream that never got a slot (``shed``) or could never
        succeed (``reject``): terminal-cause accounting only — by
        construction these cannot reach the latency histograms."""
        self._ctr(TERMINAL_TOTAL, cause=cause,
                  **{"class": qos or "silver"}).inc()

    # -- aggregates ------------------------------------------------------
    def sync_blame_counters(self) -> None:
        """Mirror the PhaseClock's per-cause totals into the monotone
        ``nns_llm_blame_ns_total{cause=}`` counters (the federable
        aggregate: per-phase totals only grow, so the deltas are
        always >= 0).  Serialized under the leaf lock: the decode
        thread syncs periodically and a snapshotting reader (soak,
        flight recorder) may force one — an unlocked race would
        double-publish a delta."""
        causes: Dict[str, int] = {}
        for phase, ns in self._phases.totals_ns().items():
            cause = PHASE_BLAME.get(phase, phase)
            causes[cause] = causes.get(cause, 0) + ns
        with self._lock:
            for cause, ns in causes.items():
                prev = self._blame_pub.get(cause, 0)
                if ns > prev:
                    self._ctr(BLAME_NS_TOTAL,
                              cause=cause).inc(ns - prev)
                    self._blame_pub[cause] = ns

    def blame_report(self) -> Dict[str, Any]:
        """Decode-thread wall-time blame shares.  These fold the
        PhaseClock partition, so the shares sum to 100 % of thread wall
        time by the same identity the phase report carries."""
        causes: Dict[str, int] = {}
        for phase, ns in self._phases.totals_ns().items():
            cause = PHASE_BLAME.get(phase, phase)
            causes[cause] = causes.get(cause, 0) + ns
        total = max(1, sum(causes.values()))
        return {"causes_ns": causes,
                "shares_pct": {c: round(100.0 * v / total, 3)
                               for c, v in sorted(causes.items())},
                "conserved_pct": 100.0}

    def records(self) -> List[Dict[str, Any]]:
        """Completed per-session records, oldest first (bounded ring —
        the flight recorder's session-timeline feed)."""
        with self._lock:
            recs = list(self._ring)
        return [r.to_dict() for r in recs]

    # -- timeline export -------------------------------------------------
    def chrome_events(self, pid: int = 9, offset_ns: int = 0
                      ) -> List[Dict[str, Any]]:
        """Chrome ``trace_event`` session lanes: one tid per completed
        session, a ``ttft`` span admit→first-token and a ``decode``
        span first→terminal carrying cause/tokens/blame.  Timestamps
        are the tracer's mono-ns base / 1000, so these merge into the
        PR 5 client/server export with the SAME ``offset_ns`` re-basing
        the span ring uses."""
        with self._lock:
            recs = list(self._ring)
        events: List[Dict[str, Any]] = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": "llm-sessions"},
        }]
        for tid, rec in enumerate(recs, start=1):
            events.append({"name": "thread_name", "ph": "M",
                           "pid": pid, "tid": tid,
                           "args": {"name": f"session {rec.key}"}})
            first = rec.first_ns or rec.end_ns
            if first > rec.admit_ns:
                events.append({
                    "name": "ttft", "cat": "llm-session", "ph": "X",
                    "pid": pid, "tid": tid,
                    "ts": (rec.admit_ns + offset_ns) / 1000.0,
                    "dur": (first - rec.admit_ns) / 1000.0,
                    "args": {"class": rec.qos, "chunks": rec.chunks},
                })
            if rec.first_ns and rec.end_ns > rec.first_ns:
                events.append({
                    "name": "decode", "cat": "llm-session", "ph": "X",
                    "pid": pid, "tid": tid,
                    "ts": (rec.first_ns + offset_ns) / 1000.0,
                    "dur": (rec.end_ns - rec.first_ns) / 1000.0,
                    "args": {"class": rec.qos, "cause": rec.cause,
                             "tokens": rec.tokens,
                             "blame_ns": dict(rec.blame_ns)},
                })
        events.sort(key=lambda e: (e["ph"] != "M", e.get("ts", 0.0)))
        return events


def default_llm_signals(pages: int = 0,
                        ttft_p99_us: float = 2_000_000.0,
                        reclaim_rate: float = 50.0,
                        min_hold_s: float = 5.0) -> List[Any]:
    """The LLM tier's default :class:`~nnstreamer_tpu.obs.timeseries.
    SustainedSignal` sources: free-page exhaustion (an *idle-style*
    below-threshold condition on the gauge), paged-reclaim churn (rate
    over the mirror counter — sustained churn means the arena is
    thrashing its prefix cache), and sustained TTFT p99 over budget.
    ``pages=0`` (dense pool) drops the paged signals."""
    from ..obs.timeseries import SustainedSignal

    out: List[Any] = [
        SustainedSignal("llm-ttft-p99-high", TTFT_US,
                        threshold=ttft_p99_us, min_hold_s=min_hold_s,
                        kind="p99"),
    ]
    if pages > 0:
        out.append(SustainedSignal(
            "llm-free-pages-low", "nns_llm_free_pages",
            threshold=max(1.0, pages / 10.0), min_hold_s=min_hold_s,
            direction="below", kind="gauge",
            disarm_above=max(2.0, pages / 4.0)))
        out.append(SustainedSignal(
            "llm-reclaim-churn", PAGES_RECLAIMED_TOTAL,
            threshold=reclaim_rate, min_hold_s=min_hold_s,
            kind="rate"))
    return out
