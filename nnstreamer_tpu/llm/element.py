"""``tensor_llm``: the stateful token-streaming serving element.

One element sits between ``tensor_query_serversrc`` and
``tensor_query_serversink`` and turns the request/response serving
plane into a continuous-batching token stream server:

- **requests in**: one ``(N,) int32`` frame per session —
  ``[prompt_len, max_new_tokens, stop_token, prompt...]`` (in-band
  header framing, so the wire caps stay one static tensor).  The
  serversrc's queue-depth admission and QoS negotiation apply unchanged
  BEFORE the frame reaches this element.
- **slot admission**: a session needs a KV-cache slot
  (:class:`~nnstreamer_tpu.llm.pool.KVCachePool`); no free slot ⇒ the
  request is answered with an explicit ``T_SHED`` + retry-after through
  the paired server (``QueryServer.shed_frame``) — never queued as
  unbounded memory.
- **decode loop**: ONE decode thread owns admission, prefill
  (flash-path, ``models/streamformer_lm.prefill_kv``), the per-step
  padded ``decode_step_pooled`` invoke over the whole resident set, and
  every downstream push — so per-client token order is exact BY
  CONSTRUCTION (single pusher, bucket re-forms every step, sessions
  join mid-flight after their prefill and leave on stop-token /
  max-new / disconnect).
- **streaming egress**: per-token ``[1, 1] int32`` frames flow to the
  serversink carrying the request's extras (client id, wire seq, QoS,
  trace context), ``pts`` = token index, and ``extra["nns_more"]`` on
  every frame but the last (the server's in-flight unit stays open for
  the whole stream, so drain waits for completions).
- **eviction**: client disconnect (polled via the server table) and a
  progress deadline reclaim slots mid-stream; EOS / ``Pipeline.drain``
  finish resident sessions before the element lets go.

Stop-token semantics (the client contract): the stream for one request
ends when the client has received ``max_new_tokens`` frames, or earlier
when a frame's token equals the request's ``stop_token`` (that frame is
delivered and IS the end marker); a NEGATIVE token is unconditionally
terminal — vocab tokens are never negative, so refusal/eviction
markers end a stream even for requests that set no stop token.  A
prompt too long for the cache (``prompt_len + max_new > max_seq``) is
answered with a single stop-token frame — a deterministic refusal, not
a shed (retrying an over-length prompt can never succeed).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

from ..analysis.sanitizer import make_condition
from ..pipeline.caps import Caps
from ..pipeline.element import Element, EOSEvent, FlowReturn
from ..pipeline.registry import register_element
from ..tensor.buffer import TensorBuffer
from ..tensor.caps_util import tensors_template_caps

#: request header length: [prompt_len, max_new_tokens, stop_token]
REQ_HEADER = 3


class _Request:
    """A parsed, slab-free copy of one request frame (the pooled wire
    slab releases the moment chain() returns)."""

    __slots__ = ("key", "prompt", "max_new", "stop_token", "qos",
                 "extra", "born_s", "truncated")

    def __init__(self, key, prompt, max_new, stop_token, qos, extra,
                 born_s, truncated=False) -> None:
        self.key = key
        self.prompt = prompt
        self.max_new = max_new
        self.stop_token = stop_token
        self.qos = qos
        self.extra = extra
        self.born_s = born_s
        #: the request asked for MORE than the server's max-new-tokens
        #: cap: the stream must end with an explicit terminal marker
        #: frame, or the client (counting toward ITS ask) would hang
        self.truncated = truncated


@register_element
class TensorLLM(Element):
    FACTORY = "tensor_llm"
    PROPERTIES = {
        "custom": (None, "streamformer_lm sizing grammar "
                         "(models/streamformer_lm.config_from_custom): "
                         "layers/width/heads/head_dim/mlp/vocab/"
                         "experts/max_seq/dtype — max_seq MUST be "
                         "named (it times slots is the cache memory "
                         "bound)"),
        "seed": (0, "deterministic weight seed"),
        "slots": (8, "KV-cache slots = max concurrently-resident "
                     "sessions; cache memory = (slots+1) x layers x "
                     "max_seq x heads x head_dim x 2 x itemsize, fixed "
                     "at start"),
        "batch": (4, "decode bucket capacity: resident sequences "
                     "advanced per shared device step (> slots is a "
                     "misconfig — the bucket could never fill)"),
        "max-new-tokens": (64, "hard cap on one session's continuation "
                               "(requests asking more are clamped)"),
        "prefill": ("auto", "prompt path: auto (flash where the length "
                            "gate says it wins) | flash | naive | step "
                            "(token-by-token through the decode loop — "
                            "the decode-without-prefill misconfig path)"),
        "id": (-1, "paired query-server table id: >= 0 enables T_SHED "
                   "egress for slot sheds and disconnect pruning "
                   "(sessions of vanished clients reclaim their slot); "
                   "-1 = standalone (appsrc/tensor_sink pipelines — "
                   "sheds emit a stop-token frame tagged "
                   "extra['nns_llm_shed'])"),
        "admit-timeout-ms": (0.0, "how long a request may wait for a "
                                  "slot before shedding (0 = shed "
                                  "immediately when no slot is free)"),
        "session-timeout-ms": (0.0, "slot-lease deadline: a session "
                                    "older than this (since admission) "
                                    "is force-completed with a "
                                    "terminal stop-token frame and its "
                                    "slot reclaimed (0 = off; max-new "
                                    "already bounds well-behaved "
                                    "streams)"),
        "queue-depth": (0, "pending-request bound before chain() "
                           "backpressures (0 = 2 x slots)"),
        "page-size": (0, "KV-cache page size in tokens: > 0 serves "
                         "from the block-paged arena (memory scales "
                         "with what a session USES, not max_seq); "
                         "must tile max_seq evenly; 0 (default) = the "
                         "dense per-session max_seq slot pool — paged "
                         "serving is explicit opt-in so dense "
                         "reference configs stay dense"),
        "pages": (0, "paged arena size in pages; 0 = "
                     "(slots+1) x max_seq / page_size - 1 — byte-"
                     "identical arena to the dense pool at the same "
                     "slots (the apples-to-apples residency sizing)"),
        "prefill-chunk": (-1, "interleaved prefill chunk in tokens: "
                              "the decode loop advances one bounded "
                              "chunk between decode steps so a long "
                              "prompt cannot stall resident streams; "
                              "0 = whole-prompt prefill; -1 = auto "
                              "(32 when paged, off when dense)"),
        "prefix-cache": (-1, "content-hash prefix reuse over full "
                             "prompt pages (chain-hashed, refcounted, "
                             "copy-on-write): 1 on / 0 off / -1 auto "
                             "(on when paged; requires pages)"),
        "token-obs": (1, "token-level observability plane: per-session "
                         "lifecycle records, TTFT/ITL histograms "
                         "(class-labeled), terminal-cause counters and "
                         "head-of-line blame (llm/tokenobs.py); 0 "
                         "disables it structurally — every hot-path "
                         "hook collapses to one attribute test (the "
                         "annotation_active() discipline, gated <2% by "
                         "hotpath_bench --stage llmobs)"),
    }

    # -- pads / caps -----------------------------------------------------
    def _make_pads(self):
        self.add_sink_pad(tensors_template_caps(), "sink")
        self.add_src_pad(tensors_template_caps(), "src")

    def set_caps(self, pad, caps):
        from ..tensor.caps_util import config_from_caps

        cfg = config_from_caps(caps)
        info = cfg.info
        if info.num_tensors != 1:
            raise ValueError(f"{self.name}: request caps must carry ONE "
                             f"int32 tensor (got {info.num_tensors})")
        t = info[0]
        if str(t.np_dtype) != "int32" or len(t.np_shape) != 1 \
                or t.np_shape[0] < REQ_HEADER + 1:
            raise ValueError(
                f"{self.name}: request tensor must be (N,) int32 with "
                f"N >= {REQ_HEADER + 1} ([prompt_len, max_new, "
                f"stop_token, prompt...]); got {t.np_shape} "
                f"{t.np_dtype}")
        self._req_cap = int(t.np_shape[0])
        self.announce_src_caps(Caps.from_string(
            "other/tensors,format=static,num_tensors=1,dimensions=1:1,"
            "types=int32,framerate=0/1"))

    # -- verifier hook ---------------------------------------------------
    def static_check(self):
        from ..filter.framework import FilterProperties
        from ..models.streamformer_lm import config_from_custom

        out = []

        def _num(key, default):
            val = self.get_property(key)
            if val is None or val == "":
                return default
            try:
                # NOT `val or default`: 0 is a meaningful setting here
                # (page-size=0 = dense pool) and must not read back as
                # the default
                return int(val)
            except (TypeError, ValueError):
                out.append(("error", f"llm-bad-{key}",
                            f"{self.name}: {key}={val!r} is not an "
                            "integer"))
                return default

        slots = _num("slots", 8)
        batch = _num("batch", 4)
        if slots < 1 or batch < 1:
            out.append(("warning", "misconfig",
                        f"{self.name}: slots/batch below 1 is clamped "
                        "to 1 at start"))
            slots, batch = max(1, slots), max(1, batch)
        if slots < batch:
            out.append(("error", "llm-slots-lt-batch",
                        f"{self.name}: slots={slots} < batch={batch}: "
                        "the decode bucket is wider than the session "
                        "pool — it could never fill; size slots >= "
                        "batch (cache memory scales with slots, "
                        "throughput with filled batch)"))
        ps = _num("page-size", 0)
        pages = _num("pages", 0)
        chunk = _num("prefill-chunk", -1)
        pfx = _num("prefix-cache", -1)
        custom = FilterProperties.parse_custom(self.custom)
        if ps < 0 or pages < 0:
            out.append(("error", "llm-page-size",
                        f"{self.name}: page-size={ps} / pages={pages} "
                        "below 0 is meaningless (0 = dense pool / "
                        "auto-sized arena)"))
        elif ps > 0 and "max_seq" in custom:
            try:
                max_seq = int(custom["max_seq"])
            except (TypeError, ValueError):
                max_seq = 0
            if max_seq > 0 and (ps > max_seq or max_seq % ps != 0):
                out.append(("error", "llm-page-size",
                            f"{self.name}: page-size={ps} must tile "
                            f"max_seq={max_seq} evenly (block tables "
                            "map position j to page j//page_size; a "
                            "ragged last page would alias positions)"))
        if ps == 0 and (pfx == 1 or chunk > 0):
            out.append(("error", "llm-prefix-without-pages",
                        f"{self.name}: prefix-cache={pfx} / "
                        f"prefill-chunk={chunk} with page-size=0: "
                        "prefix reuse shares content-hashed PAGES and "
                        "chunked prefill writes into them — neither "
                        "lever exists over dense per-session slots; "
                        "set page-size > 0 or drop both"))
        if "max_seq" not in custom:
            out.append(("error", "llm-no-max-seq",
                        f"{self.name}: custom= names no max_seq — the "
                        "KV-cache slot shape (and with it the tier's "
                        "whole cache memory, slots x layers x max_seq "
                        "x heads x head_dim x 2) would be an implicit "
                        "default; the serving tier must size its cache "
                        "explicitly"))
        else:
            try:
                config_from_custom(custom)
            except (ValueError, TypeError) as exc:
                out.append(("error", "misconfig",
                            f"{self.name}: custom= rejected: {exc}"))
        mode = str(self.prefill or "auto")
        if mode not in ("auto", "flash", "naive", "step"):
            out.append(("error", "misconfig",
                        f"{self.name}: prefill={mode!r} (want auto | "
                        "flash | naive | step)"))
        elif mode == "step":
            out.append(("warning", "llm-decode-without-prefill",
                        f"{self.name}: prefill=step decodes each "
                        "prompt token-by-token through the decode "
                        "loop: correct, but the prompt costs T GEMV "
                        "steps and the flash-attention prefill (which "
                        "never materializes (T,T) scores) is bypassed "
                        "— intended only for tiny prompts or "
                        "debugging"))
        return out

    # -- lifecycle -------------------------------------------------------
    def start(self):
        from ..filter.framework import FilterProperties
        from ..models.registry import host_init
        from ..models.streamformer_lm import config_from_custom
        from ..obs.clock import mono_ns
        from ..parallel.train_step import init_params
        from .engine import DecodeEngine
        from .pool import KVCachePool

        custom = FilterProperties.parse_custom(self.custom)
        self.cfg = config_from_custom(custom)
        # for slots/batch/max_new_tokens, 0 and unset both clamp to 1:
        # the `or` default loses nothing under max()
        # nnslint: allow(falsy-zero-default)
        self._slots = max(1, int(self.slots or 1))
        # nnslint: allow(falsy-zero-default)
        self._batch = max(1, int(self.batch or 1))
        # nnslint: allow(falsy-zero-default)
        self._max_new_cap = max(1, int(self.max_new_tokens or 1))
        self._admit_timeout = max(0.0,
                                  float(self.admit_timeout_ms or 0)) / 1e3
        self._sess_timeout = max(0.0,
                                 float(self.session_timeout_ms or 0)) / 1e3
        self._depth = int(self.queue_depth or 0) or 2 * self._slots
        params = host_init(
            lambda: init_params(self.cfg, int(self.seed or 0)))
        ps = max(0, int(self.page_size if self.page_size is not None
                        else 0))
        chunk = int(self.prefill_chunk
                    if self.prefill_chunk is not None else -1)
        pfx = int(self.prefix_cache
                  if self.prefix_cache is not None else -1)
        if ps > 0:
            from .paged import PagedKVCachePool

            table_max = self.cfg.max_seq // ps
            pages = int(self.pages or 0) \
                or (self._slots + 1) * table_max - 1
            self.pool = PagedKVCachePool(
                self.cfg, pages=pages, page_size=ps,
                slots=self._slots, prefix_cache=(pfx != 0))
            self._chunk = 32 if chunk < 0 else chunk
            if str(self.prefill or "auto") == "step":
                self._chunk = 0   # prompt rides the decode grid instead
        else:
            self.pool = KVCachePool(self.cfg, self._slots)
            self._chunk = 0
        self.engine = DecodeEngine(params, self.cfg, self.pool,
                                   capacity=self._batch,
                                   prefill_mode=str(self.prefill
                                                    or "auto"),
                                   chunk=self._chunk)
        self.engine.warmup()
        self._mono_ns = mono_ns
        self._cv = make_condition("llm.engine")
        self._pending: List[_Request] = []   # bounded by _depth (cv)
        self._stopping = False
        self._flushing = False
        self._req_n = 0                      # standalone session keys
        self.shed_total = 0
        self.rejected_total = 0
        self.evicted_total = 0
        self.sessions_total = 0
        self._register_gauges()
        self._thread = threading.Thread(target=self._decode_loop,
                                        daemon=True,
                                        name=f"llm-decode:{self.name}")
        self._thread.start()

    def _register_gauges(self) -> None:
        from ..obs.metrics import REGISTRY, Gauge

        labels = {"element": self.name,
                  "pipeline": getattr(self.pipeline, "name", "") or ""}
        eng, pool = self.engine, self.pool
        # token-level observability plane: constructed only when on —
        # when off, self._tok_obs is None and every hook site in the
        # decode loop pays exactly one attribute test
        self._tok_obs = None
        if int(self.token_obs if self.token_obs is not None else 1):
            from .tokenobs import TokenObs

            self._tok_obs = TokenObs(eng.phases, labels=dict(labels))
        rate_state = {"tokens": None, "t": None}

        def _tokens_per_s() -> float:
            # scrape-to-scrape token rate (first scrape: lifetime —
            # the filter gauges' _make_rate discipline)
            import time as _time

            now = _time.monotonic()
            tokens = eng.tokens_total
            prev_t, prev_n = rate_state["t"], rate_state["tokens"]
            rate_state["t"], rate_state["tokens"] = now, tokens
            if prev_t is None or now - prev_t < 0.05:
                total = max(1e-9, eng.phases.report()["total_s"])
                return tokens / total
            return max(0.0, (tokens - prev_n) / (now - prev_t))

        self._obs_gauges = [REGISTRY.register(Gauge(n, dict(labels),
                                                    fn=f))
                            for n, f in (
            ("nns_llm_active_seqs", lambda: pool.live),
            ("nns_llm_cache_occupancy", lambda: pool.occupancy),
            ("nns_llm_cache_bytes", pool.cache_bytes),
            ("nns_llm_tokens_per_s", _tokens_per_s),
            ("nns_llm_decode_fill",
             lambda: eng.last_fill / max(1, eng.capacity)),
            ("nns_llm_pending", lambda: len(self._pending)),
        )]
        if getattr(eng, "paged", False):
            self._obs_gauges.extend(
                REGISTRY.register(Gauge(n, dict(labels), fn=f))
                for n, f in (
                    ("nns_llm_free_pages", lambda: pool.free_pages),
                    ("nns_llm_cached_pages",
                     lambda: pool.stats()["reclaimable"]),
                    ("nns_llm_prefix_hits",
                     lambda: pool.prefix_hits),
                    ("nns_llm_prefix_tokens_reused",
                     lambda: pool.prefix_tokens_reused),
                    # prefix-hit RATE: the time-series signal sources
                    # (tokenobs.default_llm_signals) and the nns-top
                    # LLM panel read a fraction, not raw counts
                    ("nns_llm_prefix_hit_rate",
                     lambda: pool.prefix_hits
                     / max(1, pool.prefix_hits + pool.prefix_misses)),
                ))
        names = ["nns_llm_tokens_total", "nns_llm_sessions_total",
                 "nns_llm_shed_total", "nns_llm_evicted_total",
                 "nns_llm_rejected_total"]
        if getattr(eng, "paged", False):
            from .tokenobs import PAGES_RECLAIMED_TOTAL

            names.append(PAGES_RECLAIMED_TOTAL)
        self._obs_counters = {
            n: REGISTRY.counter(n, **labels) for n in names}
        self._ctr_tokens = 0    # counter mirror of engine.tokens_total
        self._ctr_reclaimed = 0  # mirror of pool.pages_reclaimed

    def stop(self):
        from ..obs.metrics import REGISTRY

        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        thread = getattr(self, "_thread", None)
        if thread is not None:
            thread.join(timeout=30)
            self._thread = None
        for g in getattr(self, "_obs_gauges", ()):
            REGISTRY.unregister(g)
        self._obs_gauges = []
        self.engine = None
        self.pool = None

    def unblock(self):
        with self._cv:
            self._stopping = True
            self._cv.notify_all()

    def health_state(self):
        pool = getattr(self, "pool", None)
        if pool is not None and pool.admission.draining:
            return "draining"
        return None

    def drain(self, deadline: float = 5.0) -> None:
        """Pipeline.drain hook: stop admitting sessions (new requests
        shed with a drain-sized retry-after), finish every resident
        stream, within ``deadline``."""
        pool = getattr(self, "pool", None)
        if pool is None:
            return
        pool.admission.start_drain(deadline)
        with self._cv:
            self._cv.notify_all()
            self._cv.wait_for(
                lambda: not self._pending and pool.live == 0,
                timeout=max(0.0, deadline))

    # -- ingress ---------------------------------------------------------
    def chain(self, pad, buf: TensorBuffer) -> FlowReturn:
        arr = np.asarray(buf.np(0)).reshape(-1)
        bad = None
        plen = 0
        if arr.shape[0] < REQ_HEADER + 1:
            bad = (f"request frame too short ({arr.shape[0]} < "
                   f"{REQ_HEADER + 1})")
        else:
            plen = int(arr[0])
            if plen < 1 or plen > arr.shape[0] - REQ_HEADER:
                bad = (f"prompt_len={plen} out of range for a "
                       f"{arr.shape[0]}-element request frame")
        extra = dict(buf.extra)
        if bad is not None:
            if extra.get("query_client_id") is None:
                # developer path (appsrc tests): loud
                raise ValueError(f"{self.name}: {bad}")
            # serving path: a malformed frame is a CLIENT error — it
            # must not error the pipeline every other client shares.
            # A reject request rides the decode thread (the single
            # pusher) and is answered with one terminal frame there,
            # settling the request's in-flight unit.
            from ..utils.log import ml_logw

            ml_logw("%s: %s — answering with a terminal frame",
                    self.name, bad)
            prompt = None
            asked, max_new, stop_token = 0, 0, -1
        else:
            asked = max(1, int(arr[1]))
            max_new = min(self._max_new_cap, asked)
            stop_token = int(arr[2])
            # slab-free copy: the request's pooled wire slab releases
            # when this buffer dies at return — a disconnecting client
            # can never strand a slab behind a resident session
            prompt = np.array(arr[REQ_HEADER:REQ_HEADER + plen],
                              np.int32)
        cid = extra.get("query_client_id")
        wseq = extra.get("query_seq")
        with self._cv:
            self._req_n += 1
            # the local counter keeps keys unique even against a buggy
            # or hostile client REUSING a wire seq while its first
            # stream is resident — a key collision must never reach
            # pool.acquire's ValueError (one client's duplicate would
            # error the pipeline every client shares); reply routing
            # rides the extras (cid, seq), not the key
            key = ((cid, wseq, self._req_n) if cid is not None
                   else ("local", self._req_n))
            req = _Request(key, prompt, max_new, stop_token,
                           str(extra.get("nns_class", "silver")),
                           extra, self._now(),
                           truncated=(prompt is not None
                                      and asked > max_new))
            # bounded pending: backpressure the serving thread (and
            # through it the serversrc's bounded queue, whose admission
            # sheds at ITS watermarks) rather than queue unbounded
            self._cv.wait_for(
                lambda: len(self._pending) < self._depth
                or self._stopping)
            if self._stopping:
                return FlowReturn.EOS
            self._pending.append(req)
            self._cv.notify_all()
        return FlowReturn.OK

    def _now(self) -> float:
        return self._mono_ns() / 1e9

    # -- events ----------------------------------------------------------
    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            # finish every admitted stream before EOS crosses: resident
            # sessions are ADMITTED work (inflight-counted server-side)
            with self._cv:
                self._flushing = True
                self._cv.notify_all()
                self._cv.wait_for(
                    lambda: self._stopping
                    or (not self._pending
                        and (self.pool is None or self.pool.live == 0)),
                    timeout=120.0)
                self._flushing = False
        super().on_event(pad, event)

    # -- decode loop -----------------------------------------------------
    def _server(self):
        sid = int(self.id if self.id is not None else -1)
        if sid < 0:
            return None
        from ..query.server import peek_server

        return peek_server(sid)

    def _decode_loop(self) -> None:
        try:
            self._decode_loop_inner()
        except Exception as exc:  # noqa: BLE001 — surfaced as pipeline err
            if self.pipeline is not None:
                self.pipeline.post_error(self, exc)

    def _decode_loop_inner(self) -> None:
        eng = self.engine
        pool = self.pool
        rr = 0                         # round-robin cursor
        while True:
            with self._cv:
                if self._stopping:
                    return
                if not self._pending and pool.live == 0:
                    eng.phases.enter("idle")
                    # idle tick bounds disconnect-prune latency too
                    self._cv.wait(0.05)
                    if self._stopping:
                        return
                taken, self._pending = self._pending, []
                self._cv.notify_all()   # free chain() backpressure slots
            self._prune_sessions()
            requeue = self._admit(taken)
            sessions = [s for s in pool.sessions()
                        if not getattr(s, "prefilling", False)]
            if sessions:
                n = len(sessions)
                pick = [sessions[(rr + i) % n]
                        for i in range(min(n, self._batch))]
                rr = (rr + len(pick)) % max(1, n)
                self._run_step(pick)
            # interleaved chunked prefill: ONE bounded chunk per loop
            # iteration, so a long prompt time-shares the decode thread
            # with resident streams instead of stalling them (with no
            # decodable sessions the loop spins here chunk after chunk
            # — full prefill throughput when there is no one to starve)
            self._advance_prefills()
            if requeue:
                with self._cv:
                    self._pending[:0] = requeue
            with self._cv:
                if not self._pending and pool.live == 0:
                    self._cv.notify_all()   # EOS/drain waiters

    # -- admission -------------------------------------------------------
    def _admit(self, reqs: List[_Request]) -> List[_Request]:
        """Admit / shed / requeue pending requests.  Returns the
        requests still inside their admit-timeout window (no slot yet,
        not shed by policy)."""
        eng, pool = self.engine, self.pool
        requeue: List[_Request] = []
        for req in reqs:
            prev = eng.phases.enter("admit")
            try:
                if req.prompt is None \
                        or len(req.prompt) + req.max_new \
                        > self.cfg.max_seq:
                    # deterministic refusal (malformed / over-length):
                    # a retry can never succeed, so this is a terminal
                    # stop-token answer, not a shed
                    self.rejected_total += 1
                    self._obs_counters["nns_llm_rejected_total"].inc()
                    if self._tok_obs is not None:
                        self._tok_obs.on_refused(req.qos, "reject")
                    self._emit(req.extra, req.stop_token, 0, last=True)
                    continue
                verdict = pool.admit(req.qos,
                                     no_slot_retry_s=eng
                                     .retry_after_hint(),
                                     prompt=req.prompt,
                                     max_new=req.max_new)
                if verdict is not None:
                    if self._admit_timeout > 0 \
                            and self._now() - req.born_s \
                            < self._admit_timeout \
                            and not pool.admission.draining:
                        requeue.append(req)
                    else:
                        self._shed(req, verdict)
                    continue
                sess = pool.acquire(req.key, qos=req.qos,
                                    extra=req.extra, prompt=req.prompt,
                                    max_new=req.max_new)
                sess.max_new = req.max_new
                sess.stop_token = req.stop_token
                sess.truncated = req.truncated
                self.sessions_total += 1
                self._obs_counters["nns_llm_sessions_total"].inc()
                if self._tok_obs is not None:
                    # the lifecycle record opens HERE, inside the admit
                    # phase: TTFT measures admit → first emitted token,
                    # chunk interleave and bucket waits included — what
                    # the client waited, not what one executable cost
                    self._tok_obs.on_admit(sess)
                if self._chunk > 0:
                    # chunked prefill: the session joins RESIDENT but
                    # not yet decodable — the decode loop advances one
                    # bounded chunk per iteration (_advance_prefills),
                    # so this prompt cannot stall the streams already
                    # emitting tokens; its first token emits when the
                    # last chunk lands
                    continue
                t0 = self._mono_ns()
                first = eng.prefill(sess, req.prompt)
                tracer = self._tracer()
                if tracer is not None:
                    ctx = req.extra.get("nns_trace")
                    if ctx is not None and ctx.trace_id:
                        # the session's one-time prompt cost, in the
                        # CLIENT's merged timeline (obs/attrib.py
                        # llm-prefill state)
                        tracer.annotate_span("llm-prefill", t0,
                                             self._mono_ns(), seq=-1,
                                             trace_id=ctx.trace_id)
                sess.next_token = first
                # the prefill's token is this session's first answer —
                # emit it NOW (time-to-first-token is the prefill, not
                # the prefill plus one bucket cycle)
                self._finish_or_emit(sess, first)
            finally:
                eng.phases.enter(prev)
        return requeue

    def _shed(self, req: _Request, retry_after_s: float) -> None:
        self.shed_total += 1
        self._obs_counters["nns_llm_shed_total"].inc()
        if self._tok_obs is not None:
            # counted, never observed: a fast shed must not flatter
            # the admitted-traffic TTFT distribution
            self._tok_obs.on_refused(req.qos, "shed")
        srv = self._server()
        if srv is not None:
            srv.shed_frame(req.extra, retry_after_s)
            return
        # standalone pipelines (appsrc/tensor_sink): the shed is a
        # tagged stop-token frame so the consumer still sees an
        # explicit, final answer
        extra = dict(req.extra)
        extra["nns_llm_shed"] = retry_after_s
        self._emit(extra, req.stop_token, 0, last=True)

    def _advance_prefills(self) -> None:
        """Advance ONE bounded prefill chunk — the oldest prefilling
        session, admission order — and emit its first token when the
        prompt completes.  One chunk per decode-loop iteration is the
        interleave contract: a 2048-token prompt costs resident streams
        ``ceil(2048/chunk)`` extra bounded slices, never one monolithic
        stall (the PhaseClock's ``llm-prefill-chunk`` share is the
        proof)."""
        if self._chunk <= 0:
            return
        eng, pool = self.engine, self.pool
        for sess in pool.sessions():
            if not getattr(sess, "prefilling", False):
                continue
            t0 = self._mono_ns()
            first = eng.prefill_chunk_step(sess)
            t1 = self._mono_ns()
            if self._tok_obs is not None:
                self._tok_obs.on_chunk(sess)
            tracer = self._tracer()
            if tracer is not None:
                ctx = sess.extra.get("nns_trace")
                if ctx is not None and ctx.trace_id:
                    tracer.annotate_span("llm-prefill-chunk", t0, t1,
                                         seq=-1, trace_id=ctx.trace_id)
            if first is not None:
                sess.next_token = first
                self._finish_or_emit(sess, first)
            return

    # -- stepping / egress -----------------------------------------------
    def _run_step(self, picked) -> None:
        eng = self.engine
        t0 = self._mono_ns()
        toks = eng.step(picked)
        t1 = self._mono_ns()
        self._ctr_sync()
        tracer = self._tracer()
        if tracer is not None:
            # the SHARED decode window, once per resident trace — the
            # cross-stream device-invoke convention (per-token
            # wall-clock truth, not a 1/n share)
            for sess in picked:
                ctx = sess.extra.get("nns_trace")
                if ctx is not None and ctx.trace_id:
                    tracer.annotate_span("llm-decode", t0, t1, seq=-1,
                                         trace_id=ctx.trace_id)
        for sess, tok in zip(picked, toks):
            sess.next_token = tok
            self._finish_or_emit(sess, tok)

    def _finish_or_emit(self, sess, tok: int) -> None:
        """Emit one token frame for ``sess``; release its slot when the
        stream is complete (stop token, or the granted length).  A
        TRUNCATED stream (the request asked more than the server's
        max-new-tokens cap) that runs out without hitting its stop
        token gets one extra terminal MARKER frame (the stop token, -1
        when none — negative is unconditionally terminal client-side):
        the client counts toward ITS ask, so a silently clamped stream
        would otherwise hang it until the per-token timeout."""
        sess.emitted += 1
        by_stop = sess.stop_token >= 0 and tok == sess.stop_token
        done = sess.emitted >= sess.max_new or by_stop
        marker = done and sess.truncated and not by_stop
        self._emit(sess.extra, tok, sess.emitted - 1,
                   last=done and not marker)
        if marker:
            self._emit(sess.extra, sess.stop_token, sess.emitted,
                       last=True)
        tobs = self._tok_obs
        if tobs is not None:
            # after the push: first-token latency includes its egress
            tobs.on_token(sess)
            if done:
                tobs.on_terminal(sess, "stop" if by_stop else "max_new")
        if done:
            self.pool.release(sess.key)

    def _emit(self, extra: Dict[str, Any], tok: int, index: int,
              last: bool) -> None:
        prev = self.engine.phases.enter("egress")
        try:
            out_extra = dict(extra)
            if not last:
                out_extra["nns_more"] = True
            buf = TensorBuffer(
                tensors=[np.array([[tok]], np.int32)], pts=index,
                extra=out_extra)
            # the decode thread is the only pusher: per-client frame
            # order IS emission order
            self.push(buf)
        finally:
            self.engine.phases.enter(prev)

    # -- eviction --------------------------------------------------------
    def _prune_sessions(self) -> None:
        """Reclaim slots of disconnected clients (polled on the server
        table) and deadline-overrun sessions.  Every eviction still
        EMITS a terminal stop-token frame: for a vanished client the
        reply is unsendable but settles the stream's in-flight unit
        (drain must converge), for a live one it explicitly ends the
        stream under the stop-token contract."""
        pool = self.pool
        srv = self._server()
        dead = []
        if srv is not None:
            for sess in pool.sessions():
                cid = sess.extra.get("query_client_id")
                if cid is not None and not srv.client_connected(cid):
                    dead.append((sess.key, "disconnect"))
        if self._sess_timeout > 0:
            dead.extend((k, "evict")
                        for k in pool.aged_keys(self._sess_timeout))
        for key, cause in dead:
            sess = pool.release(key)
            if sess is not None:
                self.evicted_total += 1
                self._obs_counters["nns_llm_evicted_total"].inc()
                if self._tok_obs is not None:
                    # the terminal marker frame is NOT a token: the
                    # record closes under its cause without observing
                    # TTFT/ITL (a reaped zombie must not poison p99)
                    self._tok_obs.on_terminal(sess, cause)
                self._emit(sess.extra, sess.stop_token, sess.emitted,
                           last=True)

    # -- helpers ---------------------------------------------------------
    def _tracer(self):
        pl = self.pipeline
        tracer = pl.tracer if pl is not None else None
        if tracer is not None and tracer.ring is not None:
            return tracer
        return None

    def _ctr_sync(self) -> None:
        """Mirror the engine's token count — and the paged pool's
        reclaim churn plus the blame aggregates when token obs is on —
        into the registry counters (counters are monotonic-inc only)."""
        delta = self.engine.tokens_total - self._ctr_tokens
        if delta > 0:
            self._obs_counters["nns_llm_tokens_total"].inc(delta)
            self._ctr_tokens = self.engine.tokens_total
        reclaimed = getattr(self.pool, "pages_reclaimed", 0)
        if reclaimed > self._ctr_reclaimed:
            from .tokenobs import PAGES_RECLAIMED_TOTAL

            self._obs_counters[PAGES_RECLAIMED_TOTAL].inc(
                reclaimed - self._ctr_reclaimed)
            self._ctr_reclaimed = reclaimed
        if self._tok_obs is not None:
            self._tok_obs.sync_blame_counters()
