"""nnstreamer_tpu — TPU-native tensor stream pipeline framework.

A ground-up re-design of the NNStreamer capability set (reference surveyed in
SURVEY.md) for Cloud TPU: media↔tensor stream pipelines whose inference
elements compile to XLA and run on TPU via JAX, with sharded multi-chip
execution (jax.sharding over a Mesh), a gst-launch-style pipeline language,
and a distributed tensor-query offload layer.
"""

__version__ = "0.5.0"

from .tensor import (TensorBuffer, TensorFormat, TensorInfo, TensorsConfig,
                     TensorsInfo, TensorType)
from .pipeline import (Caps, Element, FlowReturn, ParseError, Pipeline,
                       parse_launch)

__all__ = [
    "TensorType", "TensorFormat", "TensorInfo", "TensorsInfo",
    "TensorsConfig", "TensorBuffer", "Caps", "Element", "FlowReturn",
    "ParseError", "Pipeline", "parse_launch", "__version__",
]
