"""Stream capability (caps) system: typed, intersectable media descriptions.

The reference delegates caps to GStreamer (``GstCaps``/``GstStructure``) and
layers tensor semantics on top (gst_tensor_caps_from_config / …_config_from_
structure, nnstreamer_plugin_api_impl.c:1110-1393).  GStreamer is external to
the reference, so this module is a ground-up design: a small algebra of
structures whose field values are concrete values, option lists, or ranges,
with intersection / fixation / subset tests — just enough to drive the same
negotiation logic the reference elements rely on.

Caps strings look like GStreamer's for familiarity::

    other/tensors,format=static,num_tensors=1,dimensions=3:224:224,types=uint8,framerate=30/1
    video/x-raw,format=RGB,width=640,height=480,framerate=30/1
"""

from __future__ import annotations

import dataclasses
from fractions import Fraction
from typing import Any, Dict, Iterable, List, Optional, Tuple, Union


class IntRange:
    """Inclusive integer range field value (GStreamer GST_TYPE_INT_RANGE)."""

    __slots__ = ("lo", "hi")

    def __init__(self, lo: int, hi: int):
        if lo > hi:
            raise ValueError(f"empty range [{lo},{hi}]")
        self.lo, self.hi = lo, hi

    def __eq__(self, other):
        return (isinstance(other, IntRange) and self.lo == other.lo
                and self.hi == other.hi)

    def __hash__(self):
        return hash(("IntRange", self.lo, self.hi))

    def __repr__(self):
        return f"[{self.lo},{self.hi}]"

    def contains(self, v: int) -> bool:
        return self.lo <= v <= self.hi


class FractionRange:
    """Inclusive fraction range (framerates)."""

    __slots__ = ("lo", "hi")

    def __init__(self, lo: Fraction, hi: Fraction):
        if lo > hi:
            raise ValueError(f"empty range [{lo},{hi}]")
        self.lo, self.hi = lo, hi

    def __eq__(self, other):
        return (isinstance(other, FractionRange) and self.lo == other.lo
                and self.hi == other.hi)

    def __hash__(self):
        return hash(("FractionRange", self.lo, self.hi))

    def __repr__(self):
        return f"[{self.lo},{self.hi}]"

    def contains(self, v: Fraction) -> bool:
        return self.lo <= v <= self.hi


#: Full-range framerate used as the lenient default (reference intersects
#: tensor caps with framerate leniency, nnstreamer_plugin_api_impl.c:1201-1260).
ANY_FRAMERATE = FractionRange(Fraction(0, 1), Fraction(1 << 31, 1))

FieldValue = Union[int, str, Fraction, Tuple[Any, ...], IntRange, FractionRange, list]


def _intersect_value(a: FieldValue, b: FieldValue) -> Optional[FieldValue]:
    """Intersect two field values; None means empty intersection."""
    if isinstance(a, list) or isinstance(b, list):
        la = a if isinstance(a, list) else [a]
        lb = b if isinstance(b, list) else [b]
        out = []
        for va in la:
            for vb in lb:
                r = _intersect_value(va, vb)
                if r is not None and r not in out:
                    out.append(r)
        if not out:
            return None
        return out[0] if len(out) == 1 else out
    if isinstance(a, IntRange) and isinstance(b, IntRange):
        lo, hi = max(a.lo, b.lo), min(a.hi, b.hi)
        if lo > hi:
            return None
        return lo if lo == hi else IntRange(lo, hi)
    if isinstance(a, IntRange):
        return b if (isinstance(b, int) and a.contains(b)) else None
    if isinstance(b, IntRange):
        return a if (isinstance(a, int) and b.contains(a)) else None
    if isinstance(a, FractionRange) and isinstance(b, FractionRange):
        lo, hi = max(a.lo, b.lo), min(a.hi, b.hi)
        if lo > hi:
            return None
        return lo if lo == hi else FractionRange(lo, hi)
    if isinstance(a, FractionRange):
        return b if (isinstance(b, Fraction) and a.contains(b)) else None
    if isinstance(b, FractionRange):
        return a if (isinstance(a, Fraction) and b.contains(a)) else None
    return a if a == b else None


def _is_fixed_value(v: FieldValue) -> bool:
    return not isinstance(v, (list, IntRange, FractionRange))


def _fixate_value(v: FieldValue) -> FieldValue:
    if isinstance(v, list):
        return _fixate_value(v[0])
    if isinstance(v, IntRange):
        return v.lo
    if isinstance(v, FractionRange):
        # Prefer a sane default inside the range (30/1 if allowed, else lo).
        default = Fraction(30, 1)
        return default if v.contains(default) else v.lo
    return v


@dataclasses.dataclass
class Structure:
    """One media description: name + constrained fields."""

    name: str
    fields: Dict[str, FieldValue] = dataclasses.field(default_factory=dict)

    def get(self, key: str, default=None):
        return self.fields.get(key, default)

    def intersect(self, other: "Structure") -> Optional["Structure"]:
        if self.name != other.name:
            return None
        out: Dict[str, FieldValue] = {}
        for key in set(self.fields) | set(other.fields):
            if key in self.fields and key in other.fields:
                r = _intersect_value(self.fields[key], other.fields[key])
                if r is None:
                    return None
                out[key] = r
            else:
                out[key] = self.fields.get(key, other.fields.get(key))
        return Structure(self.name, out)

    def is_fixed(self) -> bool:
        return all(_is_fixed_value(v) for v in self.fields.values())

    def fixate(self) -> "Structure":
        return Structure(self.name,
                         {k: _fixate_value(v) for k, v in self.fields.items()})

    def is_subset_of(self, other: "Structure") -> bool:
        """True if every stream matching self also matches other."""
        if self.name != other.name:
            return False
        for k, v in other.fields.items():
            if k not in self.fields:
                # other constrains a field self leaves open → not subset
                if not _is_fixed_value(v):
                    continue
                return False
            if _intersect_value(self.fields[k], v) != self.fields[k]:
                return False
        return True

    def __str__(self) -> str:
        parts = [self.name]
        for k, v in self.fields.items():
            parts.append(f"{k}={_value_to_str(v)}")
        return ",".join(parts)


def _value_to_str(v: FieldValue) -> str:
    if isinstance(v, Fraction):
        return f"{v.numerator}/{v.denominator}"
    if isinstance(v, list):
        return "{" + ";".join(_value_to_str(x) for x in v) + "}"
    return str(v)


class Caps:
    """An ordered set of alternative :class:`Structure` s.

    Empty caps = "cannot link"; ``Caps.any()`` = unconstrained.
    """

    def __init__(self, structures: Optional[Iterable[Structure]] = None,
                 any_caps: bool = False):
        self.structures: List[Structure] = list(structures or [])
        self._any = any_caps

    # -- constructors --------------------------------------------------------
    @classmethod
    def any(cls) -> "Caps":
        return cls(any_caps=True)

    @classmethod
    def empty(cls) -> "Caps":
        return cls()

    @classmethod
    def from_string(cls, s: str) -> "Caps":
        """Parse a caps string: ``name,k=v,...;name2,k=v`` — alternatives
        separated by ``;``."""
        s = s.strip()
        if s in ("ANY", "any"):
            return cls.any()
        if not s:
            return cls.empty()
        structures = []
        for alt in _split_top(s, ";"):
            if alt.strip():
                structures.append(_parse_structure(alt.strip()))
        return cls(structures)

    @classmethod
    def new(cls, name: str, **fields) -> "Caps":
        return cls([Structure(name, dict(fields))])

    # -- algebra -------------------------------------------------------------
    def is_any(self) -> bool:
        return self._any

    def is_empty(self) -> bool:
        return not self._any and not self.structures

    def is_fixed(self) -> bool:
        return (not self._any and len(self.structures) == 1
                and self.structures[0].is_fixed())

    def intersect(self, other: "Caps") -> "Caps":
        if self._any:
            return Caps(list(other.structures), any_caps=other._any)
        if other._any:
            return Caps(list(self.structures))
        out = []
        for a in self.structures:
            for b in other.structures:
                r = a.intersect(b)
                if r is not None:
                    out.append(r)
        return Caps(out)

    def can_intersect(self, other: "Caps") -> bool:
        return not self.intersect(other).is_empty()

    def fixate(self) -> "Caps":
        if self._any:
            raise ValueError("cannot fixate ANY caps")
        if not self.structures:
            raise ValueError("cannot fixate EMPTY caps")
        return Caps([self.structures[0].fixate()])

    def first(self) -> Structure:
        if not self.structures:
            raise ValueError("empty caps")
        return self.structures[0]

    def append(self, other: "Caps") -> "Caps":
        if self._any or other._any:
            return Caps.any()
        return Caps(self.structures + other.structures)

    def __eq__(self, other):
        if not isinstance(other, Caps):
            return NotImplemented
        return self._any == other._any and self.structures == other.structures

    def __str__(self) -> str:
        if self._any:
            return "ANY"
        if not self.structures:
            return "EMPTY"
        return ";".join(str(s) for s in self.structures)

    def __repr__(self) -> str:
        return f"Caps({self})"


def _fraction(raw: str) -> Fraction:
    """Fraction('16/0') raises ZeroDivisionError, which would leak a
    non-ValueError out of caps parsing (fuzz-found) — a zero
    denominator is a malformed caps VALUE, i.e. a ValueError."""
    try:
        return Fraction(raw)
    except ZeroDivisionError:
        raise ValueError(f"caps fraction with zero denominator: {raw!r}")


def _parse_value(raw: str, _depth: int = 0) -> FieldValue:
    raw = raw.strip()
    if raw.startswith("{") and raw.endswith("}"):
        # caps lists don't nest semantically; a deeply nested brace
        # string is malformed input, and unbounded recursion here would
        # leak a RecursionError out of the ValueError contract
        if _depth >= 8:
            raise ValueError(f"caps value nests too deeply: {raw[:40]!r}")
        return [_parse_value(p, _depth + 1)
                for p in raw[1:-1].split(";") if p.strip()]
    if raw.startswith("[") and raw.endswith("]"):
        lo, hi = raw[1:-1].split(",")
        lo, hi = lo.strip(), hi.strip()
        if "/" in lo or "/" in hi:
            return FractionRange(_fraction(lo), _fraction(hi))
        return IntRange(int(lo), int(hi))
    if "/" in raw and all(p.strip().lstrip("-").isdigit()
                          for p in raw.split("/", 1)):
        return _fraction(raw)
    try:
        return int(raw)
    except ValueError:
        return raw


def _parse_structure(s: str) -> Structure:
    parts = [p.strip() for p in _split_fields(s)]
    name = parts[0]
    fields: Dict[str, FieldValue] = {}
    for p in parts[1:]:
        if not p:
            continue
        k, _, v = p.partition("=")
        fields[k.strip()] = _parse_value(v)
    return Structure(name, fields)


def _split_top(s: str, sep: str) -> List[str]:
    """Split on a separator at brace/bracket depth 0 only."""
    out, depth, cur = [], 0, []
    for ch in s:
        if ch in "{[":
            depth += 1
        elif ch in "}]":
            depth -= 1
        if ch == sep and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out


def _split_fields(s: str) -> List[str]:
    """Split on top-level commas (not inside {} or [])."""
    return _split_top(s, ",")
