"""Element factory registry.

Equivalent of the reference's plugin registerer
(gst/nnstreamer/registerer/nnstreamer.c:91-133 registering 22+ elements) —
but in-process: element classes register by factory name and launch-string
parsing resolves them here.
"""

from __future__ import annotations

from typing import Dict, Type

from .element import Element

_FACTORIES: Dict[str, Type[Element]] = {}


def register_element(cls: Type[Element]) -> Type[Element]:
    """Class decorator: register by ``cls.FACTORY``."""
    if not cls.FACTORY:
        raise ValueError(f"{cls.__name__} has no FACTORY name")
    _FACTORIES[cls.FACTORY] = cls
    return cls


def element_factory(name: str) -> Type[Element]:
    # Import-on-demand keeps `import nnstreamer_tpu` light: the standard
    # element library registers itself when first needed.
    if name not in _FACTORIES:
        from .. import elements as _  # noqa: F401 - triggers registration
    if name not in _FACTORIES:
        raise KeyError(f"no such element factory {name!r}; "
                       f"known: {sorted(_FACTORIES)}")
    return _FACTORIES[name]


def register_element_alias(alias: str, cls: Type[Element]) -> None:
    """Second factory name for the same class (the reference registers
    ``edgesink``/``edgesrc`` without the underscore our canonical
    names use — verbatim reference launch lines need both)."""
    _FACTORIES[alias] = cls


def make_element(name: str, element_name=None, **props) -> Element:
    return element_factory(name)(element_name, **props)


def list_factories():
    from .. import elements as _  # noqa: F401

    return sorted(_FACTORIES)
