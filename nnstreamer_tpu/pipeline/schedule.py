"""Segment compiler: fused dispatch plans for linear pipeline runs.

The per-buffer element-graph tax is the streaming bottleneck once payloads
are zero-copy: every frame crosses ``Pad.push → peer._chain_entry → chain``
(plus a tracer test and a try/except) for every element in the chain, even
when each element is a trivial transform.  The NNStreamer paper's pipeline
parallelism (Ham et al., arXiv:1901.04985) decides *where* thread
boundaries go; StreamTensor (arXiv:2509.13694) shows the complementary win
of compiling linear dataflow *segments* into one fused kernel instead of
interpreting the graph per item.  This module does the latter at the
scheduling layer:

- At ``Pipeline.play()`` a :class:`SegmentPlanner` walks the pad graph and
  finds every **head pad** — a src pad whose owning element is a thread/
  topology boundary (Source, Queue, Tee branch, mux, demux, any opt-out
  element).  Linear 1-sink/1-src elements downstream of a head that
  expose :meth:`~nnstreamer_tpu.pipeline.element.Element.plan_step` are
  **fused**: the head pad's ``push`` becomes one flat loop over bound
  step callables, ending in the boundary element's ``_chain_entry``.
- Plans compile **lazily on the first buffer** (caps have been negotiated
  by then — buffers follow caps in-band) and cache the negotiated state
  inside the bound closures.
- Plans **invalidate** on caps renegotiation, on custom events
  (model-update), on request-pad linking after play, and on
  ``enable_tracing`` — the head falls back to a compile stub and the next
  buffer rebuilds against current state.  Elements that opt out
  (``plan_step() -> None``) simply terminate the fused run; dataflow
  continues interpreted, bit-for-bit identical.
- Tracing: with a tracer attached, the compiled executor wraps each step
  in the same ``enter``/``exit(name)`` pair ``_chain_entry`` uses, so
  per-element proctime/buffers counters are exactly those of interpreted
  dispatch.  With no tracer the executor contains **zero** tracer
  references — fusion is how tracing costs nothing when off.

Install/uninstall works by shadowing ``Pad.push`` with an instance
attribute on head pads only: interpreted pipelines never pay a check, and
``uninstall()`` (at ``Pipeline.stop``) restores the class method.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ..analysis.sanitizer import make_rlock
from .element import Element, FlowReturn, Pad


def _is_linear_fusable(el: Element) -> bool:
    """Can ``el`` appear *inside* a fused run?  Exactly one sink and one
    src pad, and the element offers a plan step."""
    return (len(el.sink_pads) == 1 and len(el.src_pads) == 1
            and el.plan_step() is not None)


class SegmentPlanner:
    """Owns the fused dispatch plans of one playing pipeline."""

    def __init__(self, pipeline) -> None:
        self.pipeline = pipeline
        self._lock = make_rlock("planner")
        self._heads: List[Pad] = []
        self._plans: Dict[str, Dict] = {}   # head full_name -> plan info
        #: bumped on every invalidate/rescan; tests assert rebuilds happened
        self.epoch = 0

    # -- lifecycle -----------------------------------------------------------
    def install(self) -> None:
        """Compute the head-pad set and arm every head with a lazy compile
        stub.  Called from ``Pipeline.play()``."""
        with self._lock:
            self._heads = self._find_heads()
            for head in self._heads:
                self._install_stub(head)

    def uninstall(self) -> None:
        """Restore interpreted dispatch everywhere (``Pipeline.stop``)."""
        with self._lock:
            for head in self._heads:
                head.__dict__.pop("push", None)
            self._heads = []
            self._plans.clear()

    def invalidate(self, element: Optional[Element] = None) -> None:
        """Drop the compiled plans ``element``'s state change can affect
        and reconcile the head set; affected heads recompile on their
        next buffer.  Called per element an event traverses (caps
        renegotiation, custom events) — SCOPED, so an event delivered
        late on a queue's drain thread does not wipe an upstream
        segment's plan that will never see another buffer (and unrelated
        segments never pay a recompile).  ``element=None`` (tracer
        attach, graph change) drops everything.

        Head-set reconciliation matters because ``plan_step`` answers are
        state-dependent: an element that could not fuse before
        negotiation (so its src pad was a head) may be interior to a
        longer run afterwards — and vice versa."""
        with self._lock:
            self.epoch += 1
            for name, plan in list(self._plans.items()):
                if element is not None \
                        and element.name not in plan["elements"] \
                        and plan["tail"] != element.name \
                        and plan["_pad"].element is not element:
                    continue
                plan["_pad"].__dict__.pop("push", None)
                del self._plans[name]
            heads = self._find_heads()
            live = {p["head"] for p in self._plans.values()}
            for old in self._heads:
                if old not in heads and old.full_name not in live:
                    old.__dict__.pop("push", None)
            for head in heads:
                if head.full_name not in live \
                        and "push" not in head.__dict__:
                    self._install_stub(head)
            self._heads = heads

    def rescan(self) -> None:
        """The graph changed (request pad linked after play): full drop
        + head-set rebuild."""
        self.invalidate()

    def plans(self) -> List[Dict]:
        """Snapshot of the compiled plans (observability / tests / bench):
        one dict per fused segment with ``head``, ``elements`` (fused
        element names in order), ``tail`` (the boundary element the
        segment pushes into) and ``dispatches`` (plan executions so
        far — a cross-stream batch buffer of N frames counts ONE: the
        whole bucket traverses the fused segment as a single plan
        execution, which is exactly the per-frame dispatch tax the
        serving-plane batcher amortizes)."""
        with self._lock:
            out = []
            for p in self._plans.values():
                row = {k: v for k, v in p.items() if not k.startswith("_")}
                row["dispatches"] = p["_count"][0]
                out.append(row)
            return out

    # -- graph walk ----------------------------------------------------------
    def _find_heads(self) -> List[Pad]:
        """Every linked src pad whose owner cannot itself be fused as an
        intermediate: sources, queues, tees, muxes, sinks of runs, and
        opt-out elements.  Src pads of fusable linear elements are interior
        to some other head's run and are never pushed directly."""
        heads: List[Pad] = []
        for el in self.pipeline.elements:
            if _is_linear_fusable(el):
                continue
            for pad in el.src_pads:
                if pad.peer is not None:
                    heads.append(pad)
        return heads

    def _walk(self, head: Pad) -> Tuple[List[Tuple[Callable, Element]],
                                        Optional[Pad]]:
        """Collect the maximal fusable run downstream of ``head``.
        Returns (steps, tail sink pad); empty steps = nothing to fuse."""
        steps: List[Tuple[Callable, Element]] = []
        pad = head.peer
        limit = len(self.pipeline.elements)   # cycle guard
        while pad is not None and len(steps) < limit:
            el = pad.element
            if len(el.sink_pads) != 1 or len(el.src_pads) != 1:
                break
            fn = el.plan_step()
            if fn is None:
                break
            steps.append((fn, el))
            pad = el.src_pads[0].peer
        return steps, pad

    # -- compilation ---------------------------------------------------------
    def _install_stub(self, head: Pad) -> None:
        def compile_and_push(buf, _head=head):
            return self._compile(_head)(buf)

        head.push = compile_and_push

    def _compile(self, head: Pad) -> Callable:
        """Build (and install) the executor for ``head``.  Runs on the
        segment's own streaming thread, serialized against invalidation by
        the planner lock."""
        with self._lock:
            steps, tail_pad = self._walk(head)
            if not steps or tail_pad is None:
                # nothing fusable downstream: restore interpreted dispatch
                # for this head (an invalidate re-arms the stub, so a later
                # renegotiation can still make the run fusable)
                head.__dict__.pop("push", None)
                return lambda buf, _h=head: Pad.push(_h, buf)
            count = [0]
            executor = self._make_executor(head, steps, tail_pad, count)
            head.push = executor
            self._plans[head.full_name] = {
                "head": head.full_name,
                "elements": [el.name for _, el in steps],
                "tail": tail_pad.element.name,
                "epoch": self.epoch,
                "_pad": head,           # stripped from plans() snapshots
                "_count": count,        # plan executions (mutable cell)
            }
            return executor

    def _make_executor(self, head: Pad, steps, tail_pad: Pad,
                       count: List[int]) -> Callable:
        pipeline = self.pipeline
        tracer = pipeline.tracer
        tail_entry = tail_pad.element._chain_entry
        plan = tuple(steps)
        OK, EOS, ERROR = FlowReturn.OK, FlowReturn.EOS, FlowReturn.ERROR
        FR = FlowReturn

        if tracer is None:
            def run(buf, _plan=plan, _head=head, _tail=tail_entry,
                    _tp=tail_pad, _n=count):
                if _head.eos:
                    return EOS
                _n[0] += 1
                el = None
                try:
                    for fn, el in _plan:
                        out = fn(buf)
                        if out is None:
                            return OK
                        if out.__class__ is FR:
                            return out
                        buf = out
                except Exception as exc:  # noqa: BLE001 — pipeline error
                    pipeline.post_error(el, exc)
                    return ERROR
                return _tail(_tp, buf)

            return run

        def run_traced(buf, _plan=plan, _head=head, _tail=tail_entry,
                       _tp=tail_pad, _tracer=tracer, _n=count):
            if _head.eos:
                return EOS
            _n[0] += 1
            el = None
            try:
                for fn, el in _plan:
                    _tracer.enter(el.name, buf)
                    try:
                        out = fn(buf)
                    finally:
                        _tracer.exit()
                    if out is None:
                        return OK
                    if out.__class__ is FR:
                        return out
                    buf = out
            except Exception as exc:  # noqa: BLE001 — pipeline error
                pipeline.post_error(el, exc)
                return ERROR
            return _tail(_tp, buf)

        return run_traced
