"""Segment compiler: fused dispatch plans for linear pipeline runs.

The per-buffer element-graph tax is the streaming bottleneck once payloads
are zero-copy: every frame crosses ``Pad.push → peer._chain_entry → chain``
(plus a tracer test and a try/except) for every element in the chain, even
when each element is a trivial transform.  The NNStreamer paper's pipeline
parallelism (Ham et al., arXiv:1901.04985) decides *where* thread
boundaries go; StreamTensor (arXiv:2509.13694) shows the complementary win
of compiling linear dataflow *segments* into one fused kernel instead of
interpreting the graph per item, and "Pushing Tensor Accelerators Beyond
MatMul" (arXiv:2512.02371) shows pre/post-processing folding into the
accelerated region.  This module does both at the scheduling layer,
through a three-tier **lowering interface**:

``interpret``
    No segment compiler at all: per-pad ``Pad.push`` dispatch (the
    baseline the dispatch bench compares against).  ``fuse=False`` /
    ``NNS_FUSE=0``.
``fuse-python``
    The PR 3 tier (default): maximal linear element runs flatten into
    one loop over bound :meth:`~nnstreamer_tpu.pipeline.element.Element.
    plan_step` closures — the per-element *dispatch* cost is gone, but
    each step still executes host Python and every device-touching step
    pays its own dispatch/serialize boundary.
``fuse-xla``
    Net-new (``fuse="xla"`` / ``NNS_FUSE=xla``): a segment whose every
    step also offers :meth:`~nnstreamer_tpu.pipeline.element.Element.
    lower_step` compiles its whole transform→filter→decode chain into
    ONE jitted XLA computation.  Per-element ``serialize``+``dispatch``
    shares collapse into a single per-segment ``device-invoke`` window
    (the PR 8 profiler adjudicates), intermediate tensors stay device-
    resident, and the ``TensorBuffer.np()`` sync point moves to segment
    exit.  Segments with any non-lowerable step fall back to
    fuse-python automatically (the plan row records the element and
    reason; ``launch.py --check`` warns via analysis/verify.py).

Mechanics shared by the fused tiers:

- At ``Pipeline.play()`` a :class:`SegmentPlanner` walks the pad graph and
  finds every **head pad** — a src pad whose owning element is a thread/
  topology boundary (Source, Queue, Tee branch, mux, demux, any opt-out
  element).  Linear 1-sink/1-src elements downstream of a head that
  expose ``plan_step`` are **fused**: the head pad's ``push`` becomes one
  flat executor ending in the boundary element's ``_chain_entry``.
- Plans compile **lazily on the first buffer** (caps have been negotiated
  by then — buffers follow caps in-band) and cache the negotiated state
  inside the bound closures.
- Plans **invalidate** on caps renegotiation, on custom events
  (model-update), and on request-pad linking after play — the head falls
  back to a compile stub and the next buffer rebuilds against current
  state.  ``enable_tracing`` does NOT invalidate: :meth:`SegmentPlanner.
  retrace` reuses the cached step list and compiled XLA executables and
  swaps only the executor wrapper, so attaching a profiler to a warm
  fuse-xla pipeline never triggers a cold ``device-compile``.
- fuse-xla executables are cached per segment keyed on (plan epoch via
  plan lifetime, input caps identity via the bound closures, concrete
  stacked shape/dtype signature): a steady-state stream or a PR 9
  padded-bucket stream compiles each distinct signature ONCE
  (:class:`SegmentExec` counts ``compiles`` vs ``exec_cache_hits`` —
  the hotpath gate pins a 100 % hit rate after warmup).
- fuse-xla dispatch is **double-buffered** (``NNS_FUSE_DEPTH``, default
  2): the executor holds the previous frame's in-flight output and
  pushes it downstream only when the next frame has been dispatched, so
  the consumer's D2H sync on frame k-1 overlaps frame k's compute (and
  frame k+1's H2D rides jax's async dispatch).  The hold is gated on
  ``Element.has_pending_input()`` — a frame is kept ONLY while the head
  already has the next in-band item queued, so a quiescent stream
  (sparse request/response traffic) pushes synchronously and can never
  strand a reply in the slot.  Any in-band event flushes the pending
  slot first, so data-vs-event order is exact.
- Tracing: with a tracer attached, the fuse-python executor wraps each
  step in the same ``enter``/``exit(name)`` pair ``_chain_entry`` uses,
  so per-element proctime/buffers counters are exactly those of
  interpreted dispatch.  The fuse-xla traced executor records one
  ``device-invoke`` (or first-call ``device-compile``) state window for
  the whole segment plus zero-duration per-element marks (buffers
  counters survive; per-element proctime is structurally gone — the
  elements executed jointly).  With no tracer the executors contain
  **zero** tracer references — fusion is how tracing costs nothing
  when off.

Install/uninstall works by shadowing ``Pad.push`` with an instance
attribute on head pads only: interpreted pipelines never pay a check, and
``uninstall()`` (at ``Pipeline.stop``) restores the class method.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..analysis.sanitizer import make_rlock
from .element import Element, FlowReturn, LoweredStep, Pad

#: the lowering tiers, in ascending order of ambition
FUSE_TIERS = ("interpret", "python", "xla")


def resolve_tier(value=None) -> str:
    """Normalize a ``fuse=`` value (bool / str / None) to a tier name.

    ``None`` reads ``NNS_FUSE`` (default ``python``); booleans keep the
    historical ``fuse=False`` → interpret meaning; strings accept the
    tier names plus ``0``/``1``/``fuse-python``/``fuse-xla`` spellings.
    """
    if value is None:
        value = os.environ.get("NNS_FUSE", "1")
    if isinstance(value, bool):
        return "python" if value else "interpret"
    v = str(value).strip().lower()
    if v in ("0", "false", "no", "off", "interpret", "none"):
        return "interpret"
    if v in ("", "1", "true", "yes", "on", "python", "fuse-python"):
        return "python"
    if v in ("2", "xla", "fuse-xla"):
        return "xla"
    raise ValueError(
        f"unknown fuse tier {value!r} (interpret | python | xla)")


def _is_linear_fusable(el: Element) -> bool:
    """Can ``el`` appear *inside* a fused run?  Exactly one sink and one
    src pad, and the element offers a plan step."""
    return (len(el.sink_pads) == 1 and len(el.src_pads) == 1
            and el.plan_step() is not None)


class SegmentExec:
    """Compiled whole-segment XLA computation + its executable cache.

    One instance per fuse-xla plan.  ``fns`` are the per-element
    :class:`~nnstreamer_tpu.pipeline.element.LoweredStep` functions in
    segment order; ``params`` their device pytrees, passed as jit
    ARGUMENTS (not closure constants) so weights never bake into the
    compiled graph as constants (a model update drops the plan — and
    this instance with it — via the epoch machinery; the point of the
    argument plumbing is avoiding constant-folded weights, not
    surviving the swap).  Executables are AOT-compiled
    (``jax.jit(...).lower(...).compile()``) and cached per concrete
    input signature — per-frame shape, and each padded-bucket stacked
    shape (PR 9 ``pad_rows`` quantization) — so a steady-state stream
    never re-traces: ``compiles``/``hits`` are the evidence the
    hotpath ``fusexla`` gate pins (100 % hits after warmup).
    """

    __slots__ = ("fns", "params", "post", "compiles", "hits", "_cache")

    def __init__(self, fns, params, post=None) -> None:
        self.fns = tuple(fns)
        self.params = tuple(params)
        self.post = post
        self.compiles = 0
        self.hits = 0
        self._cache: Dict[tuple, object] = {}

    # pure; traced once per distinct input signature
    def _composed(self, params, *arrays):
        ts = list(arrays)
        for fn, p in zip(self.fns, params):
            ts = list(fn(p, ts))
        return tuple(ts)

    @staticmethod
    def _materialize(arrays) -> list:
        from ..tensor.buffer import BatchView

        return [a.device_slice() if isinstance(a, BatchView) else a
                for a in arrays]

    @staticmethod
    def _sig(arrays, extra: tuple = ()) -> tuple:
        # arity is fixed: `arrays` is the segment's input tuple, whose
        # length the compiled plan pins at build time
        # nnsjit: allow(unbounded-signature)
        return extra + tuple(
            (tuple(a.shape), str(a.dtype), bool(getattr(a, "weak_type",
                                                        False)))
            for a in arrays)

    def _compile(self, key: tuple, fun, args) -> object:
        import jax

        self.compiles += 1
        from ..analysis import compileledger
        compileledger.record("pipeline.segment", key)
        exe = jax.jit(fun).lower(self.params, *args).compile()
        self._cache[key] = exe
        return exe

    def run(self, arrays) -> list:
        """One per-frame dispatch through the fused executable."""
        arrays = self._materialize(arrays)
        key = self._sig(arrays)
        exe = self._cache.get(key)
        if exe is None:
            exe = self._compile(key, self._composed, arrays)
        else:
            self.hits += 1
        return list(exe(self.params, *arrays))

    def run_stacked(self, arrays, n: int, capacity: int) -> list:
        """One cross-stream bucket (PR 9 stacked ``(n, …)`` buffers)
        through the vmapped fused executable: rows pad to the
        ``pad_rows`` quantization (repeating the last live row, the
        padded-bucket policy ``_jitexec.invoke_stacked`` set) so a
        BOUNDED executable set serves every fill level — rows past
        ``n`` are padding and are never replied (XBatchMeta contract).
        Returns the PADDED stacked outputs."""
        import jax
        import jax.numpy as jnp

        from ..filter.backends._jitexec import JitExecMixin
        from ..tensor.buffer import is_device_array

        bucket = JitExecMixin.pad_rows(n, capacity)
        padded = []
        for arr in self._materialize(arrays):
            rows = int(arr.shape[0])
            if rows < bucket:
                if is_device_array(arr):
                    pad = arr[-1:]
                    arr = jnp.concatenate(
                        [arr, jnp.broadcast_to(
                            pad, (bucket - rows,) + tuple(pad.shape[1:]))],
                        axis=0)
                else:
                    arr = np.asarray(arr)
                    arr = np.concatenate(
                        [arr, np.broadcast_to(
                            arr[-1:],
                            (bucket - rows,) + arr.shape[1:])], axis=0)
            padded.append(arr)
        key = self._sig(padded, extra=("xb",))
        exe = self._cache.get(key)
        if exe is None:
            vmapped = jax.vmap(self._composed,
                               in_axes=(None,) + (0,) * len(padded))
            exe = self._compile(key, vmapped, padded)
        else:
            self.hits += 1
        return list(exe(self.params, *padded))


class SegmentPlanner:
    """Owns the fused dispatch plans of one playing pipeline."""

    def __init__(self, pipeline) -> None:
        self.pipeline = pipeline
        #: lowering tier ("python" | "xla"); "interpret" never constructs
        #: a planner (Pipeline.play gates on pipeline.fuse)
        self.tier = getattr(pipeline, "fuse_tier", "python")
        #: fuse-xla double-buffer depth: 1 = synchronous push, 2 (the
        #: default) holds one in-flight output so downstream D2H overlaps
        #: the next frame's compute.  Tunable via NNS_FUSE_DEPTH.
        try:
            self.depth = max(1, int(os.environ.get("NNS_FUSE_DEPTH", "2")))
        except ValueError:
            self.depth = 2
        self._lock = make_rlock("planner")
        self._heads: List[Pad] = []
        self._plans: Dict[str, Dict] = {}   # head full_name -> plan info
        #: bumped on every invalidate/rescan; tests assert rebuilds happened
        self.epoch = 0

    # -- lifecycle -----------------------------------------------------------
    def install(self) -> None:
        """Compute the head-pad set and arm every head with a lazy compile
        stub.  Called from ``Pipeline.play()``."""
        with self._lock:
            self._heads = self._find_heads()
            for head in self._heads:
                self._install_stub(head)

    def uninstall(self) -> None:
        """Restore interpreted dispatch everywhere (``Pipeline.stop``)."""
        with self._lock:
            for head in self._heads:
                head.__dict__.pop("push", None)
                head.__dict__.pop("push_event", None)
                head.__dict__.pop("_nns_pending", None)
            for plan in self._plans.values():
                pad = plan["_pad"]
                pad.__dict__.pop("push", None)
                pad.__dict__.pop("push_event", None)
                pad.__dict__.pop("_nns_pending", None)
            self._heads = []
            self._plans.clear()

    def invalidate(self, element: Optional[Element] = None) -> None:
        """Drop the compiled plans ``element``'s state change can affect
        and reconcile the head set; affected heads recompile on their
        next buffer.  Called per element an event traverses (caps
        renegotiation, custom events) — SCOPED, so an event delivered
        late on a queue's drain thread does not wipe an upstream
        segment's plan that will never see another buffer (and unrelated
        segments never pay a recompile).  ``element=None`` (graph
        change) drops everything.

        Head-set reconciliation matters because ``plan_step`` answers are
        state-dependent: an element that could not fuse before
        negotiation (so its src pad was a head) may be interior to a
        longer run afterwards — and vice versa."""
        with self._lock:
            self.epoch += 1
            for name, plan in list(self._plans.items()):
                if element is not None \
                        and element.name not in plan["elements"] \
                        and plan["tail"] != element.name \
                        and plan["_pad"].element is not element:
                    continue
                plan["_pad"].__dict__.pop("push", None)
                del self._plans[name]
            heads = self._find_heads()
            live = {p["head"] for p in self._plans.values()}
            for old in self._heads:
                if old not in heads and old.full_name not in live:
                    old.__dict__.pop("push", None)
            for head in heads:
                if head.full_name not in live \
                        and "push" not in head.__dict__:
                    self._install_stub(head)
            self._heads = heads

    def rescan(self) -> None:
        """The graph changed (request pad linked after play): full drop
        + head-set rebuild."""
        self.invalidate()

    def retrace(self) -> None:
        """A tracer attached (or detached): swap every compiled plan's
        executor wrapper in place, KEEPING the cached step list and the
        fuse-xla executable cache.  The previous behavior (a full
        ``invalidate``) forced a whole-plan recompile — for fuse-xla
        that meant ``launch.py --profile`` against a warm pipeline paid
        a cold XLA ``device-compile`` just to change the wrapper."""
        with self._lock:
            for plan in self._plans.values():
                head = plan["_pad"]
                seg = plan.get("_seg")
                if seg is not None:
                    executor = self._make_xla_executor(
                        head, plan["_steps"], plan["_tail_pad"],
                        plan["_count"], seg)
                else:
                    executor = self._make_executor(
                        head, plan["_steps"], plan["_tail_pad"],
                        plan["_count"])
                head.push = executor

    def plans(self) -> List[Dict]:
        """Snapshot of the compiled plans (observability / tests / bench):
        one dict per fused segment with ``head``, ``elements`` (fused
        element names in order), ``tail`` (the boundary element the
        segment pushes into), ``lowering`` (``python`` | ``xla``, plus
        ``fallback`` diagnostics when an xla request fell back),
        ``dispatches`` (plan executions so far — a cross-stream batch
        buffer of N frames counts ONE: the whole bucket traverses the
        fused segment as a single plan execution, which is exactly the
        per-frame dispatch tax the serving-plane batcher amortizes) and,
        for xla plans, ``compiles``/``exec_cache_hits`` — the
        no-steady-state-recompiles evidence."""
        with self._lock:
            out = []
            for p in self._plans.values():
                row = {k: v for k, v in p.items() if not k.startswith("_")}
                row["dispatches"] = p["_count"][0]
                seg = p.get("_seg")
                if seg is not None:
                    row["compiles"] = seg.compiles
                    row["exec_cache_hits"] = seg.hits
                out.append(row)
            return out

    # -- graph walk ----------------------------------------------------------
    def _find_heads(self) -> List[Pad]:
        """Every linked src pad whose owner cannot itself be fused as an
        intermediate: sources, queues, tees, muxes, sinks of runs, and
        opt-out elements.  Src pads of fusable linear elements are interior
        to some other head's run and are never pushed directly."""
        heads: List[Pad] = []
        for el in self.pipeline.elements:
            if _is_linear_fusable(el):
                continue
            for pad in el.src_pads:
                if pad.peer is not None:
                    heads.append(pad)
        return heads

    def _walk(self, head: Pad) -> Tuple[List[Tuple[Callable, Element]],
                                        Optional[Pad]]:
        """Collect the maximal fusable run downstream of ``head``.
        Returns (steps, tail sink pad); empty steps = nothing to fuse."""
        steps: List[Tuple[Callable, Element]] = []
        pad = head.peer
        limit = len(self.pipeline.elements)   # cycle guard
        while pad is not None and len(steps) < limit:
            el = pad.element
            if len(el.sink_pads) != 1 or len(el.src_pads) != 1:
                break
            fn = el.plan_step()
            if fn is None:
                break
            steps.append((fn, el))
            pad = el.src_pads[0].peer
        return steps, pad

    # -- lowering ------------------------------------------------------------
    def _lower_segment(self, steps) -> Tuple[Optional[SegmentExec],
                                             List[Dict]]:
        """Lower every step of a segment into one :class:`SegmentExec`,
        or return the fallback diagnostics (element + reason per
        non-lowerable step) — the whole segment then runs fuse-python.
        A host ``post`` finisher is only legal on the tail element."""
        try:
            import jax  # noqa: F401  — availability probe
        except Exception as exc:  # noqa: BLE001 — env without jax
            return None, [{"element": "<jax>",
                           "reason": f"jax unavailable: {exc!r}"}]
        fallback: List[Dict] = []
        lowered: List[LoweredStep] = []
        for i, (_, el) in enumerate(steps):
            try:
                ls = el.lower_step()
            except Exception as exc:  # noqa: BLE001 — element-specific
                fallback.append({"element": el.name,
                                 "reason": f"lower_step raised {exc!r}"})
                continue
            if ls is None:
                fallback.append({
                    "element": el.name,
                    "reason": el.lower_reason() or "lower_step "
                                                  "returned None"})
                continue
            if ls.post is not None and i != len(steps) - 1:
                fallback.append({
                    "element": el.name,
                    "reason": "host post-finisher only allowed on the "
                              "segment tail"})
                continue
            lowered.append(ls)
        if fallback:
            return None, fallback
        return SegmentExec([ls.fn for ls in lowered],
                           [ls.params for ls in lowered],
                           post=lowered[-1].post if lowered else None), []

    # -- compilation ---------------------------------------------------------
    @staticmethod
    def _flush_pending(head: Pad) -> None:
        """Push out any double-buffered frames a DROPPED xla plan left
        behind, before a non-xla executor takes over the head — each
        entry carries its own tail binding, so the frames reach the
        tail they were produced for, in order."""
        pend = head.__dict__.get("_nns_pending")
        while pend:
            entry, tp, out, _t = pend.pop(0)
            entry(tp, out)

    def _install_stub(self, head: Pad) -> None:
        def compile_and_push(buf, _head=head):
            return self._compile(_head)(buf)

        head.push = compile_and_push

    def _compile(self, head: Pad) -> Callable:
        """Build (and install) the executor for ``head``.  Runs on the
        segment's own streaming thread, serialized against invalidation by
        the planner lock."""
        with self._lock:
            steps, tail_pad = self._walk(head)
            if not steps or tail_pad is None:
                # nothing fusable downstream: restore interpreted dispatch
                # for this head (an invalidate re-arms the stub, so a later
                # renegotiation can still make the run fusable)
                self._flush_pending(head)
                head.__dict__.pop("push", None)
                return lambda buf, _h=head: Pad.push(_h, buf)
            count = [0]
            plan = {
                "head": head.full_name,
                "elements": [el.name for _, el in steps],
                "tail": tail_pad.element.name,
                "epoch": self.epoch,
                "lowering": "python",
                "_pad": head,           # stripped from plans() snapshots
                "_count": count,        # plan executions (mutable cell)
                "_steps": steps,        # cached: retrace() reuses
                "_tail_pad": tail_pad,
            }
            seg = None
            if self.tier == "xla":
                seg, fallback = self._lower_segment(steps)
                if seg is not None:
                    plan["lowering"] = "xla"
                    plan["_seg"] = seg
                else:
                    plan["fallback"] = fallback
            if seg is not None:
                executor = self._make_xla_executor(head, steps, tail_pad,
                                                   count, seg)
                if self.depth > 1:
                    self._install_event_flush(head)
            else:
                self._flush_pending(head)
                executor = self._make_executor(head, steps, tail_pad,
                                               count)
            head.push = executor
            self._plans[head.full_name] = plan
            return executor

    def _make_executor(self, head: Pad, steps, tail_pad: Pad,
                       count: List[int]) -> Callable:
        pipeline = self.pipeline
        tracer = pipeline.tracer
        tail_entry = tail_pad.element._chain_entry
        plan = tuple(steps)
        OK, EOS, ERROR = FlowReturn.OK, FlowReturn.EOS, FlowReturn.ERROR
        FR = FlowReturn

        if tracer is None:
            def run(buf, _plan=plan, _head=head, _tail=tail_entry,
                    _tp=tail_pad, _n=count):
                if _head.eos:
                    return EOS
                _n[0] += 1
                el = None
                try:
                    for fn, el in _plan:
                        out = fn(buf)
                        if out is None:
                            return OK
                        if out.__class__ is FR:
                            return out
                        buf = out
                except Exception as exc:  # noqa: BLE001 — pipeline error
                    pipeline.post_error(el, exc)
                    return ERROR
                return _tail(_tp, buf)

            return run

        def run_traced(buf, _plan=plan, _head=head, _tail=tail_entry,
                       _tp=tail_pad, _tracer=tracer, _n=count):
            if _head.eos:
                return EOS
            _n[0] += 1
            el = None
            try:
                for fn, el in _plan:
                    _tracer.enter(el.name, buf)
                    try:
                        out = fn(buf)
                    finally:
                        _tracer.exit()
                    if out is None:
                        return OK
                    if out.__class__ is FR:
                        return out
                    buf = out
            except Exception as exc:  # noqa: BLE001 — pipeline error
                pipeline.post_error(el, exc)
                return ERROR
            return _tail(_tp, buf)

        return run_traced

    # -- fuse-xla executors --------------------------------------------------
    def _install_event_flush(self, head: Pad) -> None:
        """Shadow ``push_event`` on a double-buffered head: any in-band
        event (caps, EOS, custom) flushes the pending output slot FIRST,
        so events never overtake data the executor is still holding.
        Installed once; survives plan invalidation (the pending slot may
        still hold a frame produced by the dropped plan — each entry
        carries its own tail binding)."""
        if "push_event" in head.__dict__:
            return
        pending = head.__dict__.setdefault("_nns_pending", [])

        def push_event_flush(event, _head=head, _pend=pending):
            while _pend:
                entry, tp, out, _t = _pend.pop(0)
                entry(tp, out)
            return Pad.push_event(_head, event)

        head.push_event = push_event_flush

    def _make_xla_executor(self, head: Pad, steps, tail_pad: Pad,
                           count: List[int], seg: SegmentExec) -> Callable:
        """The whole-segment executor: one fused device dispatch per
        buffer (stacked PR 9 buckets ride the vmapped executable), a
        two-slot pending queue for compute/D2H overlap, and a python-
        tier escape for the one shape the jitted region cannot express
        (a stacked bucket whose tail carries a host post-finisher)."""
        pipeline = self.pipeline
        tracer = pipeline.tracer
        tail_entry = tail_pad.element._chain_entry
        OK, EOS, ERROR = FlowReturn.OK, FlowReturn.EOS, FlowReturn.ERROR
        depth = self.depth
        pending = head.__dict__.setdefault("_nns_pending", [])
        last_el = steps[-1][1]
        py_run = self._make_executor(head, steps, tail_pad, count)
        els = tuple(el for _, el in steps)
        # double-buffer gate: hold a finished frame ONLY while the head
        # element already has the next in-band item queued — overlap
        # exactly when there is back-to-back work, synchronous push on a
        # quiescent stream (a sparse request/response flow must never
        # strand its reply in the pending slot)
        more = head.element.has_pending_input

        if tracer is None:
            def run(buf, _head=head, _seg=seg, _tail=tail_entry,
                    _tp=tail_pad, _n=count, _pend=pending, _depth=depth,
                    _py=py_run, _last=last_el, _more=more):
                if _head.eos:
                    return EOS
                xb = buf.extra.get("nns_xbatch")
                if xb is not None and _seg.post is not None:
                    # a host post-finisher is per-frame (label lookup);
                    # it cannot run over a stacked bucket — python tier
                    while _pend:
                        entry, tp, out, _t = _pend.pop(0)
                        entry(tp, out)
                    return _py(buf)
                _n[0] += 1
                try:
                    if xb is not None:
                        outs = _seg.run_stacked(buf.tensors, xb.n,
                                                xb.capacity)
                    else:
                        outs = _seg.run(buf.tensors)
                    out = buf.with_tensors(outs)
                    if _seg.post is not None:
                        out = _seg.post(out)
                except Exception as exc:  # noqa: BLE001 — pipeline error
                    pipeline.post_error(_last, exc)
                    return ERROR
                if _depth > 1:
                    _pend.append((_tail, _tp, out, 0))
                    keep = _depth - 1 if _more() else 0
                    ret = OK
                    while len(_pend) > keep:
                        entry, tp, held, _t = _pend.pop(0)
                        ret = entry(tp, held)
                    return ret
                return _tail(_tp, out)

            return run

        from .tracing import annotate

        def run_traced(buf, _head=head, _seg=seg, _tail=tail_entry,
                       _tp=tail_pad, _tracer=tracer, _n=count,
                       _pend=pending, _depth=depth, _py=py_run,
                       _last=last_el, _els=els, _annotate=annotate,
                       _more=more):
            if _head.eos:
                return EOS
            xb = buf.extra.get("nns_xbatch")
            if xb is not None and _seg.post is not None:
                while _pend:
                    entry, tp, out, _t = _pend.pop(0)
                    entry(tp, out)
                return _py(buf)
            _n[0] += 1
            import time as _time

            try:
                # the tail-most fused element's span covers the shared
                # dispatch; the state annotation nests inside it, so
                # attribution reads the window as device-invoke (the
                # per-element serialize/dispatch shares it replaced)
                _tracer.enter(_last.name, buf)
                try:
                    before = _seg.compiles
                    t0 = _time.monotonic_ns()
                    if xb is not None:
                        outs = _seg.run_stacked(buf.tensors, xb.n,
                                                xb.capacity)
                    else:
                        outs = _seg.run(buf.tensors)
                    t1 = _time.monotonic_ns()
                    _annotate("device-compile" if _seg.compiles > before
                              else "device-invoke", t0, t1)
                finally:
                    _tracer.exit()
                # zero-duration marks for the other fused elements keep
                # their buffers counters truthful (proctime is jointly
                # spent inside the fused window and cannot be split)
                for el in _els:
                    if el is not _last:
                        _tracer.enter(el.name, buf)
                        _tracer.exit()
                out = buf.with_tensors(outs)
                if _seg.post is not None:
                    out = _seg.post(out)
            except Exception as exc:  # noqa: BLE001 — pipeline error
                pipeline.post_error(_last, exc)
                return ERROR
            if _depth > 1:
                _pend.append((_tail, _tp, out, _time.monotonic_ns()))
                keep = _depth - 1 if _more() else 0
                ret = OK
                while len(_pend) > keep:
                    entry, tp, held, t_held = _pend.pop(0)
                    if t_held:
                        # the double-buffer residency is deliberate
                        # pipelining (downstream's D2H overlapped the
                        # next frame's compute): attribute it as
                        # queue-wait, the PR 9 convention for residency
                        # windows — not an uncovered dispatch gap
                        seq = held.extra.get("nns_seq", -1)
                        ctx = held.extra.get("nns_trace")
                        _tracer.annotate_span(
                            "queue-wait", t_held, _time.monotonic_ns(),
                            seq=seq,
                            trace_id=(ctx.trace_id if ctx is not None
                                      and ctx.trace_id else 0))
                    ret = entry(tp, held)
                return ret
            return _tail(_tp, out)

        return run_traced
