"""N-pad time synchronization for combining elements (mux/merge).

Parity with the reference's collectpads sync engine
(gst_tensor_time_sync_*, nnstreamer_plugin_api_impl.c:34-450; policy doc
Documentation/synchronization-policies-at-mux-merge.md): policies decide
which per-pad buffers form one output frame and what PTS it carries.

- ``nosync``: pair buffers by arrival order (FIFO zip).
- ``slowest``: output PTS = max of head PTS; pads ahead of that PTS wait,
  pads behind drop forward until within range.
- ``basepad``: pad0 drives; option ``N:duration`` — other pads pick their
  newest buffer not newer than pad0's PTS + duration.
- ``refresh``: emit whenever pad0 produces, reusing the latest buffer seen
  on other pads.
"""

from __future__ import annotations

import enum
from typing import Dict, List, Optional

from ..analysis.sanitizer import make_lock
from ..tensor.buffer import TensorBuffer


class SyncMode(enum.Enum):
    NOSYNC = "nosync"
    SLOWEST = "slowest"
    BASEPAD = "basepad"
    REFRESH = "refresh"

    @classmethod
    def from_string(cls, s: Optional[str]) -> "SyncMode":
        if not s:
            return cls.NOSYNC
        return cls(str(s).strip().lower())


def parse_sync_option(sync_option) -> "tuple[Optional[int], int]":
    """Parse the mux/merge ``sync-option`` string into
    ``(duration_ns, base_pad)``: ``'<basepad>:<duration_ns>'`` or a
    bare ``'<duration_ns>'``.  Reference ssat spellings include
    trailing junk (``sync-option=0:0.``) which g_ascii_strtoull
    ignores — numbers here parse the leading digits and drop the rest
    the same way (no digits at all parses as 0, as strtoull does)."""
    import re

    dur: Optional[int] = None
    base_pad = 0
    if sync_option not in (None, ""):
        def num(s):
            digits = re.match(r"\s*\+?(\d*)", str(s)).group(1)
            return int(digits) if digits else 0
        parts = str(sync_option).split(":")
        if len(parts) >= 2:
            base_pad, dur = num(parts[0]), num(parts[1])
        else:
            dur = num(parts[0])
    return dur, base_pad


class CollectPads:
    """Per-pad FIFOs + a sync policy; thread-safe (each upstream branch may
    chain from its own streaming thread, as with GStreamer collectpads)."""

    def __init__(self, num_pads: int, mode: SyncMode = SyncMode.NOSYNC,
                 base_duration_ns: Optional[int] = None, base_pad: int = 0):
        self.num_pads = num_pads
        self.mode = mode
        self.base_duration_ns = base_duration_ns
        self.base_pad = base_pad
        self._fifos: Dict[int, List[TensorBuffer]] = {
            i: [] for i in range(num_pads)}
        self._latest: Dict[int, Optional[TensorBuffer]] = {
            i: None for i in range(num_pads)}
        self._eos: Dict[int, bool] = {i: False for i in range(num_pads)}
        self._lock = make_lock("collectpads")

    def add_pad(self) -> int:
        with self._lock:
            i = self.num_pads
            self.num_pads += 1
            self._fifos[i] = []
            self._latest[i] = None
            self._eos[i] = False
            return i

    def push(self, pad_index: int, buf: TensorBuffer
             ) -> Optional[List[TensorBuffer]]:
        """Queue a buffer; return one synchronized frame set if ready."""
        with self._lock:
            self._fifos[pad_index].append(buf)
            self._latest[pad_index] = buf
            return self._collect_locked()

    def set_eos(self, pad_index: int) -> bool:
        """Mark a pad EOS; returns True when collection is exhausted.

        Reference semantics (gst_tensor_time_sync_buffer_from_collectpad
        sets is_eos, nnstreamer_plugin_api_impl.c): the mux ends as soon
        as ANY pad is EOS with nothing queued — no complete set can ever
        form again.  (All-pads-EOS would deadlock recurrent topologies:
        the tensor_reposrc state branch only ends AFTER the mux ends,
        tests/nnstreamer_repo_rnn.)"""
        with self._lock:
            self._eos[pad_index] = True
            return self._exhausted_locked()

    def exhausted(self) -> bool:
        """True when an EOS pad's FIFO has drained — re-checked after each
        collect so the mux ends once the tail is flushed."""
        with self._lock:
            return self._exhausted_locked()

    def _exhausted_locked(self) -> bool:
        # a pad blocks collection forever iff it is EOS with nothing
        # queued AND the sync mode cannot substitute for it: NOSYNC/
        # SLOWEST need every pad's queue; BASEPAD/REFRESH reuse
        # ``_latest`` for non-base pads (so those only block when no
        # buffer was EVER seen)
        for i in range(self.num_pads):
            if not (self._eos[i] and not self._fifos[i]):
                continue
            if self.mode in (SyncMode.NOSYNC, SyncMode.SLOWEST):
                return True
            if i == self.base_pad or self._latest[i] is None:
                return True
        return False

    def _collect_locked(self) -> Optional[List[TensorBuffer]]:
        mode = self.mode
        if mode is SyncMode.NOSYNC:
            if all(self._fifos[i] for i in range(self.num_pads)):
                return [self._fifos[i].pop(0) for i in range(self.num_pads)]
            return None
        if mode is SyncMode.SLOWEST:
            if not all(self._fifos[i] for i in range(self.num_pads)):
                return None
            target = max(self._fifos[i][0].pts or 0
                         for i in range(self.num_pads))
            out = []
            for i in range(self.num_pads):
                fifo = self._fifos[i]
                # drop stale buffers: keep newest with pts <= target
                while len(fifo) > 1 and (fifo[1].pts or 0) <= target:
                    fifo.pop(0)
                out.append(fifo.pop(0))
            return out
        if mode is SyncMode.BASEPAD:
            bp = self.base_pad
            if not self._fifos[bp]:
                return None
            base = self._fifos[bp][0]
            limit = (base.pts or 0) + (self.base_duration_ns or 0)
            out: List[Optional[TensorBuffer]] = [None] * self.num_pads
            for i in range(self.num_pads):
                if i == bp:
                    continue
                fifo = self._fifos[i]
                if not fifo:
                    if self._latest[i] is None:
                        return None
                    out[i] = self._latest[i]
                    continue
                while len(fifo) > 1 and (fifo[1].pts or 0) <= limit:
                    fifo.pop(0)
                out[i] = fifo.pop(0) if fifo else self._latest[i]
            out[bp] = self._fifos[bp].pop(0)
            return out
        if mode is SyncMode.REFRESH:
            bp = self.base_pad
            if not self._fifos[bp]:
                return None
            if any(self._latest[i] is None for i in range(self.num_pads)):
                return None
            out = []
            for i in range(self.num_pads):
                if i == bp:
                    out.append(self._fifos[bp].pop(0))
                    continue
                fifo = self._fifos[i]
                out.append(fifo.pop(0) if fifo else self._latest[i])
            return out
        raise AssertionError(mode)

    def finalize(self) -> Optional[List[List[TensorBuffer]]]:
        """Once EVERY pad is EOS, drain whatever frame-sets the sync policy
        can still form (BASEPAD/REFRESH keep emitting a base backlog from
        ``_latest``) and return them; ``None`` while any pad is still live.
        Collection is push-driven, so without this a base-pad backlog at
        all-EOS would strand the mux with no EOS ever sent."""
        with self._lock:
            if not all(self._eos.values()):
                return None
            frames = []
            while True:
                fs = self._collect_locked()
                if fs is None:
                    return frames
                frames.append(fs)
