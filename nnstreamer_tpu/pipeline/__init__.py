"""Pipeline substrate (L0-equivalent): caps, elements, graph, parse."""

from .caps import ANY_FRAMERATE, Caps, FractionRange, IntRange, Structure
from .element import (CapsEvent, CustomEvent, Element, EOSEvent, Event,
                      FlowReturn, Pad, PadDirection, SegmentEvent)
from .graph import AppSrc, Pipeline, PipelineError, Queue, Source, Tee
from .registry import element_factory, list_factories, make_element, register_element
from .parse import CapsFilter, ParseError, parse_launch

__all__ = [
    "Caps", "Structure", "IntRange", "FractionRange", "ANY_FRAMERATE",
    "Element", "Pad", "PadDirection", "Event", "CapsEvent", "EOSEvent",
    "SegmentEvent", "CustomEvent", "FlowReturn", "Pipeline", "PipelineError",
    "Source", "Queue", "Tee", "AppSrc", "register_element", "make_element",
    "element_factory", "list_factories", "parse_launch", "ParseError",
    "CapsFilter",
]
