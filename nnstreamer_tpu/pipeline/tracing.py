"""Pipeline tracing: per-element proctime / framerate / queue levels,
per-buffer timeline spans, latency distributions, interlatency.

The reference's profiling story is external GStreamer tracers — GstShark's
``proctime`` (time inside each element's chain), ``framerate`` (buffers/s
per pad) and ``interlatency`` hooks (tools/tracing/README.md:33-43,
tools/profiling/README.md:5-17).  Here tracing is built into the pipeline
substrate: attach a :class:`Tracer` and every ``chain()`` is timed with
one clock read on each side — nanosecond counters, no sampling, zero cost
when no tracer is attached (a single ``is None`` test per buffer).

Usage::

    p = parse_launch("videotestsrc num-buffers=64 ! … ! tensor_sink")
    tracer = p.enable_tracing()            # counters + histograms
    tracer = p.enable_tracing(spans=True)  # + per-buffer timeline spans
    p.run(timeout=60)
    print(json.dumps(tracer.report(), indent=2))
    tracer.export_chrome("timeline.json")  # Perfetto / chrome://tracing

``launch.py --trace`` prints the same report after the pipeline ends;
``launch.py --timeline out.json`` writes the Chrome trace.

Report fields per element: ``buffers``, ``proctime_ms`` (total time inside
chain), ``proctime_avg_us``, ``fps`` (buffers/sec over the element's
active window), ``proctime_us`` p50/p95/p99 (obs/metrics.py log-bucket
histograms) and — when the pipeline's sources stamped buffers —
``interlatency_us``: the GstShark interlatency role, source→element
transit measured per buffer at each element's exit, so the sink row reads
as end-to-end pipeline latency.

**Spans** (opt-in per tracer): each traced ``chain()`` additionally
appends ``(element, thread, start ns, duration ns, buffer seq, trace
id)`` to a bounded ring (obs/span.py), exported as Chrome ``trace_event``
JSON.  Remote spans harvested over the query wire (T_TRACE piggyback,
query/client.py) merge into the same export under extra pids, re-based
via the clock-offset estimate — one timeline for a client→server→client
round trip.

Fused segment plans (pipeline/schedule.py) keep these semantics exactly:
a compiled executor calls the same :meth:`Tracer.enter` /
:meth:`Tracer.exit` pair around each fused step that
``Element._chain_entry`` uses around ``chain()``, so per-element
``buffers``/``proctime`` are identical under fusion — and with no tracer
attached the compiled executor contains NO tracer references at all
(plans rebuild when ``enable_tracing`` attaches one), which is how
tracing costs zero calls when off instead of one test per element per
buffer.

Dataflow-copy observability (the zero-copy hot path's regression gate):
serialize/convert code reports every payload byte it MATERIALIZES into a
new host buffer via :func:`record_copy`, and pool acquires report
hits/misses via :func:`record_pool`.  Both attribute to the element whose
``chain()`` is on the current thread's trace stack, surfacing as
``bytes_copied`` / ``pool_hits`` / ``pool_misses`` in the report — so a
re-introduced full-frame copy shows up per element instead of hiding in
wall time.  With no tracer attached both calls are a single dict lookup.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple


class _ElementStats:
    __slots__ = ("buffers", "frames", "proc_ns", "first_ts", "last_ts",
                 "bytes_copied", "pool_hits", "pool_misses",
                 "inter_ns", "inter_n")

    def __init__(self) -> None:
        self.buffers = 0
        #: frame-weighted count: a cross-stream batch buffer of N
        #: frames (query/server.py bucket) counts N here and 1 in
        #: ``buffers`` — per-frame rates must not undercount buckets
        self.frames = 0
        self.proc_ns = 0
        self.first_ts: Optional[float] = None
        self.last_ts: Optional[float] = None
        self.bytes_copied = 0
        self.pool_hits = 0
        self.pool_misses = 0
        self.inter_ns = 0
        self.inter_n = 0


#: process-wide per-thread trace frame stack.  Each entry is one live
#: ``chain()``: [tracer, start_ns, child_ns, bytes_copied, pool_hits,
#: pool_misses, buf, element_name].  Module-level (not per-Tracer) so
#: record_copy / record_pool / log-context reach the active frame
#: without any registry lookups.
_TLS = threading.local()


def _stack():
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


def record_copy(nbytes: int) -> None:
    """Report ``nbytes`` of payload materialized into a fresh host buffer
    (``tobytes``/``ascontiguousarray``/adapter compaction...).  Attributes
    to the element currently in ``chain()`` on this thread; no-op (one
    getattr) when no tracer is active."""
    stack = getattr(_TLS, "stack", None)
    if stack:
        stack[-1][3] += nbytes


def record_pool(hit: bool) -> None:
    """Report one pool acquire (hit = served from the free list)."""
    stack = getattr(_TLS, "stack", None)
    if stack:
        stack[-1][4 if hit else 5] += 1


def annotation_active() -> bool:
    """True when a span-recording tracer owns this thread's innermost
    live ``chain()`` — the pre-gate for state annotations, so untraced
    hot paths never pay the clock reads an :func:`annotate` call would
    need (one getattr + one truthiness test when off)."""
    stack = getattr(_TLS, "stack", None)
    if not stack:
        return False
    tracer = stack[-1][0]
    return tracer is not None and tracer.ring is not None


def annotate(state: str, start_ns: int, end_ns: int) -> None:
    """Record a wait-state annotation span (``state:<state>``,
    obs/attrib.py closed set) against the buffer currently in
    ``chain()`` on this thread.  Callers pre-gate with
    :func:`annotation_active` so the two clock reads bracketing the
    annotated region cost nothing when tracing is off.  Used by the
    wire framing path (``serialize``), the jit-exec dispatch
    (``device-invoke`` / ``device-compile``) and the worker reorder
    pusher (``reorder-wait``); the fused executor's ``enter``/``exit``
    pairs push the same frames interpreted dispatch does, so
    annotations emit identical state edges under both executors."""
    stack = getattr(_TLS, "stack", None)
    if not stack:
        return
    frame = stack[-1]
    tracer = frame[0]
    if tracer is None or tracer.ring is None:
        return
    seq = -1
    trace_id = tracer.trace_id
    buf = frame[6]
    if buf is not None:
        extra = buf.extra
        seq = extra.get("nns_seq", -1)
        ctx = extra.get("nns_trace")
        if ctx is not None and ctx.trace_id:
            trace_id = ctx.trace_id
    from ..obs.span import Span

    tracer.ring.append(Span("state:" + state, threading.get_ident(),
                            start_ns, max(0, end_ns - start_ns), seq,
                            trace_id))


def active_frame_context() -> Dict[str, Any]:
    """Element/buffer context of this thread's innermost live traced
    ``chain()`` — the structured-logging hook (utils/log.py pulls
    ``element`` and ``buffer_seq`` into every record emitted from inside
    a traced chain).  Empty when untraced: logging context is an
    observability feature, not a hot-path tax."""
    stack = getattr(_TLS, "stack", None)
    if not stack:
        return {}
    frame = stack[-1]
    out: Dict[str, Any] = {}
    if frame[7] is not None:
        out["element"] = frame[7]
    buf = frame[6]
    if buf is not None:
        seq = buf.extra.get("nns_seq")
        if seq is not None:
            out["buffer_seq"] = seq
    return out


class copy_probe:
    """Standalone copy/pool counter for code that isn't a pipeline
    element (microbenches, unit tests)::

        with copy_probe() as probe:
            send_tensors(...)
        assert probe.bytes_copied <= header_bytes

    Pushes a synthetic frame on this thread's trace stack, so
    record_copy / record_pool attribute to it.
    """

    def __init__(self) -> None:
        self.bytes_copied = 0
        self.pool_hits = 0
        self.pool_misses = 0

    def __enter__(self) -> "copy_probe":
        _stack().append([None, 0, 0, 0, 0, 0, None, None])
        return self

    def __exit__(self, *exc) -> None:
        frame = _stack().pop()
        self.bytes_copied += frame[3]
        self.pool_hits += frame[4]
        self.pool_misses += frame[5]


class Tracer:
    """Collects per-element dataflow statistics (thread-safe: elements
    chain from multiple streaming threads).

    Dataflow is synchronous within a streaming thread — an element's
    ``chain()`` pushes downstream before returning — so SELF time is
    wall time minus the nested downstream chains' time.  A per-thread
    frame stack does that subtraction, matching GstShark's proctime
    semantics (time inside ONE element).

    ``spans=True`` additionally records every traced chain as a
    timeline span into a bounded ring (obs/span.py) for Chrome-trace
    export; off by default — span recording is per-buffer work the
    counters-only mode does not pay."""

    def __init__(self, spans: bool = False,
                 ring_capacity: int = 65536) -> None:
        from ..analysis.sanitizer import make_lock
        from ..obs.clock import mono_ns, wall_us
        from ..obs.span import SpanRing, new_trace_id

        self._stats: Dict[str, _ElementStats] = {}
        self._lock = make_lock("tracer")
        #: one process-local trace id; buffers without a propagated wire
        #: context record under it, so a single-process run still groups
        self.trace_id = new_trace_id()
        self.spans = bool(spans)
        self.ring = SpanRing(ring_capacity) if self.spans else None
        #: local mono↔wall anchor pair: lets this process's mono-ns spans
        #: be published on (and merged from) the shared wall clock
        self.anchor_mono_ns = mono_ns()
        self.anchor_wall_us = wall_us()
        #: remote spans merged in via add_remote_spans:
        #: process label -> list of re-based Span
        self._remote: Dict[str, List[Any]] = {}
        #: per-element (proctime, interlatency) histograms; registered
        #: into the global metrics registry so the live endpoint serves
        #: the same distributions the report prints
        self._hists: Dict[str, Tuple[Any, Any]] = {}
        # resilience counters (query/resilience.py STATS) are process-wide
        # and monotonic; snapshot at attach so the report shows only THIS
        # run's retries/failures/breaker transitions.  Lazy import: the
        # query package is a consumer of the pipeline package.
        from ..query.resilience import STATS

        self._resilience = STATS
        self._resilience_base = STATS.snapshot()

    # called from Element._chain_entry — keep it lean
    def enter(self, name: Optional[str] = None, buf=None) -> None:
        _stack().append([self, time.monotonic_ns(), 0, 0, 0, 0, buf,
                         name])

    def exit(self, element_name: Optional[str] = None) -> None:
        stack = _TLS.stack
        frame = stack.pop()
        end = time.monotonic_ns()
        total = end - frame[1]
        if stack:                    # attribute our total to the parent
            stack[-1][2] += total
        name = element_name if element_name is not None else frame[7]
        buf = frame[6]
        inter_ns = -1
        seq = -1
        weight = 1
        trace_id = self.trace_id
        if buf is not None:
            extra = buf.extra
            src_ns = extra.get("nns_src_ns")
            if src_ns is not None:
                inter_ns = end - src_ns
            seq = extra.get("nns_seq", -1)
            ctx = extra.get("nns_trace")
            if ctx is not None and ctx.trace_id:
                trace_id = ctx.trace_id
            xbm = extra.get("nns_xbatch")
            if xbm is not None:
                # a cross-stream bucket is ONE dispatch serving N
                # client frames: count them, or per-frame rates read
                # a batching server as 1/N of its real throughput
                weight = len(xbm.extras) or 1
        if self.ring is not None:
            from ..obs.span import Span

            self.ring.append(Span(name, threading.get_ident(),
                                  frame[1], total, seq, trace_id))
        self._record(name, total - frame[2], frame[3], frame[4],
                     frame[5], inter_ns, weight)

    def annotate_span(self, state: str, start_ns: int, end_ns: int,
                      seq: int = -1, trace_id: int = 0) -> None:
        """Ring-append a ``state:*`` annotation from a thread with no
        live trace frame (queue drain, worker pusher, serversrc create).
        No-op without span recording; callers gate on
        ``tracer.ring is not None`` before reading any clock."""
        if self.ring is None:
            return
        from ..obs.span import Span

        self.ring.append(Span("state:" + state, threading.get_ident(),
                              start_ns, max(0, end_ns - start_ns), seq,
                              trace_id or self.trace_id))

    def _element_hists(self, name: str):
        hists = self._hists.get(name)
        if hists is None:
            from ..obs.metrics import REGISTRY, Histogram

            with self._lock:          # two streaming threads, first buffer
                hists = self._hists.get(name)
                if hists is None:
                    proc = Histogram("nns_element_proctime_us",
                                     {"element": name})
                    inter = Histogram("nns_element_interlatency_us",
                                      {"element": name})
                    hists = self._hists[name] = (proc, inter)
            REGISTRY.register(hists[0])
            REGISTRY.register(hists[1])
        return hists

    def _record(self, element_name: str, proc_ns: int, copied: int,
                hits: int, misses: int, inter_ns: int = -1,
                frames: int = 1) -> None:
        now = time.monotonic()
        with self._lock:
            st = self._stats.get(element_name)
            if st is None:
                st = self._stats[element_name] = _ElementStats()
                st.first_ts = now
            st.buffers += 1
            st.frames += frames
            st.proc_ns += proc_ns
            st.last_ts = now
            st.bytes_copied += copied
            st.pool_hits += hits
            st.pool_misses += misses
            if inter_ns >= 0:
                st.inter_ns += inter_ns
                st.inter_n += 1
        proc_h, inter_h = self._element_hists(element_name)
        proc_h.observe(proc_ns / 1e3)
        if inter_ns >= 0:
            inter_h.observe(inter_ns / 1e3)

    def report(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            items = [(name, st, self._hists.get(name))
                     for name, st in self._stats.items()]
        out: Dict[str, Dict[str, float]] = {}
        for name, st, hists in items:
            window = ((st.last_ts - st.first_ts)
                      if st.buffers > 1 else 0.0)
            row = out[name] = {
                "buffers": st.buffers,
                "proctime_ms": round(st.proc_ns / 1e6, 3),
                "proctime_avg_us": round(
                    st.proc_ns / 1e3 / max(st.buffers, 1), 2),
                "fps": round((st.buffers - 1) / window, 2)
                if window > 0 else 0.0,
                "window_s": round(window, 4),
                "bytes_copied": st.bytes_copied,
            }
            if st.frames != st.buffers:
                # cross-stream buckets: per-frame truth next to the
                # per-dispatch count (fps above stays per-dispatch)
                row["frames"] = st.frames
                if window > 0:
                    row["frames_per_s"] = round(st.frames / window, 2)
            if st.pool_hits or st.pool_misses:
                row["pool_hits"] = st.pool_hits
                row["pool_misses"] = st.pool_misses
            if hists is not None:
                proc_h, inter_h = hists
                snap = proc_h.snapshot()
                for q in ("p50", "p95", "p99"):
                    if q in snap:
                        row[f"proctime_{q}_us"] = snap[q]
            if st.inter_n:
                row["interlatency_avg_us"] = round(
                    st.inter_ns / 1e3 / st.inter_n, 2)
                if hists is not None:
                    snap = hists[1].snapshot()
                    for q in ("p50", "p95", "p99"):
                        if q in snap:
                            row[f"interlatency_{q}_us"] = snap[q]
        return out

    def resilience_report(self) -> Dict[str, int]:
        """Retry / failure / breaker-transition / heartbeat counters
        accumulated since this tracer attached (delta over the
        process-wide :data:`~nnstreamer_tpu.query.resilience.STATS`) —
        the dataflow-health half of the report, next to proctime.
        Empty when the run touched no remote endpoint."""
        return self._resilience.delta(self._resilience_base)

    # -- timeline export / merge ---------------------------------------------
    def publish_spans(self, since: int = 0,
                      trace_id: Optional[int] = None
                      ) -> Tuple[Dict[str, Any], int]:
        """Span batch for wire piggyback (T_TRACE): spans appended at
        ring index >= ``since`` (optionally filtered to one trace id),
        plus this process's mono↔wall anchor so the receiver can re-base
        them.  Returns ``(payload_dict, next_cursor)``."""
        if self.ring is None:
            return ({"anchor_mono_ns": self.anchor_mono_ns,
                     "anchor_wall_us": self.anchor_wall_us,
                     "spans": []}, since)
        spans, cursor = self.ring.snapshot_since(since)
        if trace_id is not None:
            spans = [s for s in spans if s.trace_id == trace_id]
        return ({"anchor_mono_ns": self.anchor_mono_ns,
                 "anchor_wall_us": self.anchor_wall_us,
                 "spans": [list(s) for s in spans]}, cursor)

    def add_remote_spans(self, payload: Dict[str, Any],
                         offset_us: int = 0,
                         process: str = "remote") -> int:
        """Merge a peer's ``publish_spans`` payload into this timeline.

        ``offset_us`` is the peer-minus-local wall-clock offset
        (obs/clock.py OffsetEstimator).  Each remote span's mono start is
        re-based: peer mono → peer wall (via the peer anchor) → local
        wall (offset) → local mono (via our anchor), so the merged
        Chrome export shows both processes on one consistent axis."""
        from ..obs.span import Span

        r_mono = int(payload.get("anchor_mono_ns", 0))
        r_wall = int(payload.get("anchor_wall_us", 0))
        merged = self._remote.setdefault(process, [])
        n = 0
        for raw in payload.get("spans", ()):
            name, tid, start_ns, dur_ns, seq, trace_id = raw
            peer_wall_us = r_wall + (int(start_ns) - r_mono) // 1000
            local_wall_us = peer_wall_us - offset_us
            local_mono_ns = (self.anchor_mono_ns
                             + (local_wall_us - self.anchor_wall_us)
                             * 1000)
            merged.append(Span(str(name), int(tid), local_mono_ns,
                               int(dur_ns), int(seq), int(trace_id)))
            n += 1
        return n

    def chrome_trace(self, process_name: str = "pipeline"
                     ) -> Dict[str, Any]:
        """Chrome ``trace_event`` document: local spans as pid 1, each
        merged remote process as its own pid."""
        from ..obs.span import chrome_trace_events

        events: List[Dict[str, Any]] = []
        local = self.ring.snapshot() if self.ring is not None else []
        events.extend(chrome_trace_events(local, pid=1,
                                          process_name=process_name))
        for i, (proc, spans) in enumerate(sorted(self._remote.items())):
            events.extend(chrome_trace_events(spans, pid=2 + i,
                                              process_name=proc))
        # per-process groups are each sorted; re-sort the MERGED stream
        # so a multi-process export is globally time-monotonic too
        events.sort(key=lambda e: (e["ph"] != "M", e.get("ts", 0.0)))
        meta: Dict[str, Any] = {"trace_id": f"{self.trace_id:x}"}
        if self.ring is not None and self.ring.dropped:
            meta["dropped_spans"] = self.ring.dropped
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "metadata": meta}

    def export_chrome(self, path: str,
                      process_name: str = "pipeline") -> None:
        import json

        with open(path, "w", encoding="utf-8") as fh:
            json.dump(self.chrome_trace(process_name), fh)
