"""Pipeline tracing: per-element proctime / framerate / queue levels.

The reference's profiling story is external GStreamer tracers — GstShark's
``proctime`` (time inside each element's chain), ``framerate`` (buffers/s
per pad) and ``interlatency`` hooks (tools/tracing/README.md:33-43,
tools/profiling/README.md:5-17).  Here tracing is built into the pipeline
substrate: attach a :class:`Tracer` and every ``chain()`` is timed with
one clock read on each side — nanosecond counters, no sampling, zero cost
when no tracer is attached (a single ``is None`` test per buffer).

Usage::

    p = parse_launch("videotestsrc num-buffers=64 ! … ! tensor_sink")
    tracer = p.enable_tracing()
    p.run(timeout=60)
    print(json.dumps(tracer.report(), indent=2))

``launch.py --trace`` prints the same report after the pipeline ends.

Report fields per element: ``buffers``, ``proctime_ms`` (total time inside
chain), ``proctime_avg_us``, ``fps`` (buffers/sec over the element's
active window) — the proctime/framerate tracer pair.  ``interlatency``
(source-to-element transit) is derivable from per-element first/last
timestamps included as ``window_s``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class _ElementStats:
    __slots__ = ("buffers", "proc_ns", "first_ts", "last_ts")

    def __init__(self) -> None:
        self.buffers = 0
        self.proc_ns = 0
        self.first_ts: Optional[float] = None
        self.last_ts: Optional[float] = None


class Tracer:
    """Collects per-element dataflow statistics (thread-safe: elements
    chain from multiple streaming threads).

    Dataflow is synchronous within a streaming thread — an element's
    ``chain()`` pushes downstream before returning — so SELF time is
    wall time minus the nested downstream chains' time.  A per-thread
    frame stack does that subtraction, matching GstShark's proctime
    semantics (time inside ONE element)."""

    def __init__(self) -> None:
        self._stats: Dict[str, _ElementStats] = {}
        self._lock = threading.Lock()
        self._tls = threading.local()
        # resilience counters (query/resilience.py STATS) are process-wide
        # and monotonic; snapshot at attach so the report shows only THIS
        # run's retries/failures/breaker transitions.  Lazy import: the
        # query package is a consumer of the pipeline package.
        from ..query.resilience import STATS

        self._resilience = STATS
        self._resilience_base = STATS.snapshot()

    # called from Element._chain_entry — keep it lean
    def enter(self) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append([time.monotonic_ns(), 0])   # [start, child_ns]

    def exit(self, element_name: str) -> None:
        stack = self._tls.stack
        start, child_ns = stack.pop()
        total = time.monotonic_ns() - start
        if stack:                    # attribute our total to the parent
            stack[-1][1] += total
        self._record(element_name, total - child_ns)

    def _record(self, element_name: str, proc_ns: int) -> None:
        now = time.monotonic()
        with self._lock:
            st = self._stats.get(element_name)
            if st is None:
                st = self._stats[element_name] = _ElementStats()
                st.first_ts = now
            st.buffers += 1
            st.proc_ns += proc_ns
            st.last_ts = now

    def report(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for name, st in self._stats.items():
                window = ((st.last_ts - st.first_ts)
                          if st.buffers > 1 else 0.0)
                out[name] = {
                    "buffers": st.buffers,
                    "proctime_ms": round(st.proc_ns / 1e6, 3),
                    "proctime_avg_us": round(
                        st.proc_ns / 1e3 / max(st.buffers, 1), 2),
                    "fps": round((st.buffers - 1) / window, 2)
                    if window > 0 else 0.0,
                    "window_s": round(window, 4),
                }
        return out

    def resilience_report(self) -> Dict[str, int]:
        """Retry / failure / breaker-transition / heartbeat counters
        accumulated since this tracer attached (delta over the
        process-wide :data:`~nnstreamer_tpu.query.resilience.STATS`) —
        the dataflow-health half of the report, next to proctime.
        Empty when the run touched no remote endpoint."""
        return self._resilience.delta(self._resilience_base)
