"""Pipeline tracing: per-element proctime / framerate / queue levels.

The reference's profiling story is external GStreamer tracers — GstShark's
``proctime`` (time inside each element's chain), ``framerate`` (buffers/s
per pad) and ``interlatency`` hooks (tools/tracing/README.md:33-43,
tools/profiling/README.md:5-17).  Here tracing is built into the pipeline
substrate: attach a :class:`Tracer` and every ``chain()`` is timed with
one clock read on each side — nanosecond counters, no sampling, zero cost
when no tracer is attached (a single ``is None`` test per buffer).

Usage::

    p = parse_launch("videotestsrc num-buffers=64 ! … ! tensor_sink")
    tracer = p.enable_tracing()
    p.run(timeout=60)
    print(json.dumps(tracer.report(), indent=2))

``launch.py --trace`` prints the same report after the pipeline ends.

Report fields per element: ``buffers``, ``proctime_ms`` (total time inside
chain), ``proctime_avg_us``, ``fps`` (buffers/sec over the element's
active window) — the proctime/framerate tracer pair.  ``interlatency``
(source-to-element transit) is derivable from per-element first/last
timestamps included as ``window_s``.

Fused segment plans (pipeline/schedule.py) keep these semantics exactly:
a compiled executor calls the same :meth:`Tracer.enter` /
:meth:`Tracer.exit` pair around each fused step that
``Element._chain_entry`` uses around ``chain()``, so per-element
``buffers``/``proctime`` are identical under fusion — and with no tracer
attached the compiled executor contains NO tracer references at all
(plans rebuild when ``enable_tracing`` attaches one), which is how
tracing costs zero calls when off instead of one test per element per
buffer.

Dataflow-copy observability (the zero-copy hot path's regression gate):
serialize/convert code reports every payload byte it MATERIALIZES into a
new host buffer via :func:`record_copy`, and pool acquires report
hits/misses via :func:`record_pool`.  Both attribute to the element whose
``chain()`` is on the current thread's trace stack, surfacing as
``bytes_copied`` / ``pool_hits`` / ``pool_misses`` in the report — so a
re-introduced full-frame copy shows up per element instead of hiding in
wall time.  With no tracer attached both calls are a single dict lookup.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class _ElementStats:
    __slots__ = ("buffers", "proc_ns", "first_ts", "last_ts",
                 "bytes_copied", "pool_hits", "pool_misses")

    def __init__(self) -> None:
        self.buffers = 0
        self.proc_ns = 0
        self.first_ts: Optional[float] = None
        self.last_ts: Optional[float] = None
        self.bytes_copied = 0
        self.pool_hits = 0
        self.pool_misses = 0


#: process-wide per-thread trace frame stack.  Each entry is one live
#: ``chain()``: [tracer, start_ns, child_ns, bytes_copied, pool_hits,
#: pool_misses].  Module-level (not per-Tracer) so record_copy /
#: record_pool reach the active frame without any registry lookups.
_TLS = threading.local()


def _stack():
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


def record_copy(nbytes: int) -> None:
    """Report ``nbytes`` of payload materialized into a fresh host buffer
    (``tobytes``/``ascontiguousarray``/adapter compaction...).  Attributes
    to the element currently in ``chain()`` on this thread; no-op (one
    getattr) when no tracer is active."""
    stack = getattr(_TLS, "stack", None)
    if stack:
        stack[-1][3] += nbytes


def record_pool(hit: bool) -> None:
    """Report one pool acquire (hit = served from the free list)."""
    stack = getattr(_TLS, "stack", None)
    if stack:
        stack[-1][4 if hit else 5] += 1


class copy_probe:
    """Standalone copy/pool counter for code that isn't a pipeline
    element (microbenches, unit tests)::

        with copy_probe() as probe:
            send_tensors(...)
        assert probe.bytes_copied <= header_bytes

    Pushes a synthetic frame on this thread's trace stack, so
    record_copy / record_pool attribute to it.
    """

    def __init__(self) -> None:
        self.bytes_copied = 0
        self.pool_hits = 0
        self.pool_misses = 0

    def __enter__(self) -> "copy_probe":
        _stack().append([None, 0, 0, 0, 0, 0])
        return self

    def __exit__(self, *exc) -> None:
        frame = _stack().pop()
        self.bytes_copied += frame[3]
        self.pool_hits += frame[4]
        self.pool_misses += frame[5]


class Tracer:
    """Collects per-element dataflow statistics (thread-safe: elements
    chain from multiple streaming threads).

    Dataflow is synchronous within a streaming thread — an element's
    ``chain()`` pushes downstream before returning — so SELF time is
    wall time minus the nested downstream chains' time.  A per-thread
    frame stack does that subtraction, matching GstShark's proctime
    semantics (time inside ONE element)."""

    def __init__(self) -> None:
        from ..analysis.sanitizer import make_lock

        self._stats: Dict[str, _ElementStats] = {}
        self._lock = make_lock("tracer")
        # resilience counters (query/resilience.py STATS) are process-wide
        # and monotonic; snapshot at attach so the report shows only THIS
        # run's retries/failures/breaker transitions.  Lazy import: the
        # query package is a consumer of the pipeline package.
        from ..query.resilience import STATS

        self._resilience = STATS
        self._resilience_base = STATS.snapshot()

    # called from Element._chain_entry — keep it lean
    def enter(self) -> None:
        _stack().append([self, time.monotonic_ns(), 0, 0, 0, 0])

    def exit(self, element_name: str) -> None:
        stack = _TLS.stack
        frame = stack.pop()
        total = time.monotonic_ns() - frame[1]
        if stack:                    # attribute our total to the parent
            stack[-1][2] += total
        self._record(element_name, total - frame[2], frame[3], frame[4],
                     frame[5])

    def _record(self, element_name: str, proc_ns: int, copied: int,
                hits: int, misses: int) -> None:
        now = time.monotonic()
        with self._lock:
            st = self._stats.get(element_name)
            if st is None:
                st = self._stats[element_name] = _ElementStats()
                st.first_ts = now
            st.buffers += 1
            st.proc_ns += proc_ns
            st.last_ts = now
            st.bytes_copied += copied
            st.pool_hits += hits
            st.pool_misses += misses

    def report(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for name, st in self._stats.items():
                window = ((st.last_ts - st.first_ts)
                          if st.buffers > 1 else 0.0)
                out[name] = {
                    "buffers": st.buffers,
                    "proctime_ms": round(st.proc_ns / 1e6, 3),
                    "proctime_avg_us": round(
                        st.proc_ns / 1e3 / max(st.buffers, 1), 2),
                    "fps": round((st.buffers - 1) / window, 2)
                    if window > 0 else 0.0,
                    "window_s": round(window, 4),
                    "bytes_copied": st.bytes_copied,
                }
                if st.pool_hits or st.pool_misses:
                    out[name]["pool_hits"] = st.pool_hits
                    out[name]["pool_misses"] = st.pool_misses
        return out

    def resilience_report(self) -> Dict[str, int]:
        """Retry / failure / breaker-transition / heartbeat counters
        accumulated since this tracer attached (delta over the
        process-wide :data:`~nnstreamer_tpu.query.resilience.STATS`) —
        the dataflow-health half of the report, next to proctime.
        Empty when the run touched no remote endpoint."""
        return self._resilience.delta(self._resilience_base)
