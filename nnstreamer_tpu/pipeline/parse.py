"""gst-launch-style pipeline string parser.

The reference's user API is gst-launch pipeline strings (every SSAT golden
test builds one, e.g. tests/nnstreamer_filter_tensorflow2_lite/runTest.sh).
This parser accepts the same shape of syntax::

    parse_launch("videotestsrc num-buffers=10 ! "
                 "video/x-raw,format=RGB,width=224,height=224 ! "
                 "tensor_converter ! "
                 "tensor_filter framework=xla model=mobilenet_v2 ! "
                 "tensor_sink name=out")

Supported: element factories with ``key=value`` properties, ``!`` links,
caps-filter segments (a bare caps string between ``!``), ``name=`` element
naming, branch references ``name. ! ...`` (tee/demux fan-out).
"""

from __future__ import annotations

import shlex
from typing import List, Optional

from .caps import Caps
from .element import CapsEvent, Element, FlowReturn
from .graph import Pipeline
from .registry import make_element, register_element


@register_element
class CapsFilter(Element):
    """Pass-through element that constrains negotiation (GStreamer
    ``capsfilter`` role — what a bare caps string in a launch line becomes).
    """

    FACTORY = "capsfilter"
    PROPERTIES = {"caps": (None, "constraint caps")}

    def _make_pads(self):
        self.add_sink_pad(Caps.any(), "sink")
        self.add_src_pad(Caps.any(), "src")

    def _constraint(self) -> Caps:
        constraint = self.caps
        if isinstance(constraint, str):
            constraint = Caps.from_string(constraint)
        return constraint if constraint is not None else Caps.any()

    def set_caps(self, pad, caps):
        inter = caps.intersect(self._constraint())
        if inter.is_empty():
            raise ValueError(
                f"capsfilter {self.name}: {caps} does not satisfy "
                f"{self._constraint()}")
        self.src_pad.push_event(CapsEvent(caps))

    def get_allowed_caps(self, sink_pad):
        downstream = self.src_pad.peer_allowed_caps()
        return self._constraint().intersect(downstream)

    def chain(self, pad, buf):
        return self.src_pad.push(buf)


def _coerce(value: str):
    try:
        return int(value, 0)  # handles decimal and 0x… hex
    except ValueError:
        pass
    try:
        return float(value)
    except ValueError:
        pass
    if value.lower() in ("true", "false"):
        return value.lower() == "true"
    return value


def parse_launch(description: str, pipeline: Optional[Pipeline] = None) -> Pipeline:
    """Build a :class:`Pipeline` from a launch string."""
    p = pipeline or Pipeline()
    # split into segments on '!'
    segments = [s.strip() for s in description.split("!")]
    prev: Optional[Element] = None
    for seg in segments:
        if not seg:
            raise ValueError("empty segment in launch string")
        tokens = shlex.split(seg)
        head = tokens[0]
        # branch reference: "name."
        if head.endswith(".") and len(tokens) == 1:
            prev = p.get(head[:-1])
            continue
        # caps filter: token containing '/' before any '=' (media type)
        if "/" in head and "=" not in head.split(",")[0]:
            el = CapsFilter(None, caps=Caps.from_string(seg.replace(" ", "")))
            p.add(el)
            if prev is not None:
                p.link(prev, el)
            prev = el
            continue
        props = {}
        name = None
        for tok in tokens[1:]:
            k, _, v = tok.partition("=")
            if k == "name":
                name = v
            else:
                props[k] = _coerce(v)
        el = make_element(head, name, **props)
        p.add(el)
        if prev is not None:
            p.link(prev, el)
        prev = el
    return p
