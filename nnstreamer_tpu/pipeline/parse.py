"""gst-launch-style pipeline string parser.

The reference's user API is gst-launch pipeline strings (every SSAT golden
test builds one, e.g. tests/nnstreamer_filter_tensorflow2_lite/runTest.sh).
This parser accepts the same shape of syntax::

    parse_launch("videotestsrc num-buffers=10 ! "
                 "video/x-raw,format=RGB,width=224,height=224 ! "
                 "tensor_converter ! "
                 "tensor_filter framework=xla model=mobilenet_v2 ! "
                 "tensor_sink name=out")

Supported: element factories with ``key=value`` properties, ``!`` links,
caps-filter segments (a bare caps string between ``!``), ``name=`` element
naming, and gst-launch's multi-chain grammar — whitespace without ``!``
starts a new chain, ``name. ! ...`` branches from an element (tee/demux
fan-out), ``... ! name.`` links into one (mux/merge fan-in), with forward
references allowed.
"""

from __future__ import annotations

import shlex
from typing import List, Optional

from .caps import Caps
from .element import CapsEvent, Element
from .graph import Pipeline
from .registry import make_element, register_element


class ParseError(ValueError):
    """Single error domain for malformed launch strings — the role of
    GStreamer's GST_PARSE_ERROR quark (no-such-element, link failures,
    bad syntax all surface as one catchable type;
    gst/parse/grammar.y).  Subclasses ValueError so existing callers
    catching ValueError keep working; parser internals must never leak
    a raw KeyError/NotImplementedError to the user."""


@register_element
class CapsFilter(Element):
    """Pass-through element that constrains negotiation (GStreamer
    ``capsfilter`` role — what a bare caps string in a launch line becomes).
    """

    FACTORY = "capsfilter"
    PROPERTIES = {"caps": (None, "constraint caps")}

    def _make_pads(self):
        self.add_sink_pad(Caps.any(), "sink")
        self.add_src_pad(Caps.any(), "src")

    def _constraint(self) -> Caps:
        constraint = self.caps
        if isinstance(constraint, str):
            constraint = Caps.from_string(constraint)
        return constraint if constraint is not None else Caps.any()

    def set_caps(self, pad, caps):
        inter = caps.intersect(self._constraint())
        if inter.is_empty():
            raise ValueError(
                f"capsfilter {self.name}: {caps} does not satisfy "
                f"{self._constraint()}")
        self.src_pad.push_event(CapsEvent(caps))

    def get_allowed_caps(self, sink_pad):
        downstream = self.src_pad.peer_allowed_caps()
        return self._constraint().intersect(downstream)

    def chain(self, pad, buf):
        return self.src_pad.push(buf)

    def _passthrough(self, buf):
        return buf

    def plan_step(self):
        # negotiation work all happens at caps time; per-buffer this is a
        # pure passthrough, so fused dispatch elides it entirely
        return self._passthrough

    def lower_reason(self):
        return None

    def lower_step(self):
        from .element import LoweredStep

        return LoweredStep(lambda params, ts: ts)


def _coerce(value: str):
    try:
        return int(value, 0)  # handles decimal and 0x… hex
    except ValueError:
        pass
    try:
        return float(value)
    except ValueError:
        pass
    if value.lower() in ("true", "false"):
        return value.lower() == "true"
    return value


def _is_prop(tok: str) -> bool:
    """``key=value`` tokens attach to the preceding element head."""
    k, eq, _ = tok.partition("=")
    return bool(eq) and "/" not in k and not k.endswith(".")


def iter_launch_ops(description: str):
    """Tokenize a launch string into grammar operations — the single
    tokenizer shared by :func:`parse_launch` and tools/pbtxt_pipeline.py.

    Yields tuples:
      ``("link",)``                  — a ``!``
      ``("ref", name)``              — a ``name.`` branch/sink reference
      ``("caps", caps_string)``      — a caps-filter segment
      ``("element", head, props, name)`` — an element with properties
    """
    tokens = shlex.split(description)
    i = 0
    while i < len(tokens):
        tok = tokens[i]
        if tok == "!":
            yield ("link",)
        elif tok.endswith(".") and "=" not in tok:
            yield ("ref", tok[:-1], None)
        elif ("." in tok and "=" not in tok and "/" not in tok
              and not tok.replace(".", "").isdigit()):
            # gst-launch named-pad reference: ``mux.sink_0``
            el_name, _, pad_name = tok.partition(".")
            yield ("ref", el_name, pad_name)
        elif "/" in tok and "=" not in tok.split(",")[0]:
            # caps filter — gst-launch allows spaces after commas
            # ("video/x-raw, format=RGB, width=224"): join follow-on
            # fragments until the next '!' into one caps string
            parts = [tok]
            while tok.endswith(",") and i + 1 < len(tokens) \
                    and tokens[i + 1] != "!":
                i += 1
                tok = tokens[i]
                parts.append(tok)
            yield ("caps", "".join(parts))
        else:
            head = tok
            props = []
            name = None
            while i + 1 < len(tokens) and _is_prop(tokens[i + 1]):
                k, _, v = tokens[i + 1].partition("=")
                if k == "name":
                    name = v
                else:
                    props.append((k, v))
                i += 1
            yield ("element", head, props, name)
        i += 1


class _ForwardRef:
    """A ``name.`` / ``name.pad`` branch-from reference to an element named
    later in the line (gst-launch allows both directions)."""

    __slots__ = ("name", "pad")

    def __init__(self, name: str, pad: Optional[str] = None):
        self.name = name
        self.pad = pad


def _parse_launch(description: str, pipeline: Optional[Pipeline]) -> Pipeline:
    """Build a :class:`Pipeline` from a launch string.

    Implements gst-launch's chain grammar: elements join with ``!``;
    whitespace without ``!`` ends a chain and starts a new one, so tee
    fan-out / mux fan-in read exactly like the reference pipelines::

        ... ! tee name=t ! tensor_sink name=a  t. ! tensor_sink name=b
        appsrc name=s1 ! mux.  appsrc name=s2 ! mux.  tensor_mux name=mux ! ...

    A trailing ``name.`` links the chain INTO that element (requesting a
    sink pad); a leading ``name.`` branches FROM it.  References may point
    forward — both directions resolve after all elements are created.
    """
    p = pipeline or Pipeline()
    prev = None                    # Element | _ForwardRef | None
    linked = False                 # saw '!' since the previous element
    into_refs: List[tuple] = []    # (src_el, sink_name, pad): '... ! name.'
    from_refs: List[tuple] = []    # (src_name, pad, sink_el): 'name. ! ...'
    ref_refs: List[tuple] = []     # 'a.src_0 ! b.sink_1' (both by name)
    for op in iter_launch_ops(description):
        kind = op[0]
        if kind == "link":
            if prev is None:
                raise ParseError("launch string: '!' with nothing upstream")
            linked = True
            continue
        if kind == "ref":
            name, pad = op[1], op[2]
            if linked:             # chain INTO named element (sink ref)
                if isinstance(prev, _ForwardRef):
                    # 'a.src_0 ! b.sink_1': both ends by reference
                    ref_refs.append((prev.name, prev.pad, name, pad))
                else:
                    into_refs.append((prev, name, pad))
                prev, linked = None, False
            else:                  # branch FROM named element
                if isinstance(prev, _ForwardRef):
                    raise ParseError(
                        f"launch string: reference '{prev.name}.' is never "
                        f"linked (followed by '{name}.' without '!')")
                prev = _ForwardRef(name, pad)
            continue
        if kind == "caps":
            el = p.add(CapsFilter(None, caps=Caps.from_string(op[1])))
        else:
            _, head, props, name = op
            el = p.add(make_element(
                head, name, **{k: _coerce(v) for k, v in props}))
        if linked:
            if isinstance(prev, _ForwardRef):
                from_refs.append((prev.name, prev.pad, el))
            else:
                p.link(prev, el)
        elif isinstance(prev, _ForwardRef):
            raise ParseError(
                f"launch string: reference '{prev.name}.' is never linked "
                f"(followed by an element without '!')")
        prev, linked = el, False
    if linked:
        raise ParseError("launch string ends with '!'")
    if isinstance(prev, _ForwardRef):
        raise ParseError(f"launch string: trailing reference '{prev.name}.'"
                         " is never linked")
    for src_name, src_pad, sink_el in from_refs:
        p.link_pads(p.get(src_name), src_pad, sink_el, None)
    for src_el, sink_name, sink_pad in into_refs:
        p.link_pads(src_el, None, p.get(sink_name), sink_pad)
    for src_name, src_pad, sink_name, sink_pad in ref_refs:
        p.link_pads(p.get(src_name), src_pad, p.get(sink_name), sink_pad)
    return p


def parse_launch(description: str, pipeline: Optional[Pipeline] = None) -> Pipeline:
    """Build a :class:`Pipeline` from a launch string (see
    :func:`_parse_launch` for the grammar).

    Error contract (the gst_parse_launch GError analogue): ANY
    malformed launch string raises :class:`ParseError` (a ValueError) —
    unknown element factories (a KeyError from the registry), unknown
    properties (an AttributeError from the element,
    GST_PARSE_ERROR_NO_SUCH_PROPERTY's case), branch/sink references to
    unknown or static-pad elements, link failures, unparsable caps
    values (down to Fraction's ZeroDivisionError on framerate=0/0),
    unbalanced quotes, and bad syntax alike.  Fuzzed in
    tests/test_pipeline.py."""
    try:
        return _parse_launch(description, pipeline)
    except ParseError:
        raise                      # already wrapped — no double prefix
    except (KeyError, NotImplementedError, AttributeError, ValueError,
            ZeroDivisionError) as exc:
        detail = exc.args[0] if exc.args else repr(exc)
        raise ParseError(f"launch string: {detail}") from exc
