"""Pipeline substrate: elements, pads, events.

The reference builds on GStreamer's element/pad/caps machinery (external, L0
in SURVEY.md) — pad push model, caps events, EOS propagation
(gst/nnstreamer/elements/* all subclass GstElement).  This module supplies
that substrate for the TPU framework, redesigned rather than ported:

- **Push model**: a src :class:`Pad` pushes :class:`TensorBuffer` s into its
  peer sink pad, which dispatches to the owning element's ``chain``.
  Dataflow is synchronous within a streaming thread; :class:`Queue` elements
  (graph.py) create thread boundaries exactly like GStreamer queues.
- **Negotiation**: upstream decides fixed caps and announces them with a
  :class:`CapsEvent`; each element validates against its sink template,
  computes its out caps, and forwards a new CapsEvent.  Templates are checked
  at link time so impossible graphs fail fast.
- **Events**: CAPS / EOS / SEGMENT / CUSTOM flow downstream in-band, like
  GStreamer serialized events.  Custom events carry dict payloads (used for
  model-update, reference tensor_filter.c:1413-1446).
"""

from __future__ import annotations

import enum
import time
from typing import Any, Dict, List, Optional

from ..analysis.sanitizer import make_rlock
from ..tensor.buffer import TensorBuffer
from .caps import Caps


class FlowReturn(enum.Enum):
    OK = "ok"
    EOS = "eos"
    ERROR = "error"
    #: buffer intentionally dropped (e.g. QoS throttling, tensor_filter.c:609)
    DROPPED = "dropped"


class Event:
    """Base in-band event."""

    def __repr__(self):
        return self.__class__.__name__


class CapsEvent(Event):
    def __init__(self, caps: Caps):
        if not caps.is_fixed():
            raise ValueError(f"CapsEvent requires fixed caps, got {caps}")
        self.caps = caps

    def __repr__(self):
        return f"CapsEvent({self.caps})"


class EOSEvent(Event):
    pass


class SegmentEvent(Event):
    def __init__(self, start_ns: int = 0):
        self.start_ns = start_ns


class CustomEvent(Event):
    def __init__(self, name: str, data: Optional[Dict[str, Any]] = None):
        self.name = name
        self.data = data or {}

    def __repr__(self):
        return f"CustomEvent({self.name})"


class QoSEvent(Event):
    """Upstream QoS feedback (GStreamer GST_EVENT_QOS role): a downstream
    consumer reports it cannot keep up.  ``timestamp`` is the PTS of the
    late buffer, ``jitter_ns`` > 0 how late it ran, ``proportion`` the
    observed slowdown ratio (1.0 = real-time, 2.0 = consuming at half
    speed).  tensor_filter consumes these to throttle-drop (reference
    tensor_filter.c:609,1454-1485); tensor_rate adapts its target rate."""

    def __init__(self, timestamp: Optional[int], jitter_ns: int,
                 proportion: float = 1.0):
        self.timestamp = timestamp
        self.jitter_ns = jitter_ns
        self.proportion = proportion

    def __repr__(self):
        return (f"QoSEvent(ts={self.timestamp} jitter={self.jitter_ns} "
                f"proportion={self.proportion:.2f})")


class LoweredStep:
    """One element's contribution to a whole-segment XLA computation
    (``fuse=xla`` lowering tier, pipeline/schedule.py).

    ``fn(params, tensors) -> tensors`` must be a PURE jax-traceable
    function over a list of array payloads: no host materialization
    (``TensorBuffer.np()``/``np.asarray`` — enforced by the nnslint
    ``host-sync-in-lower`` rule), no buffer metadata access, no side
    effects.  ``params`` is the element's device pytree (weights for a
    filter, ``None`` for stateless transforms); the segment compiler
    passes it as a jit ARGUMENT, not a closure constant, so weights are
    never baked into the compiled graph (no constant-folding bloat, no
    stale weights silently embedded).  A model update still drops the
    plan via the custom-event invalidation (epoch machinery) and the
    segment re-lowers against the new state on its next buffer.

    ``post`` (optional) is a cheap host finisher ``post(buf) -> buf``
    run at SEGMENT EXIT, outside the jitted region — the escape hatch
    for decoders whose output is not a tensor (label lookup over a
    device-reduced argmax index).  Only the LAST element of a segment
    may carry one; an interior ``post`` makes the segment fall back to
    fuse-python.
    """

    __slots__ = ("fn", "params", "post")

    def __init__(self, fn, params=None, post=None) -> None:
        self.fn = fn
        self.params = params
        self.post = post


class PadDirection(enum.Enum):
    SRC = "src"
    SINK = "sink"


class Pad:
    """Connection point on an element.

    Mirrors the GstPad role: owns template caps, negotiated current caps, and
    a peer link.  A src pad's :meth:`push` / :meth:`push_event` drive the
    peer element synchronously.
    """

    def __init__(self, element: "Element", name: str,
                 direction: PadDirection, template: Caps):
        self.element = element
        self.name = name
        self.direction = direction
        self.template = template
        self.peer: Optional["Pad"] = None
        self.caps: Optional[Caps] = None  # negotiated, fixed
        self.eos = False

    @property
    def full_name(self) -> str:
        return f"{self.element.name}.{self.name}"

    # -- linking -------------------------------------------------------------
    def link(self, sink: "Pad") -> None:
        if self.direction is not PadDirection.SRC:
            raise ValueError(f"{self.full_name} is not a src pad")
        if sink.direction is not PadDirection.SINK:
            raise ValueError(f"{sink.full_name} is not a sink pad")
        if self.peer is not None or sink.peer is not None:
            raise ValueError(
                f"pad already linked: {self.full_name} / {sink.full_name}")
        if not self.template.can_intersect(sink.template):
            raise ValueError(
                f"cannot link {self.full_name} ({self.template}) to "
                f"{sink.full_name} ({sink.template}): no common caps")
        self.peer = sink
        sink.peer = self
        # request-pad link after play: the fused-dispatch head set changed
        # (a new tee branch is a new head) — rescan (schedule.py)
        for el in (self.element, sink.element):
            pl = getattr(el, "pipeline", None)
            if pl is not None and getattr(pl, "planner", None) is not None:
                pl.planner.rescan()
                break

    # -- dataflow (called on src pads) --------------------------------------
    def push(self, buf: TensorBuffer) -> FlowReturn:
        if self.peer is None:
            raise RuntimeError(f"pushing on unlinked pad {self.full_name}")
        if self.eos:
            return FlowReturn.EOS
        return self.peer.element._chain_entry(self.peer, buf)

    def push_event(self, event: Event) -> None:
        if isinstance(event, CapsEvent):
            self.caps = event.caps
        if isinstance(event, EOSEvent):
            self.eos = True
        if self.peer is not None:
            self.peer.element._event_entry(self.peer, event)

    # -- upstream events (called on sink pads) -------------------------------
    def push_upstream_event(self, event: Event) -> bool:
        """Send an event upstream from a sink pad (the GStreamer
        upstream-event role: QoS, reconfigure).  Delivered synchronously;
        returns True when some upstream element handled it."""
        if self.direction is not PadDirection.SINK or self.peer is None:
            return False
        return self.peer.element._upstream_event_entry(self.peer, event)

    def peer_allowed_caps(self) -> Caps:
        """Downstream CAPS query (GStreamer gst_pad_peer_query_caps role):
        what would the peer accept?  Passthrough elements forward the query
        further downstream, so a source can honor capsfilter constraints."""
        if self.peer is None:
            return Caps.any()
        allowed = self.peer.element.get_allowed_caps(self.peer)
        return allowed.intersect(self.peer.template)


class Element:
    """Base pipeline element.

    Subclasses declare pad templates via :meth:`_make_pads` (or call
    ``add_sink_pad``/``add_src_pad``) and implement:

    - ``chain(pad, buf) -> FlowReturn`` — per-buffer processing
    - ``set_caps(pad, caps) -> None`` — sink caps arrived; element must
      negotiate and announce src caps (helpers provided)
    - optionally ``start()``/``stop()`` lifecycle hooks and ``on_event``.

    Properties use the GObject-property role (the reference's de-facto user
    API, set in launch strings): declared in class attr ``PROPERTIES`` as
    ``{prop_name: (default, doc)}``, settable via :meth:`set_property` with
    automatic ``-``→``_`` normalization.
    """

    #: element type name used in launch strings (override)
    FACTORY: str = ""
    PROPERTIES: Dict[str, Any] = {}
    #: reference G_PARAM_READABLE-only property names: a write raises
    #: ValueError (the reference emits a critical warning), reads go
    #: through get_property as usual.  Entries need not appear in
    #: PROPERTIES (python-property readouts like tensor_filter's
    #: latency/throughput belong here too).
    READONLY_PROPERTIES: "tuple" = ()

    def __init__(self, name: Optional[str] = None, **props):
        self.name = name or f"{self.FACTORY or self.__class__.__name__.lower()}{id(self) & 0xffff}"
        self.sink_pads: List[Pad] = []
        self.src_pads: List[Pad] = []
        self.pipeline = None  # set by Pipeline.add
        self._lock = make_rlock("element")
        self._started = False
        for props_map in (self.UNIVERSAL_PROPERTIES, self.PROPERTIES):
            for key, spec in props_map.items():
                default = spec[0] if isinstance(spec, tuple) else spec
                setattr(self, key.replace("-", "_"), default)
        self._make_pads()
        for k, v in props.items():
            self.set_property(k, v)

    # -- pads ----------------------------------------------------------------
    def _make_pads(self) -> None:
        """Override to create pads (default: none)."""

    def add_sink_pad(self, template: Caps, name: Optional[str] = None) -> Pad:
        pad = Pad(self, name or f"sink_{len(self.sink_pads)}",
                  PadDirection.SINK, template)
        self.sink_pads.append(pad)
        return pad

    def add_src_pad(self, template: Caps, name: Optional[str] = None) -> Pad:
        pad = Pad(self, name or f"src_{len(self.src_pads)}",
                  PadDirection.SRC, template)
        self.src_pads.append(pad)
        return pad

    @property
    def sink_pad(self) -> Pad:
        return self.sink_pads[0]

    @property
    def src_pad(self) -> Pad:
        return self.src_pads[0]

    def request_sink_pad(self) -> Pad:
        """For N-to-1 elements (mux/merge): create a new sink pad on demand
        (GStreamer request-pad role)."""
        raise NotImplementedError(f"{self.FACTORY} has static pads")

    def request_src_pad(self) -> Pad:
        """For 1-to-N elements (demux/split/tee)."""
        raise NotImplementedError(f"{self.FACTORY} has static pads")

    # -- properties ----------------------------------------------------------

    #: properties EVERY reference element accepts (every nnstreamer
    #: element inherits GObject "silent" for verbose-log suppression —
    #: ssat launch lines set it liberally, so rejecting it would break
    #: verbatim reference pipelines)
    UNIVERSAL_PROPERTIES = {
        "silent": (True, "suppress verbose per-element logging"),
        "async": (False, "GstBaseSink async state-change flag, accepted "
                         "for launch-line parity (ssat sinks set "
                         "async=false everywhere; state changes here "
                         "are synchronous regardless)"),
    }

    def set_property(self, key: str, value: Any) -> None:
        attr = key.replace("-", "_")
        if key in self.READONLY_PROPERTIES \
                or attr in self.READONLY_PROPERTIES:
            raise ValueError(f"{self.FACTORY}: property {key!r} is "
                             "read-only")
        if (key not in self.PROPERTIES and attr not in self.PROPERTIES
                and key not in self.UNIVERSAL_PROPERTIES):
            raise AttributeError(f"{self.FACTORY}: no property {key!r}")
        setattr(self, attr, value)

    def get_property(self, key: str) -> Any:
        return getattr(self, key.replace("-", "_"))

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        """PLAYING transition hook (reference: GstBaseTransform start, e.g.
        tensor_filter.c:1492 opening the framework)."""

    def stop(self) -> None:
        """NULL transition hook."""

    def unblock(self) -> None:
        """Pre-stop hook: release any blocking waits (sync sinks, etc.)
        so upstream streaming threads can run to completion before the
        teardown joins them."""

    # -- dataflow entries (called by pads) -----------------------------------
    def _chain_entry(self, pad: Pad, buf: TensorBuffer) -> FlowReturn:
        tracer = (self.pipeline.tracer
                  if self.pipeline is not None else None)
        try:
            if tracer is None:
                return self.chain(pad, buf)
            tracer.enter(self.name, buf)
            try:
                return self.chain(pad, buf)
            finally:
                tracer.exit()
        except Exception as exc:  # noqa: BLE001 - becomes pipeline error
            if self.pipeline is not None:
                self.pipeline.post_error(self, exc)
                return FlowReturn.ERROR
            raise

    def _event_entry(self, pad: Pad, event: Event) -> None:
        if isinstance(event, CapsEvent):
            pad.caps = event.caps
            # caps (re)negotiation changes what fused dispatch plans may
            # assume around THIS element: drop the affected plans; the
            # next buffer recompiles against the new state (schedule.py).
            # No-op when not fused; scoped so an event crossing a queue
            # late never wipes unrelated segments' plans.
            pl = self.pipeline
            if pl is not None and getattr(pl, "planner", None) is not None:
                pl.planner.invalidate(element=self)
            try:
                self.set_caps(pad, event.caps)
            except Exception as exc:  # noqa: BLE001
                if self.pipeline is not None:
                    self.pipeline.post_error(self, exc)
                    return
                raise
            return
        if isinstance(event, EOSEvent):
            pad.eos = True
        if isinstance(event, CustomEvent):
            # model-update and friends can change an element's fusability
            # (e.g. a filter swapping backends mid-stream); scoped to the
            # plans this element participates in
            pl = self.pipeline
            if pl is not None and getattr(pl, "planner", None) is not None:
                pl.planner.invalidate(element=self)
        self.on_event(pad, event)

    # -- overridables --------------------------------------------------------
    def chain(self, pad: Pad, buf: TensorBuffer) -> FlowReturn:
        raise NotImplementedError

    def set_caps(self, pad: Pad, caps: Caps) -> None:
        """Default: passthrough caps to all src pads."""
        for sp in self.src_pads:
            sp.push_event(CapsEvent(caps))

    def on_event(self, pad: Pad, event: Event) -> None:
        """Default: forward events (incl. EOS) to all src pads."""
        for sp in self.src_pads:
            sp.push_event(event)

    def _upstream_event_entry(self, src_pad: Pad, event: Event) -> bool:
        try:
            return bool(self.on_upstream_event(src_pad, event))
        except Exception as exc:  # noqa: BLE001
            if self.pipeline is not None:
                self.pipeline.post_error(self, exc)
                return False
            raise

    #: May data-affecting upstream events (nns/device-reduce) pass through
    #: this element?  Only true for elements that forward buffers
    #: untouched to a SINGLE consumer (queue).  A tee/demux must refuse:
    #: fusing one branch's reduction into the producer would corrupt every
    #: other branch.
    UPSTREAM_TRANSPARENT = False

    def on_upstream_event(self, pad: Pad, event: Event) -> bool:
        """Handle an event travelling upstream (arrives on a SRC pad).
        Default: propagate further upstream through every sink pad until
        someone handles it; events that change the data contract only
        cross elements declaring UPSTREAM_TRANSPARENT."""
        if isinstance(event, CustomEvent) \
                and event.name == "nns/device-reduce" \
                and not self.UPSTREAM_TRANSPARENT:
            return False
        for sp in self.sink_pads:
            if sp.push_upstream_event(event):
                return True
        return False

    def has_pending_input(self) -> bool:
        """Is another in-band item (buffer or event) ALREADY queued for
        this element's streaming thread?  The fuse-xla double buffer
        (schedule.py) holds a finished frame for compute/D2H overlap
        only while this answers True — when the already-queued item is
        processed it either pushes (buffer) or flushes (event) the held
        slot, so a quiescent stream can never strand a frame: sparse
        request/response traffic gets synchronous push, saturated
        streams get the overlap.  Default False (no hold); boundary
        elements with a visible input queue (appsrc fifo, queue)
        override."""
        return False

    def plan_step(self):
        """Fused-dispatch hook (schedule.py segment compiler).

        Return a callable ``step(buf) -> TensorBuffer | None | FlowReturn``
        to let this element be flattened into a fused segment plan — the
        steady-state path then calls ``step`` in a flat loop instead of
        dispatching ``Pad.push → _chain_entry → chain`` per element.  The
        step must NOT push downstream itself; it returns the output buffer
        (``None`` = consumed, e.g. accumulating; a ``FlowReturn`` =
        terminal, e.g. ``DROPPED``).  Return ``None`` from *this method*
        to opt out of fusion (the default): the element keeps interpreted
        per-pad dispatch.  Only 1-sink/1-src elements are ever fused;
        the returned callable is re-queried on every plan (re)build, so
        an element may change its answer when its configuration changes
        (e.g. tensor_filter with batch>1 or workers>1 opts out)."""
        return None

    def lower_step(self) -> "Optional[LoweredStep]":
        """XLA-lowering hook (schedule.py ``fuse=xla`` tier).

        Return a :class:`LoweredStep` whose ``fn(params, tensors)`` is a
        pure jax-traceable twin of this element's per-buffer work, and
        the whole fused segment compiles into ONE jitted computation —
        every element boundary's serialize/dispatch cost collapses into
        a single device invoke, and intermediate tensors never touch the
        host.  Return ``None`` (the default) to keep the segment at the
        ``fuse-python`` tier; :meth:`lower_reason` then names why.

        Queried at plan-compile time (post-negotiation, like
        :meth:`plan_step`), and re-queried on every plan rebuild, so the
        answer may change with configuration, caps, or model state."""
        return None

    def lower_reason(self) -> "Optional[str]":
        """Why this element cannot join a whole-segment XLA computation:
        a reason string, or ``None`` when it is expected to lower.  Must
        be safe to call BEFORE ``start()`` (property-level assessment) —
        the static verifier reports these as ``xla-fallback`` warnings
        from ``launch.py --check`` when ``fuse=xla`` is requested."""
        return (f"{self.FACTORY or type(self).__name__} has no "
                "lower_step implementation")

    def get_allowed_caps(self, sink_pad: Pad) -> Caps:
        """Answer a downstream caps query on ``sink_pad``.  Default: the pad
        template (transform elements accept their template regardless of what
        they output).  Passthrough elements should forward downstream."""
        return sink_pad.template

    def static_src_caps(self, src_pad: Pad) -> Optional[Caps]:
        """What can this element statically claim to produce on
        ``src_pad``, before negotiation?  Used by the pipeline verifier
        (analysis/verify.py) to find caps dead-ends pre-play.  Default:
        the pad template, narrowed by a ``caps`` property when the
        element declares one (sources with explicit caps, capsfilter's
        constraint).  Return ``None`` when nothing can be known
        statically (the verifier then skips this pad)."""
        caps = None
        if "caps" in self.PROPERTIES:
            caps = self.get_property("caps")
        if caps in (None, ""):
            return src_pad.template
        if isinstance(caps, str):
            caps = Caps.from_string(caps)   # raises on a malformed value
        narrowed = caps.intersect(src_pad.template)
        if not caps.is_empty() and narrowed.is_empty():
            raise ValueError(
                f"{self.name}: caps property {caps} cannot intersect the "
                f"{src_pad.name} pad template {src_pad.template}")
        return narrowed

    def static_check(self) -> "List[tuple]":
        """Pre-play configuration check (verifier hook): return a list
        of ``(severity, message)`` tuples — ``"error"`` for settings the
        element's ``start()``/``set_caps`` would reject, ``"warning"``
        for settings the scheduler will silently override, ``"info"``
        for notable-but-fine structure.  Default: no findings."""
        return []

    def report_latency(self) -> int:
        """This element's contribution to a pipeline LATENCY query, in ns
        (reference: tensor_filter injects its rolling invoke latency when
        latency-report=1, tensor_filter.c:1313-1377).  Default: 0."""
        return 0

    def health_state(self) -> "Optional[str]":
        """Readiness hook for the /healthz endpoint (obs/httpd.py):
        return ``"degraded"`` while this element is running in a
        reduced mode (open circuit breakers, lost endpoints, fallback
        serving) or ``"draining"`` while it is refusing new work ahead
        of a shutdown, else None.  Called at scrape time only."""
        return None

    def drain(self, deadline: float = 5.0) -> None:
        """Graceful-drain hook (``Pipeline.drain``): stop accepting new
        work, finish what is in flight, within ``deadline`` seconds.
        Elements that front external clients (tensor_query_serversrc)
        override this; the default is a no-op — ordinary elements
        finish naturally when upstream stops feeding them."""

    # -- helpers -------------------------------------------------------------
    def announce_src_caps(self, caps: Caps, pad: Optional[Pad] = None) -> None:
        """Fixate-check and send a CAPS event downstream."""
        if not caps.is_fixed():
            caps = caps.fixate()
        (pad or self.src_pad).push_event(CapsEvent(caps))

    def push(self, buf: TensorBuffer, pad: Optional[Pad] = None) -> FlowReturn:
        return (pad or self.src_pad).push(buf)

    def post_eos_reached(self) -> None:
        """Sink elements call this when they observe EOS."""
        if self.pipeline is not None:
            self.pipeline._sink_eos(self)

    def __repr__(self):
        return f"<{self.__class__.__name__} {self.name!r}>"
