"""Pipeline container, sources, queues, tee: the scheduling substrate.

Supplies the GStreamer-pipeline role (reference L0, SURVEY.md §1): element
ownership, state changes, streaming threads, EOS aggregation, error posting.
Scheduling model: each :class:`Source` owns one streaming thread; dataflow is
synchronous downstream of it; :class:`Queue` introduces a thread boundary
with a bounded buffer (backpressure), exactly the role GStreamer's ``queue``
plays between decoupled segments.
"""

from __future__ import annotations

import threading
import time
import queue as _queue
from typing import Dict, List, Optional

from ..analysis.sanitizer import make_condition
from ..tensor.buffer import TensorBuffer
from .caps import Caps
from .element import (CapsEvent, Element, EOSEvent, Event,
                      FlowReturn, Pad)
from .registry import register_element


class PipelineError(RuntimeError):
    def __init__(self, element: Element, cause: BaseException):
        super().__init__(f"element {element.name}: {cause!r}")
        self.element = element
        self.cause = cause


class VerifyError(PipelineError):
    """Static verification rejected the graph at ``play()`` — before
    any thread spawned or buffer flowed (analysis/verify.py).  Subclasses
    :class:`PipelineError` so callers treating play/run failures
    uniformly keep working; ``findings`` carries the full diagnostics."""

    def __init__(self, findings):
        self.findings = list(findings)
        self.element = next((f.element for f in self.findings
                             if f.element is not None), None)
        self.cause = None
        detail = "; ".join(str(f) for f in self.findings)
        RuntimeError.__init__(
            self, f"pipeline verification failed ({len(self.findings)} "
                  f"error(s)): {detail}")


class Pipeline:
    """Owns elements, drives state, aggregates EOS/errors.

    Usage::

        p = Pipeline()
        src, conv, filt, sink = p.add(VideoTestSrc(...), TensorConverter(),
                                      TensorFilter(...), TensorSink())
        p.link(src, conv, filt, sink)
        p.run()          # play + wait EOS + stop
    """

    def __init__(self, name: str = "pipeline", fuse=None):
        self.name = name
        self.tracer = None          # set by enable_tracing()
        self.elements: List[Element] = []
        self._by_name: Dict[str, Element] = {}
        self._error: Optional[PipelineError] = None
        self._eos_sinks: set = set()
        self._cv = make_condition("pipeline.state")
        self._playing = False
        #: lowering tier of the segment compiler (schedule.py):
        #: ``interpret`` (no fusion — the dispatch-bench baseline),
        #: ``python`` (flat plan_step loops, the default), or ``xla``
        #: (whole-segment jitted computations).  ``fuse`` accepts the
        #: historical booleans, a tier name, or None = the NNS_FUSE env
        #: ("0" | "1" | "xla"); ``self.fuse`` stays the boolean view.
        from .schedule import resolve_tier

        self.fuse_tier = resolve_tier(fuse)
        self.fuse = self.fuse_tier != "interpret"
        self.planner = None         # SegmentPlanner while playing
        #: readiness lifecycle surfaced by the /healthz endpoint
        #: (obs/httpd.py): starting -> serving -> draining; "degraded"
        #: is computed per scrape from element health (health_state)
        self._lifecycle = "starting"
        self._health_token: Optional[int] = None

    # -- construction --------------------------------------------------------
    def add(self, *elements: Element):
        for el in elements:
            if el.name in self._by_name:
                raise ValueError(f"duplicate element name {el.name!r}")
            el.pipeline = self
            self.elements.append(el)
            self._by_name[el.name] = el
        return elements if len(elements) > 1 else elements[0]

    def get(self, name: str) -> Element:
        return self._by_name[name]

    def link(self, *elements: Element) -> None:
        """Link a chain src→sink, creating request pads as needed."""
        for a, b in zip(elements, elements[1:]):
            src = self._pick_src_pad(a)
            sink = self._pick_sink_pad(b)
            src.link(sink)

    def link_pads(self, a: Element, src_pad: Optional[str],
                  b: Element, sink_pad: Optional[str]) -> None:
        """Link with explicitly named pads (gst-launch ``mux.sink_1``
        syntax); ``None`` falls back to first-free/request.  Named pads
        resolve FIRST so a bad name fails before any free pad is
        requested."""
        src = sink = None
        if src_pad:
            src = self._named_pad(a, src_pad, a.src_pads,
                                  a.request_src_pad)
        if sink_pad:
            sink = self._named_pad(b, sink_pad, b.sink_pads,
                                   b.request_sink_pad)
        if src is None:
            src = self._pick_src_pad(a)
        if sink is None:
            sink = self._pick_sink_pad(b)
        src.link(sink)

    @staticmethod
    def _named_pad(el: Element, name: str, pads, request) -> Pad:
        import re

        for p in pads:
            if p.name == name:
                if p.peer is not None:
                    raise ValueError(f"{el.name}.{name} is already linked")
                return p
        # request pads are created on demand in sequence (sink_0, sink_1,
        # …): only request up to the asked-for index, and only when the
        # name fits the scheme — a typo must not spray orphan pads
        m = re.fullmatch(r"(?:sink|src)_(\d+)", name)
        if m is None:
            raise ValueError(f"{el.name}: no pad named {name!r}")
        want = int(m.group(1))
        try:
            while len(pads) <= want:
                p = request()
                if p.name == name:
                    return p
        except NotImplementedError:
            pass  # static-pad element: fall through to the ValueError
        raise ValueError(f"{el.name}: no pad named {name!r}")

    @staticmethod
    def _pick_src_pad(el: Element) -> Pad:
        for p in el.src_pads:
            if p.peer is None:
                return p
        return el.request_src_pad()

    @staticmethod
    def _pick_sink_pad(el: Element) -> Pad:
        for p in el.sink_pads:
            if p.peer is None:
                return p
        return el.request_sink_pad()

    # -- state ---------------------------------------------------------------
    @property
    def sinks(self) -> List[Element]:
        return [e for e in self.elements if not e.src_pads]

    def verify(self):
        """Run the static pipeline verifier (analysis/verify.py) on the
        current graph and return its findings — the programmatic face of
        ``launch.py --check``."""
        from ..analysis.verify import verify_pipeline

        return verify_pipeline(self)

    def play(self) -> None:
        # static verification first: caps dead-ends, dataflow cycles and
        # scheduler misconfigs fail HERE, with element-path diagnostics,
        # instead of crashing a streaming thread on the first buffer
        # (NNS_VERIFY=0 opts out; _check_links stays as the backstop)
        from ..analysis.verify import preflight

        preflight(self)
        self._check_links()
        # live metrics endpoint (obs/httpd.py): a no-op unless
        # NNS_METRICS_PORT is set; checked once per process
        from ..obs.httpd import maybe_start_from_env

        maybe_start_from_env()
        for el in self.elements:
            try:
                el.start()
            except Exception as exc:  # noqa: BLE001
                raise PipelineError(el, exc) from exc
            el._started = True
        self._playing = True
        if self.fuse:
            from .schedule import SegmentPlanner

            self.planner = SegmentPlanner(self)
            self.planner.install()
        #: running-time origin: sinks with sync=true render buffer PTS
        #: against this (GStreamer base-time role)
        self.base_time_ns = time.monotonic_ns()
        self._lifecycle = "serving"
        from ..obs.httpd import register_health_source

        self._health_token = register_health_source(
            self.health_state, label=f"pipeline:{self.name}")
        for el in self.elements:
            if isinstance(el, Source):
                try:
                    el._spawn()
                except Exception as exc:  # noqa: BLE001
                    # SYNC_NEGOTIATE sources negotiate HERE: a caps
                    # failure surfaces as the same PipelineError start()
                    # failures do, not as a raw ValueError
                    raise PipelineError(el, exc) from exc

    def health_state(self) -> str:
        """Readiness state for /healthz (obs/httpd.py): the lifecycle
        phase, demoted to ``degraded`` while any element reports it —
        e.g. a ``tensor_query_client`` whose endpoint breakers are OPEN
        or whose degraded start never reached a server.  Evaluated at
        scrape time only; costs nothing per buffer."""
        if self._lifecycle == "serving":
            if self._error is not None:
                return "degraded"
            worst = "serving"
            for el in self.elements:
                state = el.health_state()
                if state == "draining":
                    # a serving element already refusing new work
                    # (QueryServer.drain in progress) makes the whole
                    # pipeline draining: load balancers must route away
                    return "draining"
                if state == "degraded":
                    worst = "degraded"
            return worst
        return self._lifecycle

    def _check_links(self) -> None:
        for el in self.elements:
            for p in el.sink_pads + el.src_pads:
                if p.peer is None:
                    raise RuntimeError(
                        f"unlinked pad {p.full_name} (request pads are "
                        "created sequentially: naming sink_N also creates "
                        "sink_0..sink_N-1, which must all be linked)")

    def enable_tracing(self, spans: bool = False):
        """Attach a dataflow tracer (proctime/framerate per element — the
        GstShark tracer role, tools/tracing/README.md).  Returns the
        :class:`~nnstreamer_tpu.pipeline.tracing.Tracer`; call
        ``tracer.report()`` after the run.  ``spans=True`` additionally
        records per-buffer timeline spans for Chrome-trace export
        (``tracer.export_chrome``)."""
        from .tracing import Tracer

        self.tracer = Tracer(spans=spans)
        if self.planner is not None:
            # compiled executors bind the tracer at compile time: swap
            # the wrappers in place, keeping the cached step lists and
            # warm fuse-xla executables (a profiler attaching to a warm
            # pipeline must not trigger a cold device-compile)
            self.planner.retrace()
        return self.tracer

    def query_latency(self) -> "tuple[int, Dict[str, int]]":
        """Pipeline LATENCY query (reference: GStreamer latency query with
        tensor_filter injecting its invoke latency, tensor_filter.c:
        1313-1377): returns (total_ns, {element_name: ns}) summing every
        element's reported contribution."""
        per = {el.name: el.report_latency() for el in self.elements}
        per = {k: v for k, v in per.items() if v > 0}
        return sum(per.values()), per

    def post_error(self, element: Element, exc: BaseException) -> None:
        with self._cv:
            if self._error is None:
                self._error = PipelineError(element, exc)
            self._cv.notify_all()

    def _sink_eos(self, element: Element) -> None:
        with self._cv:
            self._eos_sinks.add(element.name)
            self._cv.notify_all()

    def wait(self, timeout: Optional[float] = None) -> None:
        """Wait until every sink reached EOS (or an error was posted)."""
        sink_names = {e.name for e in self.sinks}
        with self._cv:
            ok = self._cv.wait_for(
                lambda: self._error is not None
                or sink_names <= self._eos_sinks, timeout)
        if self._error is not None:
            # raise a FRESH chained copy: re-raising the stored object on a
            # second wait() would keep appending traceback frames to it
            err = PipelineError(self._error.element, self._error.cause)
            raise err from self._error
        if not ok:
            raise TimeoutError(f"pipeline {self.name}: EOS not reached")

    def drain(self, deadline: float = 5.0) -> None:
        """Graceful drain, then stop: flip /healthz to ``draining``
        (503 — load balancers route away), let elements that front
        external clients refuse new work with explicit retry-after
        answers and finish their in-flight replies (``Element.drain``,
        e.g. ``tensor_query_serversrc`` → ``QueryServer.drain``), then
        tear the pipeline down.  The ``launch.py`` SIGTERM handler
        calls this — kill -TERM a serving pipeline and clients see
        sheds, not mid-reply resets."""
        self._lifecycle = "draining"
        for el in self.elements:
            if el._started:
                try:
                    el.drain(deadline)
                except Exception as exc:   # noqa: BLE001 — drain is
                    # best-effort: one element's failure must not block
                    # the teardown of the rest
                    from ..utils.log import logger

                    logger.warning("%s: drain failed: %r", el.name, exc)
        self.stop()

    def stop(self) -> None:
        self._playing = False
        self._lifecycle = "draining"
        if self._health_token is not None:
            # unregister FIRST: a /healthz scrape racing element
            # teardown must not walk half-stopped elements
            from ..obs.httpd import unregister_health_source

            unregister_health_source(self._health_token)
            self._health_token = None
        # phase 0: release blocking waits (a sync sink's PTS wait holds
        # the very streaming thread _halt() is about to join)
        for el in self.elements:
            if el._started:
                el.unblock()
        for el in self.elements:
            if isinstance(el, Source):
                el._halt()
        stopped_any = False
        for el in self.elements:
            if el._started:
                el.stop()
                el._started = False
                stopped_any = True
        if self.planner is not None:
            self.planner.uninstall()
            self.planner = None
        if stopped_any:
            # the element/pad graph is cyclic, so DROPPED pipelines from
            # earlier runs (and the buffers their sinks retained) linger
            # until the cycle collector fires — measured ~10x throughput
            # collapse on a live stream while gc ground through GBs of
            # dead buffers.  Collecting at each stop boundary clears
            # prior runs' garbage at a moment a pause is cheapest.  (The
            # pipeline being stopped is still referenced by the caller —
            # sink.results stays readable — so ITS payload frees at the
            # caller's drop + a later collect.)
            import gc

            gc.collect()

    def run(self, timeout: Optional[float] = None) -> None:
        try:
            self.play()
            self.wait(timeout)
        finally:
            self.stop()


class Source(Element):
    """Base push source: owns a streaming thread, emits caps then buffers
    then EOS.  Subclasses implement :meth:`negotiate` (return fixed src
    caps) and :meth:`create` (return next buffer or None for EOS) —
    mirroring GstPushSrc's create vfunc (reference datareposrc/srciio use
    this model)."""

    #: sources whose negotiate() is pure (no I/O, no blocking) announce
    #: caps from play()'s thread in _spawn, BEFORE the streaming thread
    #: exists.  An app that calls element.push() right after play()
    #: otherwise races the loop thread's announcement and can reach a
    #: downstream chain() before set_caps() negotiated (seen as a flaky
    #: AttributeError on tensor_filter._in_config under suite load).
    #: Network-backed sources keep the in-thread announce: their
    #: negotiate() may block on a peer and must not stall play().
    SYNC_NEGOTIATE = False

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._thread: Optional[threading.Thread] = None
        self._halted = threading.Event()
        self._caps_announced = False

    def negotiate(self) -> Caps:
        raise NotImplementedError

    def create(self) -> Optional[TensorBuffer]:
        raise NotImplementedError

    def _spawn(self) -> None:
        self._halted.clear()
        self._caps_announced = False
        if self.SYNC_NEGOTIATE:
            self.announce_src_caps(self.negotiate())
            self._caps_announced = True
        self._thread = threading.Thread(target=self._loop,
                                        name=f"src:{self.name}", daemon=True)
        self._thread.start()

    def _halt(self) -> None:
        self._halted.set()
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join(timeout=10)

    def _loop(self) -> None:
        try:
            if not self._caps_announced:
                caps = self.negotiate()
                self.announce_src_caps(caps)
                self._caps_announced = True
            seq = 0
            while not self._halted.is_set():
                buf = self.create()
                if buf is None:
                    break
                pl = self.pipeline
                if pl is not None and pl.tracer is not None:
                    # source stamp: seq + birth time (the interlatency
                    # origin, GstShark interlatency role) + the trace
                    # context every transport sink forwards on the wire
                    # (obs/span.py).  Only when a tracer is attached.
                    tr = pl.tracer
                    extra = buf.extra
                    # seq/birth are overwritten per push: an app reusing
                    # ONE buffer object for many frames (hotpath bench
                    # style) must not measure frame k's interlatency
                    # against frame 0's birth.  The trace context stays
                    # first-writer-wins: a wire-restored context (query
                    # server, edge/shm/mqtt src) must survive.
                    extra["nns_seq"] = seq
                    src_ns = extra["nns_src_ns"] = time.monotonic_ns()
                    if "nns_trace" not in extra:
                        from ..obs.span import TraceContext

                        extra["nns_trace"] = TraceContext(
                            tr.trace_id, 0,
                            tr.anchor_wall_us
                            + (src_ns - tr.anchor_mono_ns) // 1000)
                    if tr.ring is not None:
                        # zero-duration birth marker: the frame
                        # window's left edge for wait-state attribution
                        # (obs/attrib.py) — the gap from here to the
                        # first element span is source-pacing
                        from ..obs.span import Span

                        ctx = extra["nns_trace"]
                        tid = ctx.trace_id or tr.trace_id
                        tr.ring.append(Span(
                            "src:" + self.name,
                            threading.get_ident(), src_ns, 0, seq,
                            tid))
                        adm = extra.pop("nns_admission_ns", None)
                        if adm is not None:
                            # a serving source (serversrc) deferred its
                            # admission-wait span to HERE — the one
                            # place seq is assigned, so the span can
                            # never mis-attach to a neighboring frame
                            tr.annotate_span("admission-wait",
                                             adm[0], adm[1], seq=seq,
                                             trace_id=tid)
                        xb_spans = extra.pop("nns_xb_spans", None)
                        if xb_spans is not None:
                            # a cross-stream bucket carries PER-FRAME
                            # residency spans (admission-wait +
                            # queue-wait, query/server.py): emitted
                            # under the batch buffer's seq, each with
                            # its own client's trace id so the T_TRACE
                            # piggyback routes it to the right merged
                            # timeline
                            for state, s0, s1, stid in xb_spans:
                                tr.annotate_span(state, s0, s1, seq=seq,
                                                 trace_id=stid or tid)
                seq += 1
                ret = self.push(buf)
                if ret in (FlowReturn.ERROR, FlowReturn.EOS):
                    break
            self.src_pad.push_event(EOSEvent())
        except Exception as exc:  # noqa: BLE001
            if self.pipeline is not None:
                self.pipeline.post_error(self, exc)
            else:
                raise


@register_element
class Queue(Element):
    """Thread-boundary element with a bounded buffer.

    The GStreamer ``queue`` role: decouples upstream/downstream into separate
    streaming threads with backpressure.  Events travel through the queue
    in-band to preserve ordering.
    """

    FACTORY = "queue"
    PROPERTIES = {"max-size-buffers": (16, "queue capacity")}
    UPSTREAM_TRANSPARENT = True    # buffers pass untouched, one consumer

    def _make_pads(self):
        self.add_sink_pad(Caps.any(), "sink")
        self.add_src_pad(Caps.any(), "src")

    def start(self):
        # capacity bounds DATA buffers only (the _used counter); the queue
        # itself is unbounded so control markers (caps/events/EOS) can
        # always be enqueued — a caps announcement arriving from the
        # drain thread of a downstream queue must never block on data
        # capacity (that is a self-deadlock: the would-be consumer is
        # the blocked thread).  DATA admission blocks on the _space
        # condition below, so depth is bounded by construction.
        # nnslint: allow(unbounded-queue)
        self._q: _queue.Queue = _queue.Queue()
        self._cap = max(1, int(self.max_size_buffers))
        self._used = 0
        self._space = make_condition("queue.space")
        self._drain_done = False
        self._worker = threading.Thread(target=self._drain,
                                        name=f"queue:{self.name}", daemon=True)
        self._stop = threading.Event()
        # scrape-time depth gauges (obs/metrics.py lazy-callable
        # contract: nothing on the buffer path, evaluated only when
        # /metrics pulls).  Labeled with the owning pipeline and
        # unregistered by IDENTITY at stop, so concurrent pipelines
        # with same-named queues neither collide nor tear down each
        # other's live gauges.
        from ..obs.metrics import REGISTRY, Gauge

        labels = {"queue": self.name,
                  "pipeline": getattr(self.pipeline, "name", "") or ""}
        self._obs_gauges = [
            REGISTRY.register(Gauge("nns_queue_depth", labels,
                                    fn=lambda: self._used)),
            REGISTRY.register(Gauge("nns_queue_capacity", labels,
                                    fn=lambda: self._cap)),
        ]
        self._worker.start()

    def unblock(self):
        with self._space:
            self._space.notify_all()

    def stop(self):
        from ..obs.metrics import REGISTRY

        for gauge in getattr(self, "_obs_gauges", ()):
            REGISTRY.unregister(gauge)
        self._obs_gauges = []
        self._stop.set()
        with self._space:
            self._space.notify_all()
        # drain so the sentinel always fits even if the worker died with a
        # full queue (upstream error case)
        while True:
            try:
                self._q.get_nowait()
            except _queue.Empty:
                break
        self._q.put(None)
        self._worker.join(timeout=10)

    def get_allowed_caps(self, sink_pad):
        return self.src_pad.peer_allowed_caps()

    def has_pending_input(self) -> bool:
        # fuse-xla double-buffer gate (see Element.has_pending_input):
        # the drain thread heads the downstream segment, and _q holds
        # what it will process next
        return not self._q.empty()

    def static_check(self):
        try:
            cap = int(self.max_size_buffers)
        except (TypeError, ValueError):
            return [("error", f"{self.name}: max-size-buffers="
                              f"{self.max_size_buffers!r} is not an int")]
        if cap < 1:
            return [("warning", f"{self.name}: max-size-buffers={cap} "
                                "is clamped to 1 at start")]
        return []

    def _enqueue(self, buf) -> FlowReturn:
        """Slot-bounded data put that can't deadlock: purely event-driven
        (no poll) — woken by the drain worker freeing a slot, by stop(),
        or by the worker exiting (EOS drained / downstream error)."""
        with self._space:
            while True:
                if self._stop.is_set():
                    return FlowReturn.EOS
                if self._used < self._cap:
                    break
                if self._drain_done:
                    return FlowReturn.ERROR
                self._space.wait()
            self._used += 1
        self._q.put(("buf", buf))
        return FlowReturn.OK

    def _enqueue_event(self, event) -> None:
        if not self._stop.is_set():
            self._q.put(("event", event))   # unbounded: never blocks

    def chain(self, pad, buf):
        return self._enqueue(buf)

    def set_caps(self, pad, caps):
        self._enqueue_event(CapsEvent(caps))

    def on_event(self, pad, event):
        self._enqueue_event(event)

    def _release_slot(self):
        with self._space:
            self._used -= 1
            self._space.notify()

    def _drain(self):
        try:
            while not self._stop.is_set():
                item = self._q.get()
                if item is None:
                    return
                kind, payload = item
                try:
                    if kind == "buf":
                        try:
                            self.src_pad.push(payload)
                        finally:
                            self._release_slot()
                    else:
                        self.src_pad.push_event(payload)
                        if isinstance(payload, EOSEvent):
                            return
                except Exception as exc:  # noqa: BLE001
                    if self.pipeline is not None:
                        self.pipeline.post_error(self, exc)
                    return
        finally:
            # wake any producer blocked on a full queue: _drain_done is the
            # worker-exited signal _enqueue checks (its is-the-thread-alive
            # poll is gone), set under the lock so a waiter can't re-check
            # and sleep between the flag write and the notify
            with self._space:
                self._drain_done = True
                self._space.notify_all()


@register_element
class Tee(Element):
    """1→N branch duplicator (GStreamer ``tee`` role).  Tensor PAYLOADS are
    shared, never copied — each branch gets a fresh :class:`TensorBuffer`
    wrapper (so per-buffer ``extra``/meta mutations stay branch-local, the
    GstBuffer-writability analogue) holding the same array handles, so no
    tensor bytes are duplicated and device arrays stay on device.
    Downstream must not mutate tensor data in place (same contract as
    GstBuffer refcount sharing)."""

    FACTORY = "tee"

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._done: set = set()     # branch pads that returned EOS

    def _make_pads(self):
        self.add_sink_pad(Caps.any(), "sink")

    def request_src_pad(self) -> Pad:
        return self.add_src_pad(Caps.any())

    def start(self):
        self._done = set()

    def get_allowed_caps(self, sink_pad):
        allowed = Caps.any()
        for sp in self.src_pads:
            allowed = allowed.intersect(sp.peer_allowed_caps())
        return allowed

    def chain(self, pad, buf):
        # a branch that answered EOS is done for good: drop it from the
        # fan-out instead of re-offering every frame; the LAST live branch
        # gets the original wrapper (no copy) — only the other branches
        # need a fresh wrapper for branch-local meta mutations
        done = self._done
        live = [sp for sp in self.src_pads if sp not in done]
        if not live:
            return FlowReturn.EOS
        last = len(live) - 1
        for i, sp in enumerate(live):
            ret = sp.push(buf if i == last else buf.copy())
            if ret is FlowReturn.ERROR:
                return ret
            if ret is FlowReturn.EOS:
                done.add(sp)
        return FlowReturn.EOS if len(done) >= len(self.src_pads) \
            else FlowReturn.OK


@register_element
class AppSrc(Source):
    """Programmatic source: caller supplies caps and feeds buffers
    (GStreamer appsrc role; used heavily by tests the way the reference's
    gtest pipelines use appsrc, tests/nnstreamer_plugins/unittest_plugins.cc).
    """

    FACTORY = "appsrc"
    PROPERTIES = {"caps": (None, "fixed caps to announce")}
    #: caps come from a property — negotiation is pure, so it runs in
    #: play() before the app can push() (Source.SYNC_NEGOTIATE contract)
    SYNC_NEGOTIATE = True

    #: in-band wake marker: create() blocks on the fifo with NO timeout
    #: (event-driven, zero idle wakeups); unblock()/_halt() enqueue this
    #: so teardown can interrupt the blocking get
    _WAKE = object()

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        # app-side producer owns the pacing: the prefill-before-play
        # contract (benches queue thousands of frames before the first
        # consumer exists) rules out a blocking bound here
        # nnslint: allow(unbounded-queue)
        self._fifo: _queue.Queue = _queue.Queue()

    def _make_pads(self):
        self.add_src_pad(Caps.any(), "src")

    def push_buffer(self, buf: TensorBuffer) -> None:
        self._fifo.put(buf)

    def has_pending_input(self) -> bool:
        # fuse-xla double-buffer gate: hold a finished frame only while
        # the fifo already carries the next item (buffer OR event — an
        # event flushes the held slot when it drains)
        return not self._fifo.empty()

    def push_event(self, event: Event) -> None:
        """Queue a downstream event IN-BAND: it is delivered from the
        streaming thread in arrival order with the buffers (how GStreamer
        apps send e.g. tensor_filter_update_model through appsrc — the
        serialization guarantees no frame races the event)."""
        self._fifo.put(event)

    def end_of_stream(self) -> None:
        self._fifo.put(None)

    def negotiate(self) -> Caps:
        caps = self.caps
        if isinstance(caps, str):
            caps = Caps.from_string(caps)
        if caps is None:
            raise ValueError("appsrc requires caps property")
        return caps

    def unblock(self):
        self._fifo.put(self._WAKE)

    def _halt(self) -> None:
        # order matters: set the flag BEFORE the wake marker, so a create()
        # that consumes the marker observes halted and exits (the reverse
        # order could consume the wake, see un-halted, and block forever)
        self._halted.set()
        self._fifo.put(self._WAKE)
        super()._halt()

    def create(self) -> Optional[TensorBuffer]:
        while True:
            item = self._fifo.get()
            if item is self._WAKE:
                if self._halted.is_set():
                    return None
                continue            # pre-halt unblock(): spurious, re-wait
            if isinstance(item, Event):
                self.src_pad.push_event(item)
                continue
            return item
