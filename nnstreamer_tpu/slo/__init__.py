"""SLO harness: open-loop load generation, burn-rate gating, flight
recording.

PR 5 gave the framework eyes (spans, histograms, a metrics endpoint);
this package is what *consumes* them at fleet scale — the verification
substrate the ROADMAP's serving-plane items are judged against.
StreamTensor (arXiv:2509.13694) is the motivating posture: tail
behavior under sustained concurrent streams, not mean fps, is the
honest health metric for an always-on multi-user pipeline service
(NNStreamer, arXiv:2101.06371).

- :mod:`~nnstreamer_tpu.slo.spec` — SLO objectives (latency /
  error-rate / availability targets) + multi-window burn-rate
  parameters, as plain JSON.
- :mod:`~nnstreamer_tpu.slo.loadgen` — open-loop (coordinated-omission-
  free) Poisson / constant-rate load generator over concurrent
  ``tensor_query_client`` connections, with per-class request tagging.
- :mod:`~nnstreamer_tpu.slo.evaluator` — windowed burn-rate evaluation
  over the metrics registry's snapshot/diff API; machine-readable
  PASS/FAIL verdicts; breach-onset callbacks.
- :mod:`~nnstreamer_tpu.slo.flightrec` — always-on bounded triage ring
  dumped as a Chrome-trace + metrics bundle at the moment of breach.

``tools/soak.py`` composes all four with ``testing/faults.py`` chaos
stages into scripted soaks; ``launch.py --soak/--slo`` gates any
launch-string pipeline the same way.  ``time.sleep`` polling is banned
in this package (nnslint ``sleep-poll``, slo scope): every wait is an
``Event.wait`` against an absolute deadline, because a load generator
that drifts under load measures its own jitter, not the server's.
"""

from .evaluator import Evaluator, SLOMonitor  # noqa: F401
from .flightrec import FlightRecorder  # noqa: F401
from .loadgen import (LoadGenerator, constant_schedule,  # noqa: F401
                      poisson_schedule)
from .spec import Objective, SLOSpec, demo_spec, load_spec  # noqa: F401
