"""Flight recorder: always-on bounded triage ring, dumped at SLO breach.

A failed multi-minute soak must be triaged from an ARTIFACT, not rerun:
by the time a human looks, the tunnel window is gone and the breach is
unreproducible.  So the recorder runs for the whole soak at bounded
cost — a deque of recent per-tick metric snapshots (the evaluator's
``on_tick`` feed) riding next to the serving pipeline's bounded span
ring (``Tracer(spans=True)``, obs/span.py, overwrite-oldest) — and
converts itself into a bundle the moment the evaluator reports a breach
onset (``on_breach``):

``bundle-<n>-<objective>/``
    ``manifest.json``   — breach event, wall/mono stamps, file inventory
    ``trace.json``      — Chrome ``trace_event`` export of the span ring
                          (the breaching window's spans: the ring holds
                          the most recent spans, which at dump time ARE
                          the breach neighborhood) — open in Perfetto
    ``breach.json``     — the triggering evaluation (both windows'
                          burn-rate evidence)
    ``blame.json``      — wait-state attribution summary of the breach
                          window's spans (obs/attrib.py): which states
                          ate the breaching frames' time, without
                          opening the trace
    ``metrics_timeline.jsonl`` — one line per recorded tick: metric
                          snapshot + objective burn rates (the time
                          series leading INTO the breach)
    ``metrics_final.json`` — full registry report at dump time
    ``sessions.json``   — per-session token timelines (llm/tokenobs.py
                          records: admit → first-token → terminal,
                          TTFT/ITL, head-of-line blame partition) when
                          a ``session_obs`` provider is attached; the
                          same sessions also land in ``trace.json`` as
                          one Chrome lane per session, merged onto the
                          span ring's timebase — a breach bundle from
                          an LLM soak shows WHICH sessions sat behind
                          what, next to the server's element spans

Dumps are capped (``max_dumps``) so a flapping objective cannot fill a
disk; every breach past the cap still lands in the evaluator's verdict.
"""

from __future__ import annotations

import json
import os
from collections import deque
from typing import Any, Dict, List, Optional

from ..analysis.sanitizer import make_lock
from ..obs.clock import mono_ns, wall_us
from ..obs.metrics import REGISTRY, MetricsRegistry


class FlightRecorder:
    """Bounded snapshot ring + breach-triggered bundle writer.

    Wire it up with::

        rec = FlightRecorder(out_dir, tracer=server_tracer)
        evaluator.on_tick = rec.record
        evaluator.on_breach = rec.on_breach
    """

    def __init__(self, out_dir: str, tracer: Optional[Any] = None,
                 registry: MetricsRegistry = REGISTRY,
                 capacity: int = 512, max_dumps: int = 3,
                 collector: Optional[Any] = None,
                 session_obs: Optional[Any] = None) -> None:
        self.out_dir = out_dir
        self.tracer = tracer
        self.registry = registry
        #: federation collector (obs/federation.py): when attached,
        #: every recorded tick carries the per-origin federated view,
        #: so a breach bundle from an N-process run shows ALL sides'
        #: timelines, not just the process that happened to breach
        self.collector = collector
        #: token-observability provider (llm/tokenobs.TokenObs): when
        #: attached, bundles grow ``sessions.json`` (the breach
        #: window's per-session timelines + blame) and the sessions'
        #: Chrome lanes merge into ``trace.json`` — both sides share
        #: the mono-ns timebase, so session bars line up under the
        #: server spans that caused them
        self.session_obs = session_obs
        self.max_dumps = int(max_dumps)
        self._lock = make_lock("slo")
        self._ring: "deque[Dict[str, Any]]" = deque(
            maxlen=max(8, int(capacity)))
        self.dumps: List[str] = []

    # -- feed ----------------------------------------------------------------
    def record(self, evaluation: Optional[Dict[str, Any]] = None) -> None:
        """Append one tick to the ring: wall/mono stamps, the registry
        report (cheap: values + histogram summaries, not full bucket
        vectors), and the evaluation's per-objective burn rates."""
        entry: Dict[str, Any] = {"wall_us": wall_us(),
                                 "mono_s": round(mono_ns() / 1e9, 3),
                                 "metrics": self.registry.report()}
        if self.collector is not None:
            # the federated timeline: per-origin flattened metrics
            # (remote workers' pushed state + the local registry under
            # its own origin key), plus origin liveness rows
            entry["origins"] = self.collector.report()
            entry["origin_status"] = self.collector.origins()
        if evaluation is not None:
            entry["burn"] = {
                o["name"]: {"fast": o["fast"]["burn_rate"],
                            "slow": o["slow"]["burn_rate"],
                            "breached": o["breached"]}
                for o in evaluation.get("objectives", ())}
        with self._lock:
            self._ring.append(entry)

    # -- dump ----------------------------------------------------------------
    def on_breach(self, event: Dict[str, Any],
                  evaluation: Dict[str, Any]) -> Optional[str]:
        """Evaluator breach-onset hook: write one bundle (up to
        ``max_dumps``); returns the bundle dir, or None past the cap."""
        with self._lock:
            if len(self.dumps) >= self.max_dumps:
                return None
            n = len(self.dumps)
        path = self.dump(f"{n}-{event.get('objective', 'breach')}",
                         breach={"event": event,
                                 "evaluation": evaluation})
        return path

    def dump(self, tag: str,
             breach: Optional[Dict[str, Any]] = None) -> str:
        """Write a bundle now (breach hook or operator-forced); returns
        the bundle directory path."""
        bundle = os.path.join(self.out_dir, f"bundle-{tag}")
        os.makedirs(bundle, exist_ok=True)
        with self._lock:
            timeline = list(self._ring)
        files = {}

        def _write(name: str, obj: Any) -> None:
            p = os.path.join(bundle, name)
            with open(p, "w", encoding="utf-8") as fh:
                if name.endswith(".jsonl"):
                    for row in obj:
                        fh.write(json.dumps(row) + "\n")
                else:
                    json.dump(obj, fh, indent=2)
            files[name] = os.path.getsize(p)

        if breach is not None:
            _write("breach.json", breach)
        session_events: List[Dict[str, Any]] = []
        if self.session_obs is not None:
            # breach-window session timelines: the tokenobs ring holds
            # the most recently CLOSED sessions plus every live one —
            # at dump time that IS the breach neighborhood
            _write("sessions.json",
                   {"sessions": self.session_obs.records(),
                    "blame": self.session_obs.blame_report()})
            session_events = self.session_obs.chrome_events()
        if self.tracer is not None and \
                getattr(self.tracer, "ring", None) is not None:
            trace = self.tracer.chrome_trace()
            if session_events:
                # merge the session lanes onto the span ring's export:
                # both stamp mono-ns, so the bars line up under the
                # server spans that caused them (re-sort keeps the
                # merged stream globally time-monotonic, M events first)
                events = trace["traceEvents"] + session_events
                events.sort(key=lambda e: (e["ph"] != "M",
                                           e.get("ts", 0.0)))
                trace["traceEvents"] = events
            _write("trace.json", trace)
            from ..obs.profile import attribution_block

            blame = attribution_block(self.tracer)
            if blame:
                # breach-window wait-state blame (obs/attrib.py): the
                # ring holds the breach neighborhood, so this names the
                # states that ate the breaching frames' time without
                # opening the Chrome trace
                _write("blame.json", blame)
        elif session_events:
            # no span tracer attached: the session lanes alone are
            # still a valid Chrome export
            _write("trace.json", {"traceEvents": session_events,
                                  "displayTimeUnit": "ms"})
        _write("metrics_timeline.jsonl", timeline)
        _write("metrics_final.json", self.registry.report())
        manifest = {"tag": tag, "wall_us": wall_us(),
                    "mono_s": round(mono_ns() / 1e9, 3),
                    "recorded_ticks": len(timeline),
                    "files": files}
        if self.collector is not None:
            manifest["origins"] = self.collector.origins()
        if self.tracer is not None and \
                getattr(self.tracer, "ring", None) is not None:
            manifest["span_ring"] = {
                "capacity": self.tracer.ring.capacity,
                "dropped": self.tracer.ring.dropped}
        _write("manifest.json", manifest)
        with self._lock:
            self.dumps.append(bundle)
        return bundle
