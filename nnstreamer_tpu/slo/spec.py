"""SLO specification: objectives + multi-window burn-rate parameters.

A spec declares what "healthy" means for a serving pipeline under
sustained load, in the error-budget vocabulary of SRE practice:

- every objective has a **target** success fraction (e.g. 0.99);
  the complement ``1 - target`` is the **error budget**;
- an evaluation window's **burn rate** is the fraction of requests that
  were bad in that window divided by the budget — burn 1.0 means the
  budget is being consumed exactly as fast as the objective allows,
  burn 10 means the budget would be gone in a tenth of the period;
- a **breach** requires the burn rate to exceed the threshold in BOTH
  the fast and the slow window (the classic multi-window alert: the
  fast window gives detection latency, the slow window suppresses
  blips that self-heal — a single recovered disconnect must not page).

Objective kinds (``slo/evaluator.py`` computes each from the PR 5
metrics registry via the snapshot/diff API, no bespoke plumbing):

``latency``
    Requests slower than ``threshold_us`` are bad.  Counted from the
    bucket vector of the latency histogram, so the windowed p99 rides
    along as evidence.
``error_rate``
    Failed requests (transport errors, timeouts, dead endpoints) are
    bad.
``availability``
    Same accounting as ``error_rate`` but conventionally a looser
    target — "did the service answer at all" vs "did it answer
    correctly/fast"; kept a distinct kind so verdicts name the right
    contract.
``ttft`` / ``itl``
    Token-latency kinds for the LLM serving tier: first tokens slower
    than ``threshold_us`` since their *scheduled* arrival (``ttft``),
    or inter-token gaps longer than ``threshold_us`` (``itl``), are
    bad.  Same histogram accounting as ``latency`` over the
    ``nns_slo_ttft_us`` / ``nns_slo_itl_us`` families the token
    loadgen writes — or, via ``metric``, the server-side
    ``nns_llm_ttft_us`` / ``nns_llm_itl_us`` the ``tensor_llm``
    element observes; kept distinct kinds so verdicts name the token
    contract they gate.

Specs serialize as plain JSON (``to_dict``/``from_dict``,
``load``/``dump``) — the ``tools/soak.py --slo spec.json`` format and
the machine half of every verdict artifact.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Optional, Tuple

KINDS = ("latency", "error_rate", "availability", "ttft", "itl")
#: kinds whose accounting is histogram-threshold (bucket vector math)
HIST_KINDS = ("latency", "ttft", "itl")

#: metric families the evaluator reads; the loadgen writes them and any
#: other client may too (one shared contract, obs/metrics.py registry)
REQUESTS_TOTAL = "nns_slo_requests_total"
ERRORS_TOTAL = "nns_slo_errors_total"
LATENCY_US = "nns_slo_latency_us"
#: token-latency families (schedule-anchored, client-side — the
#: coordinated-omission-free halves of the TTFT/ITL contract)
TTFT_US = "nns_slo_ttft_us"
ITL_US = "nns_slo_itl_us"
#: default histogram family per histogram-threshold kind
HIST_FAMILY = {"latency": LATENCY_US, "ttft": TTFT_US, "itl": ITL_US}


@dataclasses.dataclass(frozen=True)
class Objective:
    """One service-level objective.

    ``request_class`` restricts accounting to requests tagged with that
    class (``buf.extra["nns_class"]``, query/client.py); empty matches
    every class (sums across labels).

    ``metric`` (histogram kinds: latency/ttft/itl) overrides the
    histogram family the objective reads — e.g.
    ``nns_element_proctime_us`` gates a pipeline's own per-element
    latency instead of the loadgen's request latency, and
    ``nns_llm_ttft_us`` gates the server-observed first-token latency;
    ``match`` further restricts to metric keys containing the
    substring (e.g. ``element="filter"``).
    """

    name: str
    kind: str                      # one of KINDS
    target: float                  # success fraction in (0, 1)
    threshold_us: float = 0.0      # histogram kinds: slower-than = bad
    request_class: str = ""
    metric: str = ""               # latency kind: histogram family
    match: str = ""                # raw key-substring label filter

    def __post_init__(self) -> None:
        if self.kind not in KINDS:
            raise ValueError(f"objective {self.name!r}: kind "
                             f"{self.kind!r} (want one of {KINDS})")
        if not 0.0 < self.target < 1.0:
            raise ValueError(f"objective {self.name!r}: target "
                             f"{self.target} must be in (0, 1)")
        if self.kind in HIST_KINDS and self.threshold_us <= 0:
            raise ValueError(f"objective {self.name!r}: {self.kind} "
                             "kind requires threshold_us > 0")

    @property
    def budget(self) -> float:
        """Error budget: the bad-request fraction the target allows."""
        return 1.0 - self.target

    def to_dict(self) -> Dict[str, Any]:
        out = {"name": self.name, "kind": self.kind,
               "target": self.target}
        for field in ("threshold_us", "request_class", "metric",
                      "match"):
            value = getattr(self, field)
            if value:
                out[field] = value
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Objective":
        return cls(name=str(d["name"]), kind=str(d["kind"]),
                   target=float(d["target"]),
                   threshold_us=float(d.get("threshold_us", 0.0)),
                   request_class=str(d.get("request_class", "")),
                   metric=str(d.get("metric", "")),
                   match=str(d.get("match", "")))


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """Objectives + the shared multi-window burn-rate parameters."""

    name: str
    objectives: Tuple[Objective, ...]
    window_fast_s: float = 60.0
    window_slow_s: float = 600.0
    #: burn rate BOTH windows must exceed to breach.  2.0 = "the budget
    #: is burning at twice the sustainable rate" — a deliberate default
    #: between instant paging (1.0 would alert on exactly-at-budget)
    #: and the classic 14.4 paging threshold sized for 30-day budgets.
    burn_threshold: float = 2.0
    tick_s: float = 1.0

    def __post_init__(self) -> None:
        if not self.objectives:
            raise ValueError(f"spec {self.name!r}: no objectives")
        if not 0 < self.window_fast_s < self.window_slow_s:
            raise ValueError(
                f"spec {self.name!r}: want 0 < window_fast_s "
                f"({self.window_fast_s}) < window_slow_s "
                f"({self.window_slow_s})")
        if self.burn_threshold <= 0:
            raise ValueError(f"spec {self.name!r}: burn_threshold must "
                             "be > 0")
        if self.tick_s <= 0 or self.tick_s > self.window_fast_s:
            raise ValueError(f"spec {self.name!r}: tick_s must be in "
                             f"(0, window_fast_s]")

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name,
                "window_fast_s": self.window_fast_s,
                "window_slow_s": self.window_slow_s,
                "burn_threshold": self.burn_threshold,
                "tick_s": self.tick_s,
                "objectives": [o.to_dict() for o in self.objectives]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SLOSpec":
        return cls(name=str(d.get("name", "slo")),
                   objectives=tuple(Objective.from_dict(o)
                                    for o in d.get("objectives", ())),
                   window_fast_s=float(d.get("window_fast_s", 60.0)),
                   window_slow_s=float(d.get("window_slow_s", 600.0)),
                   burn_threshold=float(d.get("burn_threshold", 2.0)),
                   tick_s=float(d.get("tick_s", 1.0)))

    @classmethod
    def load(cls, path: str) -> "SLOSpec":
        with open(path, "r", encoding="utf-8") as fh:
            return cls.from_dict(json.load(fh))

    def dump(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(self.to_dict(), fh, indent=2)


def demo_spec(duration_s: float = 60.0,
              p99_threshold_us: float = 250_000.0) -> SLOSpec:
    """The soak-demo spec: windows scaled to the soak's duration (a
    60 s demo cannot carry a literal 10-minute slow window — fast/slow
    keep their 1:10 ratio at ``duration/6`` / ``duration*10/6``, i.e.
    10 s / 100 s for the 60 s demo), targets sized so a single
    recovered fault passes and a dead server fails."""
    fast = max(2.0, duration_s / 6.0)
    return SLOSpec(
        name="soak-demo",
        window_fast_s=fast,
        window_slow_s=fast * 10.0,
        burn_threshold=2.0,
        tick_s=max(0.25, fast / 10.0),
        objectives=(
            Objective("availability", "availability", target=0.95),
            Objective("error_rate", "error_rate", target=0.90),
            Objective("p99_latency", "latency", target=0.90,
                      threshold_us=p99_threshold_us),
        ))


def load_spec(path: Optional[str], duration_s: float = 60.0) -> SLOSpec:
    """``--slo`` resolution: a path loads that spec, None the demo."""
    return SLOSpec.load(path) if path else demo_spec(duration_s)
