"""Open-loop load generator: coordinated-omission-free latency under
tens-to-hundreds of concurrent query clients.

**Open loop** is the load-model decision that makes the numbers honest.
A closed-loop generator (send, wait for the reply, send again) slows
down exactly when the system under test slows down — every stall
*removes* the requests that would have measured it, the classic
coordinated-omission blind spot.  Here every worker precomputes an
**arrival schedule** (absolute send offsets, Poisson or constant-rate,
seeded) before the run starts, and latency is measured from the
*scheduled* arrival time, not the actual send: when the server stalls
and a worker falls behind its schedule, the queued requests go out
back-to-back and their recorded latency includes the time they spent
waiting to be sent — which is exactly the latency a real independent
client arriving at that moment would have seen.

Each worker owns one :class:`~nnstreamer_tpu.query.client.
QueryConnection` (its own TCP stream + reader thread — N workers model
N independent clients, and a chaos ``kill_connections`` severs N real
sockets).  Requests carry a **class tag** (``buf.extra["nns_class"]``,
weighted-random per request, seeded) and all accounting lands in the
PR 5 metrics registry under class-labeled families — the shared
contract ``slo/evaluator.py`` reads:

- ``nns_slo_requests_total{class=}`` / ``nns_slo_errors_total{class=}``
- ``nns_slo_latency_us{class=}`` — schedule-anchored (the honest one)
- ``nns_query_service_us{class=}`` — send-to-reply service latency via
  the ``QueryConnection.on_outcome`` hook; the gap between this and
  the schedule-anchored histogram IS the coordinated-omission evidence

All waits are ``Event.wait`` against absolute deadlines — ``time.sleep``
polling is banned in ``slo/`` (nnslint).
"""

from __future__ import annotations

import random
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..analysis.sanitizer import make_lock
from ..obs.clock import mono_ns
from ..obs.metrics import REGISTRY, MetricsRegistry, quantile_from_counts
from ..query.client import QueryConnection
from ..query.overload import ShedError
from ..tensor.buffer import TensorBuffer
from .spec import (ERRORS_TOTAL, ITL_US, LATENCY_US, REQUESTS_TOTAL,
                   TTFT_US)

SERVICE_US = "nns_query_service_us"
#: requests refused by server admission control (explicit T_SHED) — a
#: distinct family from errors: a shed is the overload layer WORKING,
#: and its latency must not poison the admitted-traffic distribution
SHED_TOTAL = "nns_slo_shed_total"


def poisson_schedule(rate_hz: float, duration_s: float,
                     rng: "random.Random") -> List[float]:
    """Poisson-process arrival offsets in ``[0, duration_s)``:
    exponential inter-arrivals at ``rate_hz`` — the memoryless model of
    independent user traffic."""
    out: List[float] = []
    t = rng.expovariate(rate_hz)
    while t < duration_s:
        out.append(t)
        t += rng.expovariate(rate_hz)
    return out


def constant_schedule(rate_hz: float, duration_s: float,
                      phase: float = 0.0) -> List[float]:
    """Constant-rate offsets (one every ``1/rate_hz`` s, shifted by
    ``phase`` so N workers interleave instead of thundering together)."""
    period = 1.0 / rate_hz
    n = int(duration_s * rate_hz)
    return [phase + i * period for i in range(n)
            if phase + i * period < duration_s]


class LoadGenerator:
    """Drive ``clients`` concurrent open-loop query streams against one
    endpoint for ``duration_s`` seconds.

    ``rate_hz`` is PER CLIENT (aggregate offered load =
    ``clients * rate_hz``).  ``classes`` is a ``[(name, weight), ...]``
    request-class mix.  ``run()`` blocks until every schedule is drained
    (or ``stop()``), returning a summary dict; the registry families
    above update live throughout, so an :class:`~nnstreamer_tpu.slo.
    evaluator.SLOMonitor` gates the run while it happens.
    """

    def __init__(self, host: str, port: int, clients: int = 64,
                 rate_hz: float = 2.0, duration_s: float = 60.0,
                 schedule: str = "poisson", seed: int = 1234,
                 classes: Sequence[Tuple[str, float]] = (("default", 1.0),),
                 timeout: float = 2.0,
                 payload: Optional[np.ndarray] = None,
                 registry: MetricsRegistry = REGISTRY,
                 qos: bool = False) -> None:
        if schedule not in ("poisson", "constant"):
            raise ValueError(f"schedule {schedule!r} "
                             "(want poisson | constant)")
        if clients < 1 or rate_hz <= 0 or duration_s <= 0:
            raise ValueError("clients >= 1, rate_hz > 0, duration_s > 0")
        self.host, self.port = host, int(port)
        self.clients = int(clients)
        self.rate_hz = float(rate_hz)
        self.duration_s = float(duration_s)
        self.schedule = schedule
        self.seed = int(seed)
        self.classes = [(str(n), float(w)) for n, w in classes]
        self.timeout = float(timeout)
        self.payload = (payload if payload is not None
                        else np.arange(4, dtype=np.float32))
        self.registry = registry
        #: QoS mode: each WORKER is assigned one class from the mix
        #: (largest-remainder apportionment over the weights) and
        #: declares it as its connection's QoS class — the per-client
        #: tiering the server's admission control sheds against.  Off:
        #: classes stay weighted-random per request (PR 6 behavior).
        self.qos = bool(qos)
        self._stop = threading.Event()
        self._lock = make_lock("slo")
        self._threads: List[threading.Thread] = []
        self._t0: float = 0.0
        self._live = 0
        self._peak_live = 0
        self._lag_us = [0] * self.clients
        self._counts = {"scheduled": 0, "sent": 0, "ok": 0, "errors": 0,
                        "shed": 0}
        self._shed_by_class = {c: 0 for c, _ in self.classes}
        # class-labeled metric families (shared contract with the
        # evaluator); gauges are lazy — scrape-time reads of loadgen
        # state, nothing per request beyond the counter/hist writes
        self._m_req = {c: registry.counter(REQUESTS_TOTAL, **{"class": c})
                       for c, _ in self.classes}
        self._m_err = {c: registry.counter(ERRORS_TOTAL, **{"class": c})
                       for c, _ in self.classes}
        self._m_lat = {c: registry.histogram(LATENCY_US, **{"class": c})
                       for c, _ in self.classes}
        self._m_srv = {c: registry.histogram(SERVICE_US, **{"class": c})
                       for c, _ in self.classes}
        self._m_shed = {c: registry.counter(SHED_TOTAL, **{"class": c})
                        for c, _ in self.classes}
        registry.gauge("nns_slo_active_clients", fn=lambda: self._live)
        registry.gauge("nns_slo_sched_lag_ms",
                       fn=lambda: max(self._lag_us) / 1e3)

    # -- schedules -----------------------------------------------------------
    def _make_schedule(self, idx: int) -> List[float]:
        if self.schedule == "poisson":
            return poisson_schedule(self.rate_hz, self.duration_s,
                                    random.Random(self.seed + idx))
        phase = (idx / self.clients) / self.rate_hz
        return constant_schedule(self.rate_hz, self.duration_s, phase)

    def _service_hook(self, cls: str, latency_s: float, ok: bool) -> None:
        hist = self._m_srv.get(cls)
        if hist is not None:
            hist.observe(latency_s * 1e6)

    # -- workers -------------------------------------------------------------
    def _qos_assignment(self) -> List[str]:
        """Per-worker class assignment for QoS mode: largest-remainder
        apportionment of ``clients`` workers over the class weights
        (deterministic — a 1:2:5 gold:silver:bronze mix over 64 workers
        is exactly 8/16/40)."""
        total_w = sum(w for _, w in self.classes) or 1.0
        exact = [(c, self.clients * w / total_w) for c, w in self.classes]
        counts = {c: int(x) for c, x in exact}
        remainder = self.clients - sum(counts.values())
        for c, _ in sorted(exact, key=lambda cw: cw[1] - int(cw[1]),
                           reverse=True)[:remainder]:
            counts[c] += 1
        out: List[str] = []
        for c, _ in self.classes:
            out.extend([c] * counts[c])
        return out

    def _worker(self, idx: int, offsets: List[float],
                cls_picks: List[str], worker_qos: Optional[str]) -> None:
        # staggered dial-in: a fleet of clients connecting in the same
        # instant overruns the server's accept/HELLO turnover, and the
        # colliding dials land in connect-retry backoff — seconds of
        # it, charged to the first scheduled arrivals (observed as a
        # one-bad-request-per-client 10 s latency tail).  Spreading the
        # dials costs nothing: arrivals are still anchored to t0.
        self._stop.wait(idx * 0.025)
        conn = QueryConnection(self.host, self.port,
                               timeout=self.timeout, max_retries=2,
                               qos=worker_qos)
        conn.on_outcome = self._service_hook
        try:
            conn.connect()
        except ConnectionError:
            pass    # each query() re-dials; down-at-start counts as
            #         errors per schedule slot, not a dead worker
        with self._lock:
            self._live += 1
            self._peak_live = max(self._peak_live, self._live)
        sent = ok = errors = 0
        shed_by_class: Dict[str, int] = {}
        try:
            for i, off in enumerate(offsets):
                target = self._t0 + off
                wait = target - mono_ns() / 1e9
                if wait > 0 and self._stop.wait(wait):
                    break
                if self._stop.is_set():
                    break
                cls = cls_picks[i]
                buf = TensorBuffer(tensors=[self.payload])
                buf.extra["nns_class"] = cls
                sent += 1
                shed = False
                try:
                    out = conn.query(buf)
                    good = out is not None
                except ShedError:
                    # explicit server-side refusal: counted in its own
                    # family — neither an error (the overload layer
                    # answered, by design) nor an admitted-latency
                    # observation (a fast shed must not flatter p99)
                    good = False
                    shed = True
                except (TimeoutError, ConnectionError, OSError):
                    good = False
                end = mono_ns() / 1e9
                self._lag_us[idx] = max(0, int((end - target) * 1e6))
                self._m_req[cls].inc()
                if shed:
                    shed_by_class[cls] = shed_by_class.get(cls, 0) + 1
                    self._m_shed[cls].inc()
                    continue
                # schedule-anchored latency: queueing-behind-schedule
                # time included (open-loop correction).  Failed
                # requests observe too — the elapsed time (>= the
                # timeout) is a LOWER bound on what the client
                # experienced, so timeouts burn the latency budget
                # instead of vanishing from the distribution (the
                # blind spot a latency-only SLO would otherwise have)
                self._m_lat[cls].observe(
                    max(0.0, (end - target)) * 1e6)
                if good:
                    ok += 1
                else:
                    errors += 1
                    self._m_err[cls].inc()
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                self._live -= 1
                self._counts["sent"] += sent
                self._counts["ok"] += ok
                self._counts["errors"] += errors
                self._counts["shed"] += sum(shed_by_class.values())
                for c, n in shed_by_class.items():
                    self._shed_by_class[c] = \
                        self._shed_by_class.get(c, 0) + n

    # -- run -----------------------------------------------------------------
    def stop(self) -> None:
        self._stop.set()

    def run(self, warmup_s: Optional[float] = None) -> Dict[str, Any]:
        """Precompute every schedule, anchor a shared t0 ``warmup_s``
        out (all workers spawn and dial before the first arrival), run
        the schedules to exhaustion, and return the summary.  The
        default warmup scales with the fleet so the staggered dial-in
        (25 ms/client) completes before the first arrival."""
        if warmup_s is None:
            warmup_s = max(0.5, 0.03 * self.clients)
        rng = random.Random(self.seed ^ 0x5105)
        # baseline the shared histograms: registry.histogram() returns
        # the same instance across LoadGenerator runs in one process,
        # so summary quantiles must diff against THIS run's start or a
        # second soak would report the first soak's distribution too
        self._lat_base = {c: h.state()[2]
                          for c, h in self._m_lat.items()}
        self._srv_base = {c: h.state()[2]
                          for c, h in self._m_srv.items()}
        names = [c for c, _ in self.classes]
        weights = [w for _, w in self.classes]
        qos_by_worker = (self._qos_assignment() if self.qos
                         else [None] * self.clients)
        schedules = []
        for idx in range(self.clients):
            offsets = self._make_schedule(idx)
            if self.qos:
                # QoS mode: the worker's whole stream carries its
                # assigned class — per-CLIENT tiering, matching the
                # per-connection QoS the server admits against
                picks = [qos_by_worker[idx]] * len(offsets)
            else:
                picks = rng.choices(names, weights=weights,
                                    k=len(offsets)) if offsets else []
            schedules.append((offsets, picks))
            self._counts["scheduled"] += len(offsets)
        t_start = mono_ns() / 1e9
        self._t0 = t_start + max(0.0, warmup_s)
        self._threads = [
            threading.Thread(target=self._worker,
                             args=(idx, offsets, picks,
                                   qos_by_worker[idx]), daemon=True,
                             name=f"loadgen-{idx}")
            for idx, (offsets, picks) in enumerate(schedules)]
        for t in self._threads:
            t.start()
        for t in self._threads:
            # bounded join: schedules end on their own; the margin
            # covers a final in-flight request timing out
            t.join(timeout=self.duration_s + warmup_s
                   + 4 * self.timeout + 30)
        elapsed = mono_ns() / 1e9 - self._t0
        return self.summary(elapsed)

    def summary(self, elapsed_s: float) -> Dict[str, Any]:
        with self._lock:
            counts = dict(self._counts)
            peak = self._peak_live
            shed_by_class = dict(self._shed_by_class)
        lat = self._quantiles(self._m_lat,
                              getattr(self, "_lat_base", {}))
        srv = self._quantiles(self._m_srv,
                              getattr(self, "_srv_base", {}))
        sent = counts["sent"]
        return {"clients": self.clients, "peak_live_clients": peak,
                "schedule": self.schedule, "qos": self.qos,
                "rate_hz_per_client": self.rate_hz,
                "offered_rate_hz": round(self.clients * self.rate_hz, 2),
                "duration_s": round(elapsed_s, 2), **counts,
                "achieved_rate_hz": round(sent / elapsed_s, 2)
                if elapsed_s > 0 else 0.0,
                "error_fraction": round(counts["errors"] / sent, 6)
                if sent else 0.0,
                # shed accounting: fraction of OFFERED traffic the
                # server refused with explicit T_SHED, and its class
                # split — admitted latency above excludes these
                "shed_fraction": round(counts["shed"] / sent, 6)
                if sent else 0.0,
                "shed_by_class": shed_by_class,
                "latency_us": lat, "service_us": srv,
                "max_sched_lag_ms": round(max(self._lag_us) / 1e3, 1)}

    @staticmethod
    def _hist_bases(hists: Dict[str, Any]) -> Dict[str, Any]:
        """Per-class bucket baselines at run start (shared registry
        instances accumulate across runs — summaries must diff)."""
        return {c: h.state()[2] for c, h in hists.items()}

    @staticmethod
    def _quantiles(hists: Dict[str, Any],
                   bases: Dict[str, Any]) -> Dict[str, float]:
        counts: Optional[List[int]] = None
        for cls, h in hists.items():
            _, _, c = h.state()
            base = bases.get(cls)
            if base is not None:
                c = [max(0, v - b) for v, b in zip(c, base)]
            if counts is None:
                counts = list(c)
            else:
                for i, v in enumerate(c):
                    counts[i] += v
        if not counts or not sum(counts):
            return {}
        return {q: round(quantile_from_counts(counts, v), 1)
                for q, v in (("p50", 0.50), ("p95", 0.95),
                             ("p99", 0.99))}


class TokenLoadGenerator(LoadGenerator):
    """Open-loop TOKEN-STREAM load: every schedule slot opens one
    ``tensor_llm`` stream (:class:`~nnstreamer_tpu.llm.client.
    TokenStreamClient`) and the per-token receive stamps become the
    coordinated-omission-free token-latency families the ttft/itl SLO
    kinds gate:

    - ``nns_slo_ttft_us{class=}`` — first token stamp minus the
      *scheduled* arrival, NOT the actual send: a worker that fell
      behind schedule charges the queueing to TTFT exactly as an
      independent client arriving on time would have experienced it
      (the open-loop correction, applied to token streams).  A stream
      that produced no first token at all (per-token timeout, dead
      connection) observes its elapsed time as a LOWER bound — a
      stalled server burns the TTFT budget instead of vanishing.
    - ``nns_slo_itl_us{class=}`` — consecutive receive-stamp gaps of
      REAL tokens (a negative terminal marker — the server's refusal /
      eviction frame — is not a token: its gap never observes, and a
      marker-only answer is an error with no TTFT at all, so refusals
      cannot flatter the admitted distribution).
    - sheds land in ``nns_slo_shed_total`` and observe nothing, as in
      the base generator.
    """

    def __init__(self, host: str, port: int,
                 prompt: Sequence[int] = (1, 2, 3, 4),
                 max_new: int = 16, stop_token: int = -1,
                 frame_len: Optional[int] = None,
                 token_timeout: Optional[float] = None,
                 **kw: Any) -> None:
        super().__init__(host, port, **kw)
        self.prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        self.max_new = int(max_new)
        self.stop_token = int(stop_token)
        self.frame_len = frame_len
        self.token_timeout = (float(token_timeout)
                              if token_timeout is not None
                              else self.timeout)
        registry = self.registry
        self._m_ttft = {c: registry.histogram(TTFT_US, **{"class": c})
                        for c, _ in self.classes}
        self._m_itl = {c: registry.histogram(ITL_US, **{"class": c})
                       for c, _ in self.classes}

    def run(self, warmup_s: Optional[float] = None) -> Dict[str, Any]:
        self._ttft_base = self._hist_bases(self._m_ttft)
        self._itl_base = self._hist_bases(self._m_itl)
        return super().run(warmup_s)

    def _worker(self, idx: int, offsets: List[float],
                cls_picks: List[str],
                worker_qos: Optional[str]) -> None:
        from ..llm.client import TokenStreamClient, TokenTimeoutError

        self._stop.wait(idx * 0.025)
        cli = TokenStreamClient(self.host, self.port,
                                timeout=self.timeout, qos=worker_qos,
                                token_timeout=self.token_timeout)
        try:
            cli.connect()
        except ConnectionError:
            pass    # stream() raises per slot; down-at-start = errors
        with self._lock:
            self._live += 1
            self._peak_live = max(self._peak_live, self._live)
        sent = ok = errors = 0
        shed_by_class: Dict[str, int] = {}
        try:
            for i, off in enumerate(offsets):
                target = self._t0 + off
                wait = target - mono_ns() / 1e9
                if wait > 0 and self._stop.wait(wait):
                    break
                if self._stop.is_set():
                    break
                cls = cls_picks[i]
                sent += 1
                shed = False
                failed = False
                toks: List[int] = []
                try:
                    for _, tok in cli.stream(self.prompt, self.max_new,
                                             self.stop_token,
                                             self.frame_len):
                        toks.append(tok)
                except ShedError:
                    shed = True
                except (TokenTimeoutError, TimeoutError,
                        ConnectionError, OSError, ValueError):
                    failed = True
                end = mono_ns() / 1e9
                self._lag_us[idx] = max(0, int((end - target) * 1e6))
                self._m_req[cls].inc()
                if shed:
                    shed_by_class[cls] = shed_by_class.get(cls, 0) + 1
                    self._m_shed[cls].inc()
                    continue
                stamps = list(cli.stamps_ns)
                n_real = len(toks)
                if toks and toks[-1] < 0:
                    n_real -= 1    # terminal marker, not a token
                if n_real > 0:
                    # schedule-anchored TTFT (open-loop correction)
                    self._m_ttft[cls].observe(max(
                        0.0, (stamps[0] / 1e9 - target)) * 1e6)
                    hist = self._m_itl[cls]
                    for j in range(1, n_real):
                        hist.observe(max(0.0, (stamps[j]
                                               - stamps[j - 1]) / 1e3))
                elif failed:
                    # no first token at all: elapsed is a LOWER bound
                    self._m_ttft[cls].observe(
                        max(0.0, end - target) * 1e6)
                # a negative LAST token is the server's refusal /
                # eviction marker: the stream answered, but not with
                # the requested generation — an error, though any real
                # tokens before the marker still observed above
                good = not failed and n_real > 0 and toks[-1] >= 0
                if good:
                    ok += 1
                else:
                    errors += 1
                    self._m_err[cls].inc()
        finally:
            try:
                cli.close()
            except OSError:
                pass
            with self._lock:
                self._live -= 1
                self._counts["sent"] += sent
                self._counts["ok"] += ok
                self._counts["errors"] += errors
                self._counts["shed"] += sum(shed_by_class.values())
                for c, n in shed_by_class.items():
                    self._shed_by_class[c] = \
                        self._shed_by_class.get(c, 0) + n

    def summary(self, elapsed_s: float) -> Dict[str, Any]:
        out = super().summary(elapsed_s)
        out["token_latency"] = {
            "ttft_us": self._quantiles(self._m_ttft,
                                       getattr(self, "_ttft_base", {})),
            "itl_us": self._quantiles(self._m_itl,
                                      getattr(self, "_itl_base", {})),
        }
        return out
