"""SLO evaluator: multi-window burn-rate gating over the metrics registry.

The evaluator owns NO instrumentation of its own — it periodically
snapshots the process metrics registry (``MetricsRegistry.
snapshot_state``, the PR 5 counters/histograms the loadgen and query
clients already write) and keeps a bounded time-indexed store of those
snapshots.  Every evaluation diffs the newest snapshot against the one
closest to ``now - window`` (``state_delta``), which yields exact
windowed request/error counts and a windowed latency distribution with
no per-request timestamping and no interference with the live metrics.

Breach logic is the standard multi-window burn-rate alert: an objective
breaches only when its error budget burns faster than
``spec.burn_threshold`` in BOTH the fast and the slow window.  The fast
window bounds detection latency; the slow window provides the evidence
that the condition is sustained — a single recovered disconnect spikes
the fast window but never the slow one, so it does not page (the
"zero SLO false-positives" gate of the soak smoke).  Early in a run
both windows necessarily cover the same "data so far", so alerts stay
UNARMED until the slow window genuinely outspans the fast one (3x,
capped at the full slow window) — otherwise a startup blip would
breach on the very first tick with no suppression in play.

:class:`SLOMonitor` runs the evaluator on its own thread with
absolute-deadline pacing (``Event.wait`` against a monotonic schedule —
no ``time.sleep`` polling, enforced by the nnslint slo scope) and fires
``on_breach`` exactly at breach ONSET per objective, which is the
flight recorder's dump trigger (slo/flightrec.py).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..analysis.sanitizer import make_lock
from ..obs.clock import mono_ns
from ..obs.metrics import (REGISTRY, MetricsRegistry,
                           count_over_threshold, quantile_from_counts,
                           state_delta)
from .spec import (ERRORS_TOTAL, HIST_FAMILY, HIST_KINDS,
                   REQUESTS_TOTAL, Objective, SLOSpec)


def _family(key: str) -> str:
    return key.partition("{")[0]


def _key_match(key: str, obj: Objective) -> bool:
    if obj.request_class and f'class="{obj.request_class}"' not in key:
        return False
    return not obj.match or obj.match in key


def _sum_counters(delta: Dict[str, Any], family: str,
                  obj: Objective) -> int:
    total = 0
    for key, st in delta.items():
        if st.get("kind") == "counter" and _family(key) == family \
                and _key_match(key, obj):
            total += int(st["value"])
    return total


def _sum_hist(delta: Dict[str, Any], family: str, obj: Objective
              ) -> Tuple[int, Optional[Tuple[int, ...]]]:
    """Summed (count, bucket vector) across matching histogram labels;
    (0, None) when the family has no data."""
    count = 0
    counts: Optional[List[int]] = None
    for key, st in delta.items():
        if st.get("kind") != "histogram" or _family(key) != family \
                or not _key_match(key, obj):
            continue
        count += int(st["count"])
        if counts is None:
            counts = list(st["counts"])
        else:
            for i, c in enumerate(st["counts"]):
                counts[i] += c
    return count, tuple(counts) if counts is not None else None


class Evaluator:
    """Windowed burn-rate evaluation of one :class:`SLOSpec`.

    ``tick(now)`` snapshots the registry, evaluates every objective
    over the fast and slow windows, records breach ONSETS, and returns
    the evaluation dict.  ``now`` defaults to the monotonic clock;
    tests inject a fake clock for deterministic window math.

    ``on_breach(breach_event, evaluation)`` fires outside the
    evaluator's lock, once per objective breach onset (re-arming only
    after the objective recovers) — the flight-recorder trigger.
    """

    def __init__(self, spec: SLOSpec,
                 registry: MetricsRegistry = REGISTRY,
                 on_breach: Optional[Callable[[Dict[str, Any],
                                               Dict[str, Any]],
                                              None]] = None) -> None:
        self.spec = spec
        self.registry = registry
        self.on_breach = on_breach
        #: per-tick observer (flight recorder's snapshot feed): called
        #: with every evaluation dict, outside the evaluator lock
        self.on_tick: Optional[Callable[[Dict[str, Any]], None]] = None
        self._lock = make_lock("slo")
        #: (t, snapshot) store, pruned to slow_window (+ one older
        #: entry so a full slow-window diff always has a base)
        self._snaps: "deque[Tuple[float, Dict[str, Any]]]" = deque()
        self._t0: Optional[float] = None
        self._ticks = 0
        self._breaches: List[Dict[str, Any]] = []
        self._breached_now: Dict[str, bool] = {}
        self._worst_burn: Dict[str, float] = {}
        self._last_eval: Optional[Dict[str, Any]] = None

    # -- windows -------------------------------------------------------------
    def _base_at_locked(self, now: float, window_s: float
                        ) -> Tuple[float, Dict[str, Any]]:
        """Newest stored snapshot at-or-before ``now - window_s``
        (falls back to the oldest stored — early in a run the "window"
        is the data so far, standard burn-rate warm-up behavior)."""
        cutoff = now - window_s
        base = self._snaps[0]
        for t, snap in self._snaps:
            if t <= cutoff:
                base = (t, snap)
            else:
                break
        return base

    def _prune_locked(self, now: float) -> None:
        horizon = now - self.spec.window_slow_s
        while len(self._snaps) > 1 and self._snaps[1][0] <= horizon:
            self._snaps.popleft()

    # -- evaluation ----------------------------------------------------------
    def _objective_window(self, obj: Objective, delta: Dict[str, Any],
                          span_s: float) -> Dict[str, Any]:
        if obj.kind in HIST_KINDS:
            # latency / ttft / itl share the histogram-threshold
            # accounting; only the default family differs (the token
            # kinds read the loadgen's schedule-anchored families
            # unless ``metric`` points at the server-side ones)
            total, counts = _sum_hist(delta,
                                      obj.metric
                                      or HIST_FAMILY[obj.kind], obj)
            bad = (count_over_threshold(counts, obj.threshold_us)
                   if counts else 0)
            p99 = (quantile_from_counts(counts, 0.99)
                   if counts and total else 0.0)
        else:   # error_rate / availability: counter accounting
            total = _sum_counters(delta, REQUESTS_TOTAL, obj)
            bad = _sum_counters(delta, ERRORS_TOTAL, obj)
            p99 = None
        frac = (bad / total) if total else 0.0
        out = {"window_s": round(span_s, 3), "total": total, "bad": bad,
               "bad_fraction": round(frac, 6),
               "burn_rate": round(frac / obj.budget, 4)}
        if p99 is not None:
            out["p99_us"] = round(p99, 1)
        return out

    def _evaluate(self, now: float, snap: Dict[str, Any]
                  ) -> Dict[str, Any]:
        with self._lock:
            t_fast, base_fast = self._base_at_locked(
                now, self.spec.window_fast_s)
            t_slow, base_slow = self._base_at_locked(
                now, self.spec.window_slow_s)
        d_fast = state_delta(snap, base_fast)
        d_slow = state_delta(snap, base_slow)
        fast_span = max(now - t_fast, 1e-9)
        slow_span = max(now - t_slow, 1e-9)
        # arming: early in a run both windows cover the same
        # "data so far" and the multi-window suppression does not exist
        # yet — a startup blip (64 clients dialing at once) would
        # breach on the first tick.  Alerts arm only once the slow
        # window genuinely outspans the fast one (3x, capped at the
        # full slow window so short specs still arm).
        armed = (slow_span + 1e-6
                 >= min(3.0 * fast_span, self.spec.window_slow_s))
        objectives = []
        for obj in self.spec.objectives:
            fast = self._objective_window(obj, d_fast, fast_span)
            slow = self._objective_window(obj, d_slow, slow_span)
            breached = (armed
                        and fast["total"] > 0 and slow["total"] > 0
                        and fast["burn_rate"] > self.spec.burn_threshold
                        and slow["burn_rate"] > self.spec.burn_threshold)
            objectives.append({**obj.to_dict(),
                               "budget": round(obj.budget, 6),
                               "fast": fast, "slow": slow,
                               "breached": breached})
        return {"t": round(now, 3),
                "burn_threshold": self.spec.burn_threshold,
                "armed": armed,
                "objectives": objectives,
                "breached": any(o["breached"] for o in objectives)}

    def tick(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One evaluation step; returns the evaluation dict and fires
        ``on_breach`` for objectives whose breach starts this tick."""
        if now is None:
            now = mono_ns() / 1e9
        # "nns_" covers the loadgen families AND metric-override
        # targets (per-element histograms, query server counters)
        snap = self.registry.snapshot_state(prefix="nns_")
        with self._lock:
            if self._t0 is None:
                self._t0 = now
            self._snaps.append((now, snap))
            self._prune_locked(now)
            self._ticks += 1
        evaluation = self._evaluate(now, snap)
        onsets: List[Dict[str, Any]] = []
        with self._lock:
            for o in evaluation["objectives"]:
                worst = max(o["fast"]["burn_rate"],
                            o["slow"]["burn_rate"])
                if worst > self._worst_burn.get(o["name"], 0.0):
                    self._worst_burn[o["name"]] = worst
                was = self._breached_now.get(o["name"], False)
                self._breached_now[o["name"]] = o["breached"]
                if o["breached"] and not was:
                    event = {"t": evaluation["t"],
                             "tick": self._ticks,
                             "objective": o["name"],
                             "kind": o["kind"],
                             "evidence": {"fast": o["fast"],
                                          "slow": o["slow"],
                                          "burn_threshold":
                                              self.spec.burn_threshold}}
                    self._breaches.append(event)
                    onsets.append(event)
            self._last_eval = evaluation
        if self.on_tick is not None:
            self.on_tick(evaluation)
        if self.on_breach is not None:
            for event in onsets:
                self.on_breach(event, evaluation)
        return evaluation

    # -- verdict -------------------------------------------------------------
    def verdict(self) -> Dict[str, Any]:
        """Machine-readable PASS/FAIL: the soak's exit artifact.  FAIL
        iff any objective ever breached (breaches latch — a soak that
        breached and recovered still failed its SLO)."""
        with self._lock:
            last = self._last_eval
            breaches = list(self._breaches)
            ticks = self._ticks
            duration = ((self._snaps[-1][0] - self._t0)
                        if self._snaps and self._t0 is not None else 0.0)
            worst = dict(self._worst_burn)
        objectives = []
        for obj in self.spec.objectives:
            row = {**obj.to_dict(),
                   "worst_burn_rate": round(worst.get(obj.name, 0.0), 4),
                   "breaches": sum(1 for b in breaches
                                   if b["objective"] == obj.name)}
            if last is not None:
                final = next((o for o in last["objectives"]
                              if o["name"] == obj.name), None)
                if final is not None:
                    row["final"] = {"fast": final["fast"],
                                    "slow": final["slow"]}
            objectives.append(row)
        ok = not breaches
        return {"slo": self.spec.name,
                "verdict": "PASS" if ok else "FAIL",
                "pass": ok,
                "burn_threshold": self.spec.burn_threshold,
                "windows": {"fast_s": self.spec.window_fast_s,
                            "slow_s": self.spec.window_slow_s},
                "ticks": ticks,
                "duration_s": round(duration, 3),
                "objectives": objectives,
                "breaches": breaches}


class SLOMonitor:
    """Background evaluation loop: ticks an :class:`Evaluator` every
    ``spec.tick_s`` on an absolute-deadline schedule (drift-free; an
    overrunning tick skips forward rather than bunching)."""

    def __init__(self, evaluator: Evaluator,
                 tick_s: Optional[float] = None) -> None:
        self.evaluator = evaluator
        self.tick_s = float(tick_s if tick_s is not None
                            else evaluator.spec.tick_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "SLOMonitor":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True,
                                            name="slo-monitor")
            self._thread.start()
        return self

    def stop(self, final_tick: bool = True) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10)
        if final_tick:
            # close the books: the verdict must include requests that
            # landed after the last scheduled tick
            self.evaluator.tick()

    def _loop(self) -> None:
        deadline = mono_ns() / 1e9 + self.tick_s
        while not self._stop.is_set():
            wait = deadline - mono_ns() / 1e9
            if wait > 0 and self._stop.wait(wait):
                return
            self.evaluator.tick()
            now = mono_ns() / 1e9
            deadline += self.tick_s
            if deadline < now:      # overran: realign, don't bunch
                deadline = now + self.tick_s
