"""Multi-host initialization: scale the mesh across TPU hosts over DCN.

The reference's inter-host story is stream transport (nnstreamer-edge /
gRPC, SURVEY.md §2.7).  The TPU-native equivalent for *compute* is a global
mesh: every host runs the same program, `jax.distributed.initialize` wires
the processes into one runtime, `jax.devices()` becomes the global device
list, and the same `make_mesh`/`make_train_step` code runs unchanged — XLA
routes collectives over ICI within a slice and DCN across slices.  (Stream
transport between pipelines remains `nnstreamer_tpu.query`.)

Typical launch (one command per host)::

    from nnstreamer_tpu.parallel import multihost, make_mesh
    multihost.initialize(coordinator="10.0.0.1:8476",
                         num_processes=4, process_id=HOST_INDEX)
    mesh = make_mesh()          # spans all hosts' devices
"""

from __future__ import annotations

from typing import Optional

_initialized = False


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               _backend=None) -> None:
    """Join the cross-host runtime.  On Cloud TPU the arguments are
    auto-detected from the metadata server when omitted; explicit values
    support bring-your-own clusters (reference role: nnstreamer-edge
    host/port wiring).

    ``_backend`` is a test seam: a callable standing in for
    ``jax.distributed.initialize`` (which cannot run single-host), so the
    argument plumbing is coverable without a cluster.
    """
    global _initialized
    if _initialized:
        return
    if _backend is None:
        import jax

        _backend = jax.distributed.initialize
    kwargs = {}
    if coordinator is not None:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    _backend(**kwargs)
    _initialized = True


def is_initialized() -> bool:
    return _initialized


def process_info() -> dict:
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
