"""Data-parallel training step for registry vision models.

`train_step.make_train_step` shards the StreamFormer over the full
dp/sp/tp/ep mesh; vision classifiers (MobileNetV2, ViT, …) are small
enough that replicated params + batch sharding over ``dp`` is the
right decomposition — the classic SPMD data-parallel recipe: annotate
shardings, jit, and let XLA's partitioner insert the gradient psum
(no hand-written collectives, per the scaling-book recipe).

The reference's trainer ABI (nnstreamer_plugin_api_trainer.h) trains
on the host only; this gives every registry vision model a multi-chip
stream-fed training path (elements/trainer.py ``framework=mesh-vision``).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _param_labels(variables) -> Any:
    """'adam' for trainable collections, 'freeze' for batch_stats —
    running BN statistics are not gradient-trained (flax convention)."""
    return {k: jax.tree.map(lambda _: "freeze" if k == "batch_stats"
                            else "adam", v)
            for k, v in variables.items()} if isinstance(variables, dict) \
        else jax.tree.map(lambda _: "adam", variables)


def make_vision_train_step(mesh: Mesh, model, lr: float = 1e-3
                           ) -> Tuple[Callable, Any, Any, NamedSharding]:
    """Returns ``(step, params, opt_state, batch_sharding)``.

    ``step(params, opt, frames, labels) -> (params, opt, loss)`` where
    ``frames`` is a uint8 (B, H, W, 3) batch sharded over ``dp`` (B must
    divide by the dp size) and ``labels`` int32 (B,) class ids.  Params
    and optimizer state are replicated; XLA inserts the cross-device
    gradient reduction.
    """
    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P("dp"))
    tx = optax.multi_transform(
        {"adam": optax.adam(lr), "freeze": optax.set_to_zero()},
        _param_labels(model.params))
    params = jax.device_put(model.params, repl)
    opt = jax.device_put(tx.init(model.params), repl)
    fwd = jax.vmap(model.forward, in_axes=(None, 0))

    def loss_fn(p, frames, labels):
        logits = fwd(p, frames)[0].astype(jnp.float32)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)
        return jnp.mean(nll)

    @functools.partial(jax.jit,
                       in_shardings=(repl, repl, data, data),
                       out_shardings=(repl, repl, None),
                       donate_argnums=(0, 1))
    def step(p, o, frames, labels):
        loss, grads = jax.value_and_grad(loss_fn)(p, frames, labels)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    return step, params, opt, data


def pad_to_multiple(batch: np.ndarray, m: int) -> np.ndarray:
    """Repeat-pad axis 0 up to a multiple of ``m`` (dp size) so a
    stream tail still shards evenly; loss over repeated samples is a
    reweighting, not a correctness issue, for the trailing batch.
    Cycles the batch as many times as needed — a 3-frame tail on a
    dp=8 mesh pads to 8, not 6."""
    b = batch.shape[0]
    pad = (-b) % m
    if not pad:
        return batch
    filler = np.concatenate([batch] * -(-pad // b), axis=0)[:pad]
    return np.concatenate([batch, filler], axis=0)
