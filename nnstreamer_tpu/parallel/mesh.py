"""Device mesh construction for multi-chip execution.

Net-new TPU capability (SURVEY.md §2.7: the reference has no DP/TP/SP/EP —
its parallelism is pipeline-threading plus among-device offload; this module
supplies the missing scale story the TPU-native way): a named
``jax.sharding.Mesh`` over all addressable devices, with axes

- ``dp``  — data parallel (batch)
- ``sp``  — sequence/context parallel (ring attention rides this axis)
- ``tp``  — tensor/model parallel (megatron-style sharded matmuls)
- ``ep``  — expert parallel (MoE all_to_all)

Axis sizes are factorized from the device count; unused axes get size 1 so
the same jitted program runs from 1 chip to a full slice.  On multi-host
deployments the mesh spans hosts (jax.devices() is global) and XLA routes
collectives over ICI within a slice and DCN across slices.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

DEFAULT_AXES = ("dp", "sp", "tp", "ep")


def factorize(n: int, num_axes: int) -> Tuple[int, ...]:
    """Greedy power-of-two-ish factorization of ``n`` across axes,
    biased toward dp first (dp gets the largest factor)."""
    sizes = [1] * num_axes
    i = 0
    remaining = n
    # assign factors round-robin, largest prime factors first
    factors: List[int] = []
    d = 2
    while d * d <= remaining:
        while remaining % d == 0:
            factors.append(d)
            remaining //= d
        d += 1
    if remaining > 1:
        factors.append(remaining)
    for f in sorted(factors, reverse=True):
        sizes[i % num_axes] *= f
        i += 1
    return tuple(sizes)


def make_mesh(n_devices: Optional[int] = None,
              axis_sizes: Optional[Dict[str, int]] = None,
              axes: Sequence[str] = DEFAULT_AXES,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh.

    - ``axis_sizes``: explicit {axis: size}; missing axes get size 1;
      product must equal the device count.
    - otherwise sizes are auto-factorized over ``axes`` with unused axes
      collapsed to 1: for n=8 → dp=2, sp=2, tp=2, ep=1.
    """
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    if axis_sizes:
        sizes = tuple(int(axis_sizes.get(a, 1)) for a in axes)
        prod = int(np.prod(sizes))
        if prod != n:
            raise ValueError(f"axis sizes {dict(zip(axes, sizes))} "
                             f"multiply to {prod}, have {n} devices")
    else:
        # auto: spread over dp/sp/tp, keep ep=1 unless explicitly requested
        auto_axes = [a for a in axes if a != "ep"] or list(axes)
        auto = factorize(n, len(auto_axes))
        lookup = dict(zip(auto_axes, auto))
        sizes = tuple(lookup.get(a, 1) for a in axes)
    grid = np.asarray(devs, dtype=object).reshape(sizes)
    return Mesh(grid, tuple(axes))


def mesh_info(mesh: Mesh) -> Dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))
