"""jax API-drift shims for the multi-chip layer.

``jax.shard_map`` graduated out of ``jax.experimental.shard_map``
upstream, and the two revisions disagree on both the attribute path
and one keyword (``check_vma`` is the graduated spelling of the
experimental ``check_rep``).  Every ``parallel/`` call site imports
:func:`shard_map` from here so the layer runs on either revision
instead of dying with ``AttributeError: module 'jax' has no attribute
'shard_map'`` on hosts that ship the experimental-only API.
"""

from __future__ import annotations

import jax


def _experimental_shard_map():
    from jax.experimental.shard_map import shard_map as _sm

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, **kw):
        # graduated-API spelling → experimental spelling
        if check_vma is not None and "check_rep" not in kw:
            kw["check_rep"] = check_vma
        return _sm(f, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, **kw)

    return shard_map


#: ``jax.shard_map`` when this jax has it, else the experimental one
#: behind a keyword-translating wrapper
shard_map = getattr(jax, "shard_map", None) or _experimental_shard_map()


def _axis_size_fallback(axis_name):
    # pre-graduation jax has no jax.lax.axis_size; psum of the constant
    # 1 over the axis constant-folds to a static Python int inside
    # shard_map, which is exactly what the ring/all-to-all loop bounds
    # need
    return jax.lax.psum(1, axis_name)


#: ``jax.lax.axis_size`` when present, else the psum(1) fold
axis_size = getattr(jax.lax, "axis_size", None) or _axis_size_fallback
