"""Pipeline parallelism (pp axis): GPipe-scheduled stage sharding.

Completes the framework's parallelism set (dp/sp/tp/ep in train_step.py,
pp here).  The reference has no training-scale story at all
(gsttensor_trainer.c is single-device); this is TPU-native design:

- **stage sharding**: transformer layers are STACKED on a leading axis and
  sharded over the mesh's ``pp`` axis — each pp rank owns ``L/pp``
  consecutive layers, embed/head are replicated (their grads are nonzero
  only on the ranks that use them; the pp psum recovers the global grad).
- **GPipe fill-drain schedule**: the batch splits into M microbatches; a
  ``lax.scan`` over ``M + pp - 1`` ticks keeps every rank busy once the
  pipe fills.  At each tick every rank applies its stage to the activation
  it received and hands the result to the next rank via
  ``jax.lax.ppermute`` — one hop over ICI per tick.
- **backward for free**: the whole schedule (scan + ppermute chain) is
  differentiated by jax; the transposed program runs the reversed
  schedule with reversed permutes, so 1F1B-style comm emerges from
  autodiff rather than hand-written send/recv.  (The reference's NCCL
  analogue would be explicit isend/irecv pairs.)
- composes with **dp** (batch), **sp** (ring attention over sequence) and
  **tp** (megatron heads/hidden) on the same mesh.

The stage math is the dense StreamFormer layer (attention + MLP; MoE stays
with the ep axis in train_step.py — pp×ep on one mesh needs more devices
than the 8-way CI mesh can host).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .compat import shard_map
from .ring_attention import ring_attention
from .train_step import StreamFormerConfig, _ln


def stacked_param_specs() -> Dict[str, Any]:
    """PartitionSpec per leaf: layer stacks shard over pp (leading axis),
    tp shards heads/hidden within each stage."""
    return {
        "embed": P(), "pos": P(), "head": P(), "ln_f": P(),
        "ln1": P("pp", None),
        "ln2": P("pp", None),
        "wqkv": P("pp", None, None, "tp", None),
        "wo": P("pp", "tp", None, None),
        "w1": P("pp", None, "tp"),
        "w2": P("pp", "tp", None),
    }


def init_stacked_params(cfg: StreamFormerConfig, seed: int = 0
                        ) -> Dict[str, Any]:
    k = jax.random.PRNGKey(seed)
    ks = jax.random.split(k, 8)
    d, h, hd, f, L = cfg.dim, cfg.heads, cfg.head_dim, cfg.mlp, cfg.layers

    def norm(key, shape, scale=0.02):
        return jax.random.normal(key, shape, jnp.float32) * scale

    return {
        "embed": norm(ks[0], (cfg.vocab, d)),
        "pos": norm(ks[1], (cfg.max_seq, d)),
        "head": norm(ks[2], (d, cfg.vocab)),
        "ln_f": jnp.ones((d,), jnp.float32),
        "ln1": jnp.ones((L, d), jnp.float32),
        "ln2": jnp.ones((L, d), jnp.float32),
        "wqkv": norm(ks[3], (L, d, 3, h, hd)),
        "wo": norm(ks[4], (L, h, hd, d)),
        "w1": norm(ks[5], (L, d, f)),
        "w2": norm(ks[6], (L, f, d)),
    }


def _stage_forward(params, x, cfg: StreamFormerConfig):
    """Apply this rank's local layer stack to activations (mb, T_local, D).
    Leading stack axis is the LOCAL pp shard (static size L/pp)."""
    n_local = params["ln1"].shape[0]
    for i in range(n_local):
        y = _ln(x.astype(jnp.float32), params["ln1"][i]).astype(cfg.dtype)
        qkv = jnp.einsum("btd,dchn->btchn", y,
                         params["wqkv"][i].astype(cfg.dtype))
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn = jax.vmap(
            lambda qq, kk, vv: ring_attention(qq, kk, vv, "sp",
                                              causal=True))(q, k, v)
        o = jnp.einsum("bthn,hnd->btd", attn,
                       params["wo"][i].astype(cfg.dtype))
        o = jax.lax.psum(o, "tp")
        x = x + o
        y = _ln(x.astype(jnp.float32), params["ln2"][i]).astype(cfg.dtype)
        hcore = jax.nn.gelu(jnp.einsum("btd,df->btf", y,
                                       params["w1"][i].astype(cfg.dtype)))
        m = jnp.einsum("btf,fd->btd", hcore,
                       params["w2"][i].astype(cfg.dtype))
        x = x + jax.lax.psum(m, "tp")
    return x


def _pp_loss_local(params, tokens, labels, cfg: StreamFormerConfig,
                   n_stages: int, microbatches: int):
    """GPipe fill-drain loss inside shard_map.

    tokens: (B_local, T_local) int32, B_local = microbatches * mb.
    Returns the global mean NLL (psum over dp/sp/pp)."""
    r = jax.lax.axis_index("pp")
    sp_idx = jax.lax.axis_index("sp")
    B, T = tokens.shape
    mb = B // microbatches
    toks = tokens.reshape(microbatches, mb, T)
    labs = labels.reshape(microbatches, mb, T)
    pos = sp_idx * T + jnp.arange(T)

    def embed(tb):
        return (params["embed"][tb] + params["pos"][pos][None]
                ).astype(cfg.dtype)

    n_ticks = microbatches + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, s):
        x_out, nll_sum, tok_count = carry
        # hand the previous tick's output to the next stage
        x_in = jax.lax.ppermute(x_out, "pp", perm)
        # rank 0 ingests microbatch s (when one remains)
        mb_in = jnp.clip(s, 0, microbatches - 1)
        fresh = embed(toks[mb_in])
        x_in = jnp.where((r == 0) & (s < microbatches), fresh, x_in)
        x_next = _stage_forward(params, x_in, cfg)
        # last rank emits microbatch s-(P-1)'s loss (when valid)
        mb_out = jnp.clip(s - (n_stages - 1), 0, microbatches - 1)
        emit = (r == n_stages - 1) & (s >= n_stages - 1)
        xf = _ln(x_next.astype(jnp.float32), params["ln_f"])
        logits = jnp.einsum("btd,dv->btv", xf, params["head"])
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(
            logp, labs[mb_out][..., None], axis=-1)[..., 0]
        nll_sum = nll_sum + jnp.where(emit, jnp.sum(nll), 0.0)
        tok_count = tok_count + jnp.where(emit, nll.size, 0)
        return (x_next, nll_sum, tok_count), None

    x0 = jnp.zeros((mb, T, cfg.dim), cfg.dtype)
    (_, nll_sum, tok_count), _ = jax.lax.scan(
        tick, (x0, jnp.float32(0), jnp.int32(0)), jnp.arange(n_ticks))
    s = jax.lax.psum(nll_sum, ("dp", "sp", "pp"))
    n = jax.lax.psum(tok_count, ("dp", "sp", "pp"))
    return s / n.astype(jnp.float32)


def make_pp_train_step(mesh: Mesh, cfg: Optional[StreamFormerConfig] = None,
                       microbatches: Optional[int] = None, seed: int = 0
                       ) -> Tuple[Any, Dict, Dict, Dict]:
    """Build (jitted_step, sharded_params, sharded_opt, specs) for a mesh
    with a ``pp`` axis (plus any of dp/sp/tp)."""
    cfg = cfg or StreamFormerConfig()
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    missing = {"dp", "sp", "tp", "pp"} - set(mesh.axis_names)
    if missing:
        raise ValueError(f"pp mesh must name axes dp/sp/tp/pp (size-1 "
                         f"axes are fine); missing {sorted(missing)}")
    n_stages = sizes.get("pp", 1)
    if cfg.layers % n_stages:
        raise ValueError(f"pp={n_stages} must divide layers={cfg.layers} "
                         "(each stage holds layers/pp consecutive layers)")
    M = microbatches or max(2, n_stages)
    specs = stacked_param_specs()
    params = init_stacked_params(cfg, seed)
    opt = {"m": jax.tree.map(jnp.zeros_like, params),
           "v": jax.tree.map(jnp.zeros_like, params),
           "step": jnp.zeros((), jnp.int32)}
    opt_specs = {"m": specs, "v": specs, "step": P()}
    mesh_axes = ("dp", "sp", "tp", "pp")

    def local_step(params, opt, tokens, labels):
        loss, grads = jax.value_and_grad(
            lambda p: _pp_loss_local(p, tokens, labels, cfg, n_stages, M)
        )(params)

        def sync(g, spec):
            used = {ax for part in spec if part
                    for ax in ((part,) if isinstance(part, str) else part)}
            axes = tuple(a for a in mesh_axes if a not in used)
            return jax.lax.psum(g, axes) if axes else g

        grads = jax.tree.map(sync, grads, specs,
                             is_leaf=lambda x: isinstance(x, jnp.ndarray))
        step = opt["step"] + 1
        b1, b2, eps = 0.9, 0.999, 1e-8
        m = jax.tree.map(lambda mm, g: b1 * mm + (1 - b1) * g,
                         opt["m"], grads)
        v = jax.tree.map(lambda vv, g: b2 * vv + (1 - b2) * g * g,
                         opt["v"], grads)
        t_f = step.astype(jnp.float32)
        corr = jnp.sqrt(1 - b2 ** t_f) / (1 - b1 ** t_f)
        params = jax.tree.map(
            lambda p, mm, vv: p - cfg.lr * corr * mm /
            (jnp.sqrt(vv) + eps), params, m, v)
        return params, {"m": m, "v": v, "step": step}, loss

    data_spec = P("dp", "sp")
    shard_step = shard_map(
        local_step, mesh=mesh,
        in_specs=(specs, opt_specs, data_spec, data_spec),
        out_specs=(specs, opt_specs, P()),
        check_vma=False)
    jitted = jax.jit(shard_step, donate_argnums=(0, 1))

    def place(tree, spec_tree):
        return jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            tree, spec_tree,
            is_leaf=lambda x: isinstance(x, (jnp.ndarray, np.ndarray)))

    return jitted, place(params, specs), place(opt, opt_specs), specs
