"""Distributed/multi-chip layer: mesh, sharding, sequence parallelism
(ring + Ulysses all-to-all), training."""

from . import multihost
from .mesh import DEFAULT_AXES, factorize, make_mesh, mesh_info
from .ring_attention import local_attention, ring_attention
from .train_step import (StreamFormerConfig, init_params, make_data_sharding,
                         make_train_step)
from .ulysses import ulysses_attention

__all__ = [
    "make_mesh", "mesh_info", "factorize", "DEFAULT_AXES",
    "ring_attention", "local_attention", "ulysses_attention",
    "StreamFormerConfig", "init_params", "make_train_step",
    "make_data_sharding", "multihost",
]
