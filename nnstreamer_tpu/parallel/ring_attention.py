"""Ring attention: exact attention over sequence shards on a mesh axis.

Net-new, first-class long-context capability (absent from the reference —
SURVEY.md §5 "Long-context / sequence parallelism: Absent"): each device
holds a sequence block; K/V blocks rotate around the ring via
``jax.lax.ppermute`` while a flash-style streaming softmax (running max +
running sum) accumulates exact attention — memory per device stays
O(T_local²) independent of ring size, and the K/V transfer for step i+1
overlaps with compute for step i (XLA schedules the ppermute async on ICI).

Use inside ``jax.shard_map`` with a mesh axis carrying the sequence
dimension (``sp``), e.g. through
:func:`nnstreamer_tpu.parallel.train_step.make_train_step`.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name: str = "sp", causal: bool = False
                   ) -> jnp.ndarray:
    """Exact multi-head attention over a ring of sequence shards.

    Args (per-device views inside shard_map):
      q, k, v: (T_local, n_heads, head_dim)
      axis_name: mesh axis carrying the sequence shards
      causal: apply causal masking using global positions

    Returns: (T_local, n_heads, head_dim) attention output.
    """
    n = jax.lax.axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    t_local, n_heads, head_dim = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, jnp.float32))

    q_pos = my_idx * t_local + jnp.arange(t_local)  # global query positions

    def block(carry, step):
        k_blk, v_blk, acc, row_max, row_sum = carry
        # source block index: the block we hold at `step` originated at
        # device (my_idx - step) mod n
        src = (my_idx - step) % n
        k_pos = src * t_local + jnp.arange(t_local)
        # scores: (heads, Tq, Tk) in f32 for stable softmax accumulation
        s = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                       k_blk.astype(jnp.float32)) * scale
        if causal:
            mask = k_pos[None, None, :] > q_pos[None, :, None]
            s = jnp.where(mask, -jnp.inf, s)
        blk_max = jnp.max(s, axis=-1)                      # (h, Tq)
        new_max = jnp.maximum(row_max, blk_max)
        # guard fully-masked rows (all -inf)
        safe_max = jnp.where(jnp.isfinite(new_max), new_max, 0.0)
        p = jnp.exp(s - safe_max[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        corr = jnp.exp(jnp.where(jnp.isfinite(row_max),
                                 row_max - safe_max, -jnp.inf))
        corr = jnp.where(jnp.isfinite(corr), corr, 0.0)
        acc = acc * corr[..., None] + jnp.einsum(
            "hqk,khd->hqd", p, v_blk.astype(jnp.float32))
        row_sum = row_sum * corr + jnp.sum(p, axis=-1)
        # rotate K/V to the next device on the ring
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, acc, new_max, row_sum), None

    acc0 = jnp.zeros((n_heads, t_local, head_dim), jnp.float32)
    max0 = jnp.full((n_heads, t_local), -jnp.inf, jnp.float32)
    sum0 = jnp.zeros((n_heads, t_local), jnp.float32)
    (_, _, acc, _, row_sum), _ = jax.lax.scan(
        block, (k, v, acc0, max0, sum0), jnp.arange(n))
    out = acc / jnp.maximum(row_sum[..., None], 1e-20)
    return jnp.transpose(out, (1, 0, 2)).astype(q.dtype)  # (Tq, h, d)


def local_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = False) -> jnp.ndarray:
    """Single-device reference attention (same signature, no ring) — used
    by tests to validate ring_attention numerically."""
    t, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    s = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        pos = jnp.arange(t)
        s = jnp.where(pos[None, None, :] > pos[None, :, None], -jnp.inf, s)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("hqk,khd->qhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
