"""Ring attention: exact attention over sequence shards on a mesh axis.

Net-new, first-class long-context capability (absent from the reference —
SURVEY.md §5 "Long-context / sequence parallelism: Absent"): each device
holds a sequence block; K/V blocks rotate around the ring via
``jax.lax.ppermute`` while a streaming softmax accumulates exact
attention, and the K/V transfer for step i+1 overlaps with compute for
step i (XLA schedules the ppermute async on ICI).  On TPU each block
runs the Pallas flash kernel and blocks merge via their logsumexp, so
per-device memory is O(T_local·d) — no score matrix in HBM; the jnp
fallback path materializes one (T_local, T_local) block at a time.

Use inside ``jax.shard_map`` with a mesh axis carrying the sequence
dimension (``sp``), e.g. through
:func:`nnstreamer_tpu.parallel.train_step.make_train_step`.
"""

from __future__ import annotations


import jax

from .compat import axis_size
import jax.numpy as jnp


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name: str = "sp", causal: bool = False,
                   flash: "bool | None" = None) -> jnp.ndarray:
    """Exact multi-head attention over a ring of sequence shards.

    Args (per-device views inside shard_map):
      q, k, v: (T_local, n_heads, head_dim)
      axis_name: mesh axis carrying the sequence shards
      causal: apply causal masking using global positions
      flash: run each ring step's block attention as the Pallas
        streaming-softmax kernel (ops/flash_attention.py) and combine
        blocks via their logsumexp — per-device memory drops from
        O(T_local²) score matrices to O(T_local·d).  Default: on TPU
        only (numerics are oracle-tested identical; the CPU interpreter
        is slow).

    Returns: (T_local, n_heads, head_dim) attention output.
    """
    if flash is None:
        from ..ops.flash_attention import flash_is_default

        flash = flash_is_default()
    n = axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    t_local, n_heads, head_dim = q.shape
    if flash:
        return _ring_flash(q, k, v, axis_name, causal, n, my_idx)
    scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, jnp.float32))

    q_pos = my_idx * t_local + jnp.arange(t_local)  # global query positions

    def block(carry, step):
        k_blk, v_blk, acc, row_max, row_sum = carry
        # source block index: the block we hold at `step` originated at
        # device (my_idx - step) mod n
        src = (my_idx - step) % n
        k_pos = src * t_local + jnp.arange(t_local)
        # scores: (heads, Tq, Tk) in f32 for stable softmax accumulation
        s = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                       k_blk.astype(jnp.float32)) * scale
        if causal:
            mask = k_pos[None, None, :] > q_pos[None, :, None]
            s = jnp.where(mask, -jnp.inf, s)
        blk_max = jnp.max(s, axis=-1)                      # (h, Tq)
        new_max = jnp.maximum(row_max, blk_max)
        # guard fully-masked rows (all -inf)
        safe_max = jnp.where(jnp.isfinite(new_max), new_max, 0.0)
        p = jnp.exp(s - safe_max[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        corr = jnp.exp(jnp.where(jnp.isfinite(row_max),
                                 row_max - safe_max, -jnp.inf))
        corr = jnp.where(jnp.isfinite(corr), corr, 0.0)
        acc = acc * corr[..., None] + jnp.einsum(
            "hqk,khd->hqd", p, v_blk.astype(jnp.float32))
        row_sum = row_sum * corr + jnp.sum(p, axis=-1)
        # rotate K/V to the next device on the ring
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, acc, new_max, row_sum), None

    acc0 = jnp.zeros((n_heads, t_local, head_dim), jnp.float32)
    max0 = jnp.full((n_heads, t_local), -jnp.inf, jnp.float32)
    sum0 = jnp.zeros((n_heads, t_local), jnp.float32)
    (_, _, acc, _, row_sum), _ = jax.lax.scan(
        block, (k, v, acc0, max0, sum0), jnp.arange(n))
    out = acc / jnp.maximum(row_sum[..., None], 1e-20)
    return jnp.transpose(out, (1, 0, 2)).astype(q.dtype)  # (Tq, h, d)


def _ring_flash(q, k, v, axis_name: str, causal: bool, n, my_idx):
    """Ring steps through the Pallas flash kernel: each K/V block runs
    the VMEM-tiled streaming-softmax forward (with its logsumexp), and
    blocks combine through the standard lse merge — no (T_local,
    T_local) score matrix ever materializes in HBM.

    Causality decomposes per block relation (the offsets are traced, so
    they cannot enter the kernel as static args): a block from the
    ring's PAST is fully visible (causal=False), the DIAGONAL block is
    causal at equal offsets, a FUTURE block contributes nothing.
    """
    from ..ops.flash_attention import flash_attention

    t_local, n_heads, head_dim = q.shape

    def _full(q, kb, vb):
        return flash_attention(q, kb, vb, causal=False, return_lse=True)

    def _diag(q, kb, vb):
        return flash_attention(q, kb, vb, causal=True, return_lse=True)

    def _skip(q, kb, vb):
        return (jnp.zeros_like(q),
                jnp.full((n_heads, t_local), -jnp.inf, jnp.float32))

    def block(carry, step):
        k_blk, v_blk, acc, m, den = carry
        src = (my_idx - step) % n
        if causal:
            rel = jnp.where(src == my_idx, 1,
                            jnp.where(src < my_idx, 0, 2)).astype(jnp.int32)
            o_blk, lse = jax.lax.switch(rel, (_full, _diag, _skip),
                                        q, k_blk, v_blk)
        else:
            o_blk, lse = _full(q, k_blk, v_blk)
        new_m = jnp.maximum(m, lse)                        # (h, Tq)
        safe = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        w = jnp.where(jnp.isfinite(lse), jnp.exp(lse - safe), 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe), 0.0)
        wq = jnp.transpose(w)[:, :, None]                  # (Tq, h, 1)
        corrq = jnp.transpose(corr)[:, :, None]
        acc = acc * corrq + o_blk.astype(jnp.float32) * wq
        den = den * corr + w
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, acc, new_m, den), None

    acc0 = jnp.zeros((t_local, n_heads, head_dim), jnp.float32)
    m0 = jnp.full((n_heads, t_local), -jnp.inf, jnp.float32)
    den0 = jnp.zeros((n_heads, t_local), jnp.float32)
    (_, _, acc, _, den), _ = jax.lax.scan(
        block, (k, v, acc0, m0, den0), jnp.arange(n))
    denq = jnp.maximum(jnp.transpose(den)[:, :, None], 1e-20)
    return (acc / denq).astype(q.dtype)


def local_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = False) -> jnp.ndarray:
    """Single-device reference attention (same signature, no ring) — used
    by tests to validate ring_attention numerically."""
    t, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    s = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        pos = jnp.arange(t)
        s = jnp.where(pos[None, None, :] > pos[None, :, None], -jnp.inf, s)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("hqk,khd->qhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
