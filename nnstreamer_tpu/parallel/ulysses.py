"""Ulysses-style all-to-all sequence parallelism: the second long-context
strategy next to :mod:`.ring_attention`.

Net-new, first-class capability (the reference has no sequence
parallelism, SURVEY.md §5).  Where ring attention keeps K/V rotating and
computes blockwise, Ulysses re-shards with two collectives:

1. all-to-all scatters the HEAD dimension and gathers the SEQUENCE
   dimension — each device then holds the FULL sequence for heads/n
   heads;
2. plain exact attention runs locally (no streaming softmax needed);
3. the inverse all-to-all restores sequence shards × all heads.

Trade-off vs ring: Ulysses moves Q, K, V and O once each through
all-to-all (4·T·H·D/n words per device, latency O(1) collectives — rides
ICI well) and needs heads % n == 0, while ring needs n ppermute rounds of
K/V but works for any head count and keeps peak memory at
O(T_local · T_local) scores.  Both are exact; pick per topology via
``StreamFormerConfig.seq_parallel``.
"""

from __future__ import annotations

import jax

from .compat import axis_size
import jax.numpy as jnp

from .ring_attention import local_attention


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      axis_name: str = "sp", causal: bool = False,
                      flash: "bool | None" = None) -> jnp.ndarray:
    """Exact attention over sequence shards via head↔sequence all-to-all.

    Args (per-device views inside shard_map):
      q, k, v: (T_local, n_heads, head_dim); n_heads must divide by the
      axis size.
      flash: run the local core as the Pallas streaming-softmax kernel
        (ops/flash_attention.py) — default: on TPU (this is the
        sequence-parallel training path: the kernel's O(T·d)
        forward AND backward residuals are the design, so the
        forward-speed crossover gate does not apply here).

    Returns: (T_local, n_heads, head_dim).
    """
    n = axis_size(axis_name)
    t_local, n_heads, _ = q.shape
    if n_heads % n:
        raise ValueError(
            f"ulysses: heads {n_heads} not divisible by |{axis_name}|={n}"
            " (use ring_attention for uneven head counts)")

    def a2a(x, split_axis, concat_axis):
        return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)

    # scatter heads, gather sequence: (T_local, H, D) -> (T_global, H/n, D)
    qg, kg, vg = (a2a(x, 1, 0) for x in (q, k, v))
    # the full sequence is local now, so plain causal attention is exact
    if flash is None:
        # NOT length-gated: ulysses is the sequence-parallel TRAINING
        # path, where the kernel's O(T*d) forward+backward residuals are
        # the point — naive autodiff saves (H, T, T) probability
        # residuals per layer, which OOMs long-context jobs that fit
        # with the kernel.  The speed crossover (flash_wins) is measured
        # on forward-only timings and does not cover the backward.
        from ..ops.flash_attention import flash_is_default

        flash = flash_is_default()
    if flash:
        from ..ops.flash_attention import flash_attention

        out = flash_attention(qg, kg, vg, causal=causal)
    else:
        out = local_attention(qg, kg, vg, causal=causal)
    # inverse: scatter sequence, gather heads
    return a2a(out, 0, 1)
