"""StreamFormer: a sharded transformer LM + train step over a 4-axis mesh.

Net-new TPU scale story (the reference's trainer is single-device
on-device training, gsttensor_trainer.c; its only distribution is stream
offload).  This module is the framework's distributed training core and the
target of the driver's multi-chip dryrun:

- **dp**: batch sharded, gradients psum'd
- **sp**: sequence sharded, attention runs as ring attention (exact) with
  K/V rotating on ICI
- **tp**: attention heads + MLP hidden megatron-sharded, activations psum'd
- **ep**: MoE experts sharded, switch-style top-1 ROUTED: each token runs
  exactly one expert (capacity-capped), with the Switch load-balance aux
  loss — compute scales with tokens, not with experts

Everything is a single ``jax.shard_map``-ped, jitted step: params enter
device-resident with per-leaf PartitionSpecs, the step never leaves the
device, and gradients are psum'd only over the axes each param is
replicated on.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .compat import shard_map
from .ring_attention import ring_attention


@dataclasses.dataclass
class StreamFormerConfig:
    vocab: int = 256
    dim: int = 128
    heads: int = 8
    head_dim: int = 16
    mlp: int = 512
    layers: int = 2
    experts: int = 2          # MoE experts (sharded over ep)
    capacity_factor: float = 1.25  # per-expert token capacity multiplier
    aux_coef: float = 0.01    # Switch load-balance aux loss weight
    max_seq: int = 1024
    dtype: Any = jnp.bfloat16
    lr: float = 1e-3
    #: long-context strategy over the sp axis: "ring" (K/V ppermute ring,
    #: any head count) or "ulysses" (head<->seq all-to-all, heads % sp == 0)
    seq_parallel: str = "ring"


def _param_specs(cfg: StreamFormerConfig) -> Dict[str, Any]:
    """PartitionSpec per parameter leaf.  tp shards heads/hidden; ep shards
    experts; everything is replicated over dp and sp."""
    layer = {
        "ln1": P(), "ln2": P(),
        "wqkv": P(None, None, "tp", None),   # (D, 3, H, Dh)
        "wo": P("tp", None, None),           # (H, Dh, D)
        "w1": P(None, "tp"),                 # (D, F)
        "w2": P("tp", None),                 # (F, D)
        "gate": P(),                         # (D, E)
        "we1": P("ep", None, None),          # (E_local, D, F)
        "we2": P("ep", None, None),          # (E_local, F, D)
    }
    return {
        "embed": P(),                        # (V, D)
        "pos": P(),                          # (max_seq, D)
        "head": P(),                         # (D, V) replicated (small V)
        "ln_f": P(),
        "layers": [dict(layer) for _ in range(cfg.layers)],
    }


def init_params(cfg: StreamFormerConfig, seed: int = 0) -> Dict[str, Any]:
    k = jax.random.PRNGKey(seed)
    ks = jax.random.split(k, 8 * cfg.layers + 4)
    it = iter(ks)

    def norm(key, shape, scale=0.02):
        return (jax.random.normal(key, shape, jnp.float32) * scale)

    d, h, hd, f, e = cfg.dim, cfg.heads, cfg.head_dim, cfg.mlp, cfg.experts
    params: Dict[str, Any] = {
        "embed": norm(next(it), (cfg.vocab, d)),
        "pos": norm(next(it), (cfg.max_seq, d)),
        "head": norm(next(it), (d, cfg.vocab)),
        "ln_f": jnp.ones((d,), jnp.float32),
        "layers": [],
    }
    for _ in range(cfg.layers):
        params["layers"].append({
            "ln1": jnp.ones((d,), jnp.float32),
            "ln2": jnp.ones((d,), jnp.float32),
            "wqkv": norm(next(it), (d, 3, h, hd)),
            "wo": norm(next(it), (h, hd, d)),
            "w1": norm(next(it), (d, f)),
            "w2": norm(next(it), (f, d)),
            "gate": norm(next(it), (d, e)),
            "we1": norm(next(it), (e, d, f)),
            "we2": norm(next(it), (e, f, d)),
        })
    return params


def _moe_switch(y, lyr, cfg: StreamFormerConfig):
    """Switch-Transformer top-1 routed MoE over the ep axis.

    Activations are replicated over ep (data rides dp/sp), so routing needs
    NO all-to-all: every ep shard sees all local tokens, gathers only those
    routed to ITS experts into a dense (E_local, capacity, D) block — a
    static-shaped, MXU-friendly batched matmul — and the psum over ep
    scatters expert outputs back to the token stream.  Tokens over an
    expert's capacity are dropped (residual passes them through), the
    standard Switch capacity-factor contract.

    Returns (moe_out (B,T,D), aux) where aux is the Switch load-balance
    loss E * Σ_e f_e·P_e computed over the GLOBAL (dp,sp) token set.
    """
    b, t, d = y.shape
    n = b * t
    e = cfg.experts
    tokens = y.reshape(n, d)
    logits = jnp.einsum("nd,de->ne", tokens.astype(jnp.float32),
                        lyr["gate"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)          # (N, E) f32
    exp_idx = jnp.argmax(probs, axis=-1)             # (N,)
    gate_val = jnp.max(probs, axis=-1)               # (N,)
    onehot = jax.nn.one_hot(exp_idx, e, dtype=jnp.float32)
    cap = max(1, int(np.ceil(n / e * cfg.capacity_factor)))  # static
    pos = jnp.cumsum(onehot, axis=0) * onehot        # 1-based slot / expert
    disp = onehot * (pos <= cap)                     # capacity-capped
    pos0 = jnp.clip(pos - 1, 0, cap - 1).astype(jnp.int32)
    e_local = lyr["we1"].shape[0]
    ep_idx = jax.lax.axis_index("ep")
    disp_l = jax.lax.dynamic_slice_in_dim(disp, ep_idx * e_local,
                                          e_local, axis=1)
    pos_l = jax.lax.dynamic_slice_in_dim(pos0, ep_idx * e_local,
                                         e_local, axis=1)
    # (N, E_local, C): token→(expert, capacity-slot) dispatch tensor
    slot = (jax.nn.one_hot(pos_l, cap, dtype=cfg.dtype)
            * disp_l[..., None].astype(cfg.dtype))
    xe = jnp.einsum("nec,nd->ecd", slot, tokens.astype(cfg.dtype))
    he = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", xe,
                                lyr["we1"].astype(cfg.dtype)))
    oe = jnp.einsum("ecf,efd->ecd", he, lyr["we2"].astype(cfg.dtype))
    out = (jnp.einsum("nec,ecd->nd", slot, oe)
           * gate_val.astype(cfg.dtype)[:, None])
    out = jax.lax.psum(out, "ep")
    # load-balance aux (Switch eq. 4): fraction routed × mean router prob,
    # over the global token set so every device agrees on the value
    f_sum = jax.lax.psum(jnp.sum(onehot, axis=0), ("dp", "sp"))
    p_sum = jax.lax.psum(jnp.sum(probs, axis=0), ("dp", "sp"))
    n_tot = jax.lax.psum(jnp.float32(n), ("dp", "sp"))
    aux = e * jnp.sum((f_sum / n_tot) * (p_sum / n_tot))
    return out.reshape(b, t, d), aux


def _ln(x, scale):
    mu = jnp.mean(x, -1, keepdims=True)
    var = jnp.var(x, -1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-5) * scale


def _forward_local(params, tokens, cfg: StreamFormerConfig):
    """Per-device forward inside shard_map.

    tokens: (B_local, T_local) int32.  Heads and MLP hidden are the local
    tp shard; sequence is the local sp shard (ring attention crosses sp);
    experts are the local ep shard (psum over ep combines).
    """
    sp_idx = jax.lax.axis_index("sp")
    b, t = tokens.shape
    pos = sp_idx * t + jnp.arange(t)
    x = params["embed"][tokens] + params["pos"][pos][None]
    x = x.astype(cfg.dtype)
    aux = jnp.float32(0)
    for lyr in params["layers"]:
        # -- attention (tp shards heads, sp ring over sequence) -------------
        y = _ln(x.astype(jnp.float32), lyr["ln1"]).astype(cfg.dtype)
        qkv = jnp.einsum("btd,dchn->btchn", y,
                         lyr["wqkv"].astype(cfg.dtype))
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if cfg.seq_parallel == "ulysses":
            from .ulysses import ulysses_attention

            seq_attn = ulysses_attention
        elif cfg.seq_parallel == "ring":
            seq_attn = ring_attention
        else:
            raise ValueError(
                f"seq_parallel={cfg.seq_parallel!r}: ring | ulysses")
        attn = jax.vmap(
            lambda qq, kk, vv: seq_attn(qq, kk, vv, "sp",
                                        causal=True))(q, k, v)
        o = jnp.einsum("bthn,hnd->btd", attn, lyr["wo"].astype(cfg.dtype))
        o = jax.lax.psum(o, "tp")  # combine head shards
        x = x + o
        # -- dense MLP (megatron tp) ---------------------------------------
        y = _ln(x.astype(jnp.float32), lyr["ln2"]).astype(cfg.dtype)
        hcore = jax.nn.gelu(jnp.einsum("btd,df->btf", y,
                                       lyr["w1"].astype(cfg.dtype)))
        m = jnp.einsum("btf,fd->btd", hcore, lyr["w2"].astype(cfg.dtype))
        m = jax.lax.psum(m, "tp")
        # -- MoE (switch-routed top-1, experts sharded over ep) ------------
        moe, aux_l = _moe_switch(y, lyr, cfg)
        aux = aux + aux_l
        x = x + m + moe
    x = _ln(x.astype(jnp.float32), params["ln_f"])
    logits = jnp.einsum("btd,dv->btv", x, params["head"])
    return logits, aux / max(1, len(params["layers"]))


def _loss_local(params, tokens, labels, cfg):
    logits, aux = _forward_local(params, tokens, cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    # global mean over (dp, sp)-sharded tokens
    s = jax.lax.psum(jnp.sum(nll), ("dp", "sp"))
    n = jax.lax.psum(nll.size, ("dp", "sp"))
    return s / n + cfg.aux_coef * aux


def make_train_step(mesh: Mesh, cfg: Optional[StreamFormerConfig] = None,
                    seed: int = 0):
    """Build (jitted_step, sharded_params, sharded_opt_state, specs).

    The returned step is ``step(params, opt, tokens, labels) -> (params,
    opt, loss)`` jitted over the mesh; tokens/labels are (B, T) int32
    sharded (dp, sp).
    """
    cfg = cfg or StreamFormerConfig()
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    if cfg.experts % axis_sizes.get("ep", 1):
        raise ValueError("experts must divide ep axis size")
    specs = _param_specs(cfg)
    params = init_params(cfg, seed)

    # Adam state mirrors param sharding
    opt = {"m": jax.tree.map(jnp.zeros_like, params),
           "v": jax.tree.map(jnp.zeros_like, params),
           "step": jnp.zeros((), jnp.int32)}
    opt_specs = {"m": specs, "v": specs, "step": P()}

    def local_step(params, opt, tokens, labels):
        loss, grads = jax.value_and_grad(
            lambda p: _loss_local(p, tokens, labels, cfg))(params)
        # psum gradients over every axis the param is REPLICATED on
        def sync(g, spec):
            used = {ax for part in spec if part
                    for ax in ((part,) if isinstance(part, str) else part)}
            axes = tuple(a for a in ("dp", "sp", "tp", "ep")
                         if a not in used)
            return jax.lax.psum(g, axes) if axes else g
        grads = jax.tree.map(sync, grads, specs,
                             is_leaf=lambda x: isinstance(x, jnp.ndarray))
        # Adam
        step = opt["step"] + 1
        b1, b2, eps = 0.9, 0.999, 1e-8
        m = jax.tree.map(lambda mm, g: b1 * mm + (1 - b1) * g,
                         opt["m"], grads)
        v = jax.tree.map(lambda vv, g: b2 * vv + (1 - b2) * g * g,
                         opt["v"], grads)
        t_f = step.astype(jnp.float32)
        corr = jnp.sqrt(1 - b2 ** t_f) / (1 - b1 ** t_f)
        params = jax.tree.map(
            lambda p, mm, vv: p - cfg.lr * corr * mm /
            (jnp.sqrt(vv) + eps), params, m, v)
        return params, {"m": m, "v": v, "step": step}, loss

    shard_step = shard_map(
        local_step, mesh=mesh,
        in_specs=(specs, opt_specs, P("dp", "sp"), P("dp", "sp")),
        out_specs=(specs, opt_specs, P()),
        check_vma=False)
    jitted = jax.jit(shard_step, donate_argnums=(0, 1))

    def place(tree, spec_tree):
        return jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            tree, spec_tree,
            is_leaf=lambda x: isinstance(x, (jnp.ndarray, np.ndarray)))

    params = place(params, specs)
    opt = place(opt, opt_specs)
    return jitted, params, opt, specs


def make_data_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("dp", "sp"))
