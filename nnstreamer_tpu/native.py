"""ctypes bindings for the native tensorwire library (libnnstw.so).

The native layer mirrors the reference's C hot paths (ORC transform
kernels, converter stride memcpy, sparse codec — see
native/tensorwire/tensorwire.cc for the file-level mapping).  Every entry
point has a numpy fallback so the framework works without the toolchain;
``available()`` reports which path is active.

The library is built on demand (``make -C native``) the first time it's
requested, then cached.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libnnstw.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False
_building: Optional[threading.Thread] = None

# dtype kind codes shared with tensorwire.cc
_KIND = {"float32": 8, "float64": 9}


def _build() -> bool:
    """Build to a process-unique name, then atomically rename into place:
    concurrent builders (pytest -n, parallel pipelines) each produce a
    whole .so and the last rename wins — never a torn file."""
    tmp = f"libnnstw.so.tmp.{os.getpid()}"
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR, f"TARGET={tmp}"],
                       check=True, capture_output=True, timeout=120)
        os.replace(os.path.join(_NATIVE_DIR, tmp), _SO_PATH)
        return True
    except (subprocess.SubprocessError, OSError):
        try:
            os.unlink(os.path.join(_NATIVE_DIR, tmp))
        except OSError:
            pass
        return False


def _load() -> Optional[ctypes.CDLL]:
    """Load libnnstw.so if present; if absent, kick off a BACKGROUND build
    and serve the numpy fallback meanwhile (a first-use build must not
    stall a streaming hot path)."""
    global _lib, _tried, _building
    with _lock:
        if _lib is not None or _tried:
            return _lib
        stale = False
        if os.path.exists(_SO_PATH):
            try:
                so_m = os.path.getmtime(_SO_PATH)
                src_dir = os.path.join(_NATIVE_DIR, "tensorwire")
                stale = any(os.path.getmtime(os.path.join(src_dir, f)) > so_m
                            for f in os.listdir(src_dir))
            except OSError:
                stale = False
        if not os.path.exists(_SO_PATH) or stale:
            if _building is None:
                _building = threading.Thread(target=_build, daemon=True,
                                             name="nnstw-build")
                _building.start()
            if _building.is_alive():
                return None  # fallback while the compile runs
            if not os.path.exists(_SO_PATH):
                _tried = True  # build finished and failed
                return None
            # rebuild finished: fall through and load the fresh .so
        _tried = True
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        if lib.tw_abi_version() not in (1, 2):
            # unknown future ABI: fall back rather than call with wrong
            # signatures (1 = original kernels, 2 = +reader)
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        lib.tw_sparse_count.restype = ctypes.c_size_t
        lib.tw_sparse_count.argtypes = [u8p, ctypes.c_size_t,
                                        ctypes.c_size_t, ctypes.c_int]
        lib.tw_sparse_gather.restype = ctypes.c_size_t
        lib.tw_sparse_gather.argtypes = [u8p, ctypes.c_size_t,
                                         ctypes.c_size_t, ctypes.c_int,
                                         u8p, u32p]
        lib.tw_sparse_scatter.argtypes = [u8p, u32p, ctypes.c_size_t,
                                          ctypes.c_size_t, u8p,
                                          ctypes.c_size_t]
        lib.tw_unstride.argtypes = [u8p, ctypes.c_size_t, u8p,
                                    ctypes.c_size_t, ctypes.c_size_t]
        lib.tw_bgrx_to_rgb.argtypes = [u8p, u8p, ctypes.c_size_t]
        lib.tw_gray_to_rgb.argtypes = [u8p, u8p, ctypes.c_size_t]
        lib.tw_crc32c.restype = ctypes.c_uint32
        lib.tw_crc32c.argtypes = [u8p, ctypes.c_size_t, ctypes.c_uint32]
        _lib = lib
        return _lib


def available() -> bool:
    """Explicit probe: waits for an in-flight background build (hot-path
    callers never come through here — they just get the fallback)."""
    lib = _load()
    if lib is None and _building is not None and _building.is_alive():
        _building.join(timeout=120)
        lib = _load()
    return lib is not None


def _u8(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def sparse_gather(arr: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Return (values, uint32 flat indices) of nonzero elements."""
    flat = np.ascontiguousarray(arr).reshape(-1)
    lib = _load()
    if lib is None:
        idx = np.flatnonzero(flat).astype(np.uint32)
        return flat[idx], idx
    kind = _KIND.get(flat.dtype.name, 0)
    esz = flat.dtype.itemsize
    nnz = lib.tw_sparse_count(_u8(flat.view(np.uint8)), flat.size, esz, kind)
    values = np.empty(nnz, dtype=flat.dtype)
    indices = np.empty(nnz, dtype=np.uint32)
    lib.tw_sparse_gather(_u8(flat.view(np.uint8)), flat.size, esz, kind,
                         _u8(values.view(np.uint8)),
                         indices.ctypes.data_as(
                             ctypes.POINTER(ctypes.c_uint32)))
    return values, indices


def sparse_scatter(values: np.ndarray, indices: np.ndarray,
                   n_elems: int) -> np.ndarray:
    """Dense flat array from (values, indices)."""
    lib = _load()
    dense = np.zeros(n_elems, dtype=values.dtype)
    if lib is None:
        dense[indices] = values
        return dense
    lib.tw_sparse_scatter(_u8(np.ascontiguousarray(values).view(np.uint8)),
                          np.ascontiguousarray(indices).ctypes.data_as(
                              ctypes.POINTER(ctypes.c_uint32)),
                          len(values), values.dtype.itemsize,
                          _u8(dense.view(np.uint8)), n_elems)
    return dense


def bgrx_to_rgb(frame: np.ndarray) -> np.ndarray:
    """(H, W, 4) BGRx → (H, W, 3) RGB."""
    h, w = frame.shape[:2]
    lib = _load()
    if lib is None:
        return frame[..., [2, 1, 0]].copy()
    src = np.ascontiguousarray(frame)
    dst = np.empty((h, w, 3), np.uint8)
    lib.tw_bgrx_to_rgb(_u8(src), _u8(dst), h * w)
    return dst


def gray_to_rgb(frame: np.ndarray) -> np.ndarray:
    """(H, W, 1) GRAY8 → (H, W, 3) RGB."""
    h, w = frame.shape[:2]
    lib = _load()
    src = np.ascontiguousarray(frame)
    if lib is None:
        return np.repeat(src.reshape(h, w, 1), 3, axis=2)
    dst = np.empty((h, w, 3), np.uint8)
    lib.tw_gray_to_rgb(_u8(src), _u8(dst), h * w)
    return dst


def unstride(src: np.ndarray, src_stride: int, row_bytes: int,
             rows: int) -> np.ndarray:
    """Drop per-row padding from a strided image buffer."""
    flat = np.ascontiguousarray(src).reshape(-1).view(np.uint8)
    lib = _load()
    if lib is None:
        out = np.empty(rows * row_bytes, np.uint8)
        for r in range(rows):
            out[r * row_bytes:(r + 1) * row_bytes] = \
                flat[r * src_stride:r * src_stride + row_bytes]
        return out
    dst = np.empty(rows * row_bytes, np.uint8)
    lib.tw_unstride(_u8(flat), src_stride, _u8(dst), row_bytes, rows)
    return dst


_CRC32C_TABLE: Optional[np.ndarray] = None


def _crc32c_table() -> np.ndarray:
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        table = np.empty(256, np.uint32)
        for i in range(256):
            c = i
            for _ in range(8):
                c = (0x82F63B78 ^ (c >> 1)) if (c & 1) else (c >> 1)
            table[i] = c
        _CRC32C_TABLE = table
    return _CRC32C_TABLE


def crc32c_fn():
    """Return a lock-free CRC-32C callable bound to the loaded native lib,
    or None when the lib is unavailable (callers fall back / skip).  The
    per-call path touches no module locks — resolve once, use per frame."""
    lib = _load()
    if lib is None:
        return None

    def _fn(data: bytes, seed: int = 0) -> int:
        arr = np.frombuffer(data, np.uint8)
        return int(lib.tw_crc32c(_u8(arr), len(data), seed))

    return _fn


def crc32c(data: bytes, seed: int = 0) -> int:
    """CRC-32C (Castagnoli) — the SAME polynomial on both paths so mixed
    native/fallback hosts agree on checksums."""
    lib = _load()
    if lib is None:
        table = _crc32c_table()
        c = ~seed & 0xFFFFFFFF
        for b in data:
            c = int(table[(c ^ b) & 0xFF]) ^ (c >> 8)
        return (~c) & 0xFFFFFFFF
    arr = np.frombuffer(data, np.uint8)
    return int(lib.tw_crc32c(_u8(arr), len(data), seed))


# ---------------------------------------------------------------------------
# Native dataset reader (data-loader role: gstdatareposrc.c reimplemented as
# a native IO engine — background pread prefetch ring, bounded memory).
# Python mmap fallback keeps behavior identical without the .so.
# ---------------------------------------------------------------------------

class RepoReader:
    """Sequential frame reader over a binary dataset file.

    ``next_frame()`` returns (global_frame_index, bytes) — the index keeps
    counting across epochs when ``wrap`` — or None at the end of a
    non-wrapping stream.
    """

    def __init__(self, path: str, frame_bytes: int, capacity: int = 8,
                 wrap: bool = False) -> None:
        self.frame_bytes = frame_bytes
        self._native = None
        self._mm = None
        self._served = 0
        self._wrap = wrap
        lib = _load()
        if lib is not None and hasattr(lib, "tw_reader_open"):
            lib.tw_reader_open.restype = ctypes.c_void_p
            lib.tw_reader_open.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                           ctypes.c_int, ctypes.c_int]
            lib.tw_reader_frames.restype = ctypes.c_long
            lib.tw_reader_frames.argtypes = [ctypes.c_void_p]
            lib.tw_reader_next.restype = ctypes.c_long
            lib.tw_reader_next.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8)]
            lib.tw_reader_close.argtypes = [ctypes.c_void_p]
            h = lib.tw_reader_open(path.encode(), frame_bytes,
                                   int(capacity), int(wrap))
            if h:
                self._native = (lib, h)
                self.num_frames = int(lib.tw_reader_frames(h))
                return
        # fallback: mmap (bounded memory too, readahead by the kernel)
        import mmap

        f = open(path, "rb")
        try:
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except ValueError:          # zero-byte file cannot be mapped
            f.close()
            raise ValueError(f"{path}: smaller than one frame") from None
        self._mm = (f, mm)
        self.num_frames = len(mm) // frame_bytes
        if self.num_frames == 0:
            self.close()
            raise ValueError(f"{path}: smaller than one frame")

    @property
    def is_native(self) -> bool:
        return self._native is not None

    def next_frame(self):
        """(global_frame_index, uint8 ndarray) — exactly one copy out of
        the ring/page cache per frame on either path."""
        if self._native is not None:
            lib, h = self._native
            dst = np.empty(self.frame_bytes, np.uint8)
            idx = lib.tw_reader_next(h, _u8(dst))
            if idx == -2:
                raise IOError(f"native reader: IO error at frame "
                              f"{self._served}")
            if idx < 0:
                return None
            self._served += 1
            return int(idx), dst
        if not self._wrap and self._served >= self.num_frames:
            return None
        idx = self._served
        pos = (idx % self.num_frames) * self.frame_bytes
        self._served += 1
        # mm[a:b] copies out of the page cache; frombuffer wraps it
        # zero-copy (a view of the mmap itself would block mm.close())
        return idx, np.frombuffer(self._mm[1][pos:pos + self.frame_bytes],
                                  np.uint8)

    def close(self) -> None:
        if self._native is not None:
            lib, h = self._native
            lib.tw_reader_close(h)
            self._native = None
        if self._mm is not None:
            self._mm[1].close()
            self._mm[0].close()
            self._mm = None
