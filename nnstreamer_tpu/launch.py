"""Command-line pipeline launcher (gst-launch-1.0 role) + element
inspector (gst-inspect-1.0 role).

Usage::

    python -m nnstreamer_tpu.launch "videotestsrc num-buffers=10 ! \
        video/x-raw,format=RGB,width=224,height=224 ! tensor_converter ! \
        tensor_filter framework=xla model=mobilenet_v2 ! \
        tensor_decoder mode=image_labeling ! tensor_sink name=out" \
        [--timeout SECONDS] [--print-sink NAME]

    python -m nnstreamer_tpu.launch --inspect              # all factories
    python -m nnstreamer_tpu.launch --inspect tensor_filter

The reference's entire user surface is gst-launch strings + gst-inspect;
this gives the TPU framework the same front door.
"""

from __future__ import annotations

import argparse
import sys
import time


def inspect(name=None, out=None) -> int:
    """List element factories / one factory's properties
    (gst-inspect-1.0 role: the reference user's discovery tool)."""
    import inspect as _inspect

    out = out or sys.stdout
    from .pipeline.registry import element_factory, list_factories

    if name:
        try:
            cls = element_factory(name)
        except KeyError as e:
            print(f"ERROR: {e}", file=sys.stderr)
            return 1
        doc = _inspect.cleandoc(cls.__doc__) if cls.__doc__ else ""
        print(f"Factory: {name}\n", file=out)
        if doc:
            print(doc + "\n", file=out)
        # element props first, then the universal ones every element
        # inherits (gst-inspect lists inherited GObject props too)
        props = dict(getattr(cls, "PROPERTIES", {}))
        props.update({k: v for k, v in
                      getattr(cls, "UNIVERSAL_PROPERTIES", {}).items()
                      if k not in props})
        if props:
            print("Properties:", file=out)
            for key, spec in sorted(props.items()):
                default, desc = (spec if isinstance(spec, tuple)
                                 else (spec, ""))
                print(f"  {key:<24} default={default!r}  {desc}", file=out)
        aliases = getattr(cls, "REFERENCE_PROP_ALIASES", None)
        if aliases:
            print("Reference-name aliases:", file=out)
            for a, target in sorted(aliases.items()):
                print(f"  {a:<24} -> {target}", file=out)
        return 0
    for fac in sorted(list_factories()):
        cls = element_factory(fac)
        first = (cls.__doc__ or "").strip().partition("\n")[0]
        print(f"{fac:<24} {first}", file=out)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="nns-launch",
                                 description="Run a pipeline description")
    ap.add_argument("pipeline", nargs="?", help="pipeline launch string")
    ap.add_argument("--inspect", nargs="?", const="", default=None,
                    metavar="FACTORY",
                    help="list element factories (or one factory's "
                         "properties) instead of running a pipeline")
    ap.add_argument("--check", action="store_true",
                    help="statically verify the pipeline graph and exit "
                         "without playing: caps dead-ends, deadlock "
                         "cycles, dead branches and scheduler "
                         "misconfigurations are reported with element "
                         "paths (analysis/verify.py); exit 1 on errors")
    ap.add_argument("--jit", action="store_true",
                    help="with --check: also run the static JIT-boundary "
                         "audit (analysis/jitaudit.py) over the package "
                         "— unquantized shapes at jit signatures, "
                         "missing donations, host syncs and tracer "
                         "branches in the jit call graph, unbounded "
                         "cache keys — and print the declared compile "
                         "budgets; a pipeline string is optional "
                         "(audit-only mode); exit 1 on findings")
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("--print-sink", default=None,
                    help="tensor_sink name whose outputs to print")
    ap.add_argument("--quiet", action="store_true")
    ap.add_argument("--stats", action="store_true",
                    help="print the pipeline LATENCY query result at EOS "
                         "(per-element invoke latency contributions)")
    ap.add_argument("--trace", action="store_true",
                    help="record per-element proctime/framerate (GstShark "
                         "tracer role) and print the report at EOS "
                         "(includes the fused segment plan, p50/p95/p99 "
                         "latency percentiles, source→element "
                         "interlatency, and the live metrics snapshot)")
    ap.add_argument("--trace-out", default=None, metavar="FILE",
                    help="write the --trace report as JSON to FILE "
                         "(machine-readable twin of the stderr report; "
                         "implies tracing)")
    ap.add_argument("--timeline", default=None, metavar="FILE",
                    help="record per-buffer timeline spans and write a "
                         "Chrome trace_event JSON to FILE at EOS "
                         "(Perfetto/chrome://tracing renders streaming "
                         "threads, queue handoffs and filter-worker "
                         "overlap; spans harvested from remote "
                         "tensor_query servers merge in under their own "
                         "process row)")
    ap.add_argument("--profile", action="store_true",
                    help="utilization attribution profile: record "
                         "per-buffer spans, decompose every frame's "
                         "end-to-end wall time into wait states "
                         "(source-pacing / queue-wait / admission-wait "
                         "/ serialize / wire / device-invoke / "
                         "reorder-wait / sink — obs/attrib.py), print "
                         "the blame table at EOS and write the profile "
                         "artifacts (profile.json + Chrome trace + "
                         "folded-stacks flamegraph) under "
                         "--profile-out; live nns_mfu / occupancy "
                         "gauges ride the metrics registry")
    ap.add_argument("--profile-out", default="profile", metavar="DIR",
                    help="artifact dir for --profile "
                         "(default: ./profile)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    metavar="PORT",
                    help="serve live Prometheus metrics on "
                         "127.0.0.1:PORT while the pipeline runs "
                         "(GET /metrics; same effect as "
                         "NNS_METRICS_PORT; PORT 0 binds an ephemeral "
                         "port — the chosen port is logged and "
                         "exported as NNS_METRICS_BOUND_PORT)")
    ap.add_argument("--push-metrics", default=None, metavar="HOST:PORT",
                    help="telemetry federation (obs/federation.py): "
                         "push this process's metrics registry to a "
                         "collector as T_METRICS deltas every "
                         "--push-interval seconds, so a fleet of "
                         "worker launches is scraped from ONE "
                         "federated /metrics endpoint")
    ap.add_argument("--push-interval", type=float, default=1.0,
                    metavar="SECONDS",
                    help="metrics push period for --push-metrics "
                         "(default 1.0)")
    ap.add_argument("--top", nargs="?", const=1.0, type=float,
                    default=None, metavar="INTERVAL",
                    help="live nns-top dashboard on stderr while the "
                         "pipeline runs (obs/dashboard.py): "
                         "per-element occupancy, queue depths, bucket "
                         "fill, MFU, shed/admit rates and sustained "
                         "signals, refreshed every INTERVAL seconds "
                         "(default 1.0) from an in-process time-series "
                         "ring")
    ap.add_argument("--fuse", default=None,
                    choices=["interpret", "python", "xla"],
                    help="segment-compiler lowering tier "
                         "(pipeline/schedule.py): 'interpret' = per-pad "
                         "dispatch, 'python' = fused plan_step loops "
                         "(default), 'xla' = whole-segment jitted XLA "
                         "computations with double-buffered device "
                         "pipelining (segments with non-lowerable steps "
                         "fall back to python — --check reports them as "
                         "xla-fallback warnings).  Same as NNS_FUSE="
                         "0|1|xla")
    ap.add_argument("--no-fuse", action="store_true",
                    help="disable the segment compiler: interpreted "
                         "per-pad dispatch (the baseline "
                         "tools/hotpath_bench.py --stage dispatch "
                         "compares against); same as --fuse interpret")
    ap.add_argument("--jax-trace", default=None, metavar="DIR",
                    help="record a device-level JAX/XLA profiler trace "
                         "into DIR (TensorBoard profile format): per-op "
                         "device timeline under the element-granular "
                         "--trace report")
    ap.add_argument("--soak", type=float, default=None, metavar="SECONDS",
                    help="soak mode: run the pipeline for SECONDS and "
                         "treat not-reaching-EOS as success (the soak "
                         "IS the workload); combine with --slo to gate "
                         "the run on burn-rate objectives")
    ap.add_argument("--slo", default=None, metavar="FILE",
                    help="evaluate the run against an SLO spec JSON "
                         "(slo/spec.py; the literal value 'demo' uses "
                         "the built-in demo spec): multi-window "
                         "burn-rate gating over the live metrics "
                         "registry, verdict JSON on stderr at exit, "
                         "exit code 3 on FAIL; breaches dump "
                         "flight-recorder bundles (with the span "
                         "timeline when --timeline is active)")
    ap.add_argument("--slo-out", default="flightrec", metavar="DIR",
                    help="flight-recorder bundle dir for --slo "
                         "breaches (default: ./flightrec)")
    ap.add_argument("--drain-grace", type=float, default=5.0,
                    metavar="SECONDS",
                    help="graceful-drain budget for SIGTERM: on TERM "
                         "the pipeline flips /healthz to draining "
                         "(503), serving elements shed new requests "
                         "with retry-after and finish in-flight "
                         "replies, then the process exits 0 "
                         "(Pipeline.drain)")
    args = ap.parse_args(argv)

    if args.inspect is not None:
        return inspect(args.inspect or args.pipeline)
    if not args.pipeline and not (args.check and args.jit):
        ap.error("pipeline launch string required (or use --inspect)")

    if args.no_fuse:
        args.fuse = "interpret"
    if args.fuse is not None:
        # via the env so every pipeline this process builds — including
        # the --check graph and any serving sub-pipelines — inherits the
        # requested lowering tier
        import os as _os

        _os.environ["NNS_FUSE"] = {"interpret": "0", "python": "1",
                                   "xla": "xla"}[args.fuse]

    from .utils.platform import honor_jax_platforms

    honor_jax_platforms()

    from . import parse_launch

    if args.check:
        rc = check(args.pipeline) if args.pipeline else 0
        if args.jit:
            rc = max(rc, check_jit())
        return rc

    import os as _os

    fleet_role = _os.environ.get("NNS_FLEET_ROLE")
    if fleet_role:
        # fleet membership tag (fleet/pool.py sets NNS_FLEET_ROLE=
        # worker on spawned processes): rides the federated scrape so
        # the nns-top fleet view labels each origin router/worker
        from .obs.metrics import REGISTRY

        REGISTRY.gauge("nns_fleet_role", fn=lambda: 1.0,
                       role=str(fleet_role))

    t0 = time.time()
    slo_failed = False
    try:
        p = parse_launch(args.pipeline)   # tier from NNS_FUSE (set above)
        if args.print_sink:
            sink = p.get(args.print_sink)
            sink.connect("new-data", _print_buffer)
        if args.stats:
            for el in p.elements:
                if hasattr(el, "latency_report"):
                    el.latency_report = True
        if args.metrics_port is not None:
            from .obs.httpd import start_metrics_server

            start_metrics_server(args.metrics_port)
        want_trace = (args.trace or args.trace_out or args.timeline
                      or args.profile)
        tracer = (p.enable_tracing(
                      spans=bool(args.timeline or args.profile))
                  if want_trace else None)
        profiler = None
        if args.profile:
            from .obs.profile import Profiler

            profiler = Profiler(p, tracer=tracer)
        plans = None
        metrics = None
        prof_report = None
        slo_monitor = slo_evaluator = None
        if args.slo:
            from .slo import Evaluator, FlightRecorder, SLOMonitor
            from .slo.spec import load_spec

            spec = load_spec(None if args.slo == "demo" else args.slo,
                             duration_s=args.soak or 60.0)
            recorder = FlightRecorder(args.slo_out, tracer=tracer)
            slo_evaluator = Evaluator(spec,
                                      on_breach=recorder.on_breach)
            slo_evaluator.on_tick = recorder.record
            slo_monitor = SLOMonitor(slo_evaluator)
        if args.jax_trace:
            import jax

            jax.profiler.start_trace(args.jax_trace)
        publisher = None
        if args.push_metrics:
            from .obs.federation import MetricsPublisher

            host, _, port = str(args.push_metrics).rpartition(":")
            if not port.isdigit():
                ap.error(f"--push-metrics {args.push_metrics!r}: "
                         "want HOST:PORT")
            from .obs.httpd import health_report

            publisher = MetricsPublisher(
                host or "127.0.0.1", int(port),
                interval_s=args.push_interval,
                health_fn=lambda: health_report()["state"])
        top_loop = top_sampler = top_ring = None
        if args.top is not None:
            from .obs.dashboard import RingSource, TopLoop
            from .obs.timeseries import RingSampler, TimeSeriesRing

            top_ring = TimeSeriesRing(interval_s=max(0.1, args.top))
            top_sampler = RingSampler(top_ring)
            # in-place redraw only on a real terminal: piped/captured
            # stderr gets plain appended frames, not clear-screen
            # escapes clobbering the log
            top_loop = TopLoop(RingSource(top_ring, label="launch"),
                               interval_s=max(0.1, args.top),
                               out=sys.stderr,
                               ansi=sys.stderr.isatty())
        _install_sigterm_drain(p, args.drain_grace)
        try:
            p.play()
            if slo_monitor is not None:
                # breach bundles grow per-session token timelines when
                # a tensor_llm element is recording (token-obs=1; the
                # recorder exists at play, the element's plane does not
                # until start() — wire it here)
                recorder.session_obs = next(
                    (el._tok_obs for el in p.elements
                     if getattr(el, "_tok_obs", None) is not None),
                    None)
                slo_monitor.start()
            if publisher is not None:
                publisher.start()
            if top_loop is not None:
                top_sampler.start()
                top_loop.start()
            if args.soak is not None:
                try:
                    p.wait(args.soak)
                except TimeoutError:
                    pass    # soak: surviving until the deadline IS the
                    #         success condition; the SLO verdict judges
            else:
                p.wait(args.timeout)
            if tracer is not None and p.planner is not None:
                plans = p.planner.plans()   # snapshot before stop() drops it
            if tracer is not None:
                # snapshot the LIVE registry before stop(): element
                # teardown unregisters the queue/filter gauges, and the
                # report should show the running pipeline's state
                from .obs.metrics import REGISTRY

                metrics = REGISTRY.report()
            if profiler is not None:
                # report BEFORE stop(): the device/occupancy gauges
                # (nns_mfu, nns_device_mem_bytes) unregister at element
                # teardown and the profile must carry their live values
                prof_report = profiler.report(metrics_report=metrics)
            if args.stats:
                total, per = p.query_latency()
                for name, ns in sorted(per.items()):
                    print(f"latency {name}: {ns / 1e6:.3f} ms",
                          file=sys.stderr)
                print(f"latency total: {total / 1e6:.3f} ms",
                      file=sys.stderr)
                for el in p.elements:
                    fw = getattr(el, "fw", None)
                    executor = getattr(fw, "executor", "")
                    if executor:
                        reason = getattr(fw, "fallback_reason", "")
                        note = f" (device path blocked by: {reason})" \
                            if reason else ""
                        print(f"executor {el.name}: {executor}{note}",
                              file=sys.stderr)
        finally:
            if top_loop is not None:
                top_loop.stop()
                top_sampler.stop(final_capture=False)
                top_ring.close()
            if publisher is not None:
                # final push BEFORE element teardown: the collector's
                # last view of this worker must include the run's
                # closing counters, not a half-torn registry
                publisher.stop(final_push=True)
            if slo_monitor is not None:
                # final tick BEFORE element teardown: the verdict must
                # see the run's last requests while gauges are live
                slo_monitor.stop(final_tick=True)
            p.stop()
            if slo_evaluator is not None:
                import json as _json

                verdict = slo_evaluator.verdict()
                slo_failed = not verdict["pass"]
                print(_json.dumps(verdict, indent=2), file=sys.stderr)
            if args.jax_trace:
                import jax

                jax.profiler.stop_trace()
                print(f"jax trace written to {args.jax_trace}",
                      file=sys.stderr)
            if tracer is not None:
                # print even on timeout/error: bounded profiling of a
                # live pipeline is exactly the --trace --timeout use case
                import json as _json

                report = {"trace": tracer.report()}
                if plans is not None:
                    # which element runs the scheduler fused, and where
                    # each fused segment pushes (its thread boundary)
                    report["plan"] = plans
                resilience = tracer.resilience_report()
                if resilience:
                    # retry/failure/breaker/heartbeat counters from the
                    # query layer (query/resilience.py), this run only
                    report["resilience"] = resilience
                if metrics is None:   # error/timeout path: post-stop view
                    from .obs.metrics import REGISTRY

                    metrics = REGISTRY.report()
                if metrics:
                    # the live-endpoint view embedded in the report:
                    # queue depths, pool occupancy, filter scheduler
                    # state, per-element latency summaries
                    report["metrics"] = metrics
                if profiler is not None:
                    import os as _os

                    _os.makedirs(args.profile_out, exist_ok=True)
                    if prof_report is None:   # error/timeout path
                        prof_report = profiler.report(
                            metrics_report=metrics)
                    report["attribution"] = prof_report["blame"]
                    print(profiler.blame_table(prof_report),
                          file=sys.stderr)
                    prof_path = _os.path.join(args.profile_out,
                                              "profile.json")
                    with open(prof_path, "w", encoding="utf-8") as fh:
                        _json.dump({"pipeline": args.pipeline,
                                    "profile": prof_report,
                                    "trace": report["trace"]},
                                   fh, indent=2)
                    profiler.export_chrome(_os.path.join(
                        args.profile_out, "trace.json"))
                    profiler.export_folded(_os.path.join(
                        args.profile_out, "flame.folded"))
                    profiler.close()
                    print(f"profile written to {args.profile_out}/"
                          "{profile.json, trace.json, flame.folded}",
                          file=sys.stderr)
                if args.timeline:
                    tracer.export_chrome(args.timeline)
                    print(f"timeline written to {args.timeline}",
                          file=sys.stderr)
                if args.trace_out:
                    with open(args.trace_out, "w",
                              encoding="utf-8") as fh:
                        _json.dump(report, fh, indent=2)
                if args.trace or not (args.trace_out or args.timeline
                                      or args.profile):
                    print(_json.dumps(report, indent=2),
                          file=sys.stderr)
    except Exception as exc:  # noqa: BLE001
        print(f"ERROR: {exc}", file=sys.stderr)
        return 1
    if not args.quiet:
        print(f"pipeline finished in {time.time() - t0:.2f}s",
              file=sys.stderr)
    return 3 if slo_failed else 0


def _install_sigterm_drain(pipeline, grace_s: float) -> None:
    """SIGTERM → graceful drain: the orchestrator's stop signal flips
    the pipeline to ``draining`` (healthz 503 routes the load balancer
    away), serving elements answer new requests with explicit sheds
    while in-flight replies finish, then the process exits 0 — clients
    see retry-after hints, never mid-reply connection resets."""
    import signal

    fired = []

    def _on_term(signum, frame):
        if fired:           # re-delivery while the first drain unwinds
            raise SystemExit(0)
        fired.append(signum)
        print(f"SIGTERM: draining pipeline (grace {grace_s:.1f}s)...",
              file=sys.stderr)
        try:
            pipeline.drain(grace_s)
        finally:
            raise SystemExit(0)

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:
        pass    # not the main thread (embedded use): caller owns signals


def check(description: str, out=None) -> int:
    """``--check``: build the pipeline graph and run the static verifier
    WITHOUT playing — no element starts, no thread spawns, no buffer
    flows.  Prints every finding (errors first, element-path
    diagnostics) plus the streaming-thread structure; returns 1 when
    the graph has error-severity findings, else 0."""
    out = out or sys.stderr
    from .analysis.verify import thread_segments, verify_pipeline
    from .pipeline.parse import ParseError

    from . import parse_launch

    import os as _os

    if str(description).endswith(".json") \
            and _os.path.exists(description):
        # fleet config document (fleet/config.py), not a launch
        # string: run the fleet verifier — router-with-zero-workers,
        # min>max, drain-grace-vs-bucket-window are named errors here
        return check_fleet(description, out=out)
    try:
        p = parse_launch(description)
    except ParseError as exc:
        print(f"check: FAIL (parse): {exc}", file=out)
        return 1
    findings = verify_pipeline(p)
    for f in findings:
        print(f"check: {f}", file=out)
    for seg in thread_segments(p):
        members = " -> ".join(seg["elements"]) or "(boundary only)"
        print(f"check: thread {seg['thread']}: {members}", file=out)
    errors = [f for f in findings if f.severity == "error"]
    if errors:
        print(f"check: FAIL ({len(errors)} error(s))", file=out)
        return 1
    print("check: OK", file=out)
    return 0


def check_jit(out=None) -> int:
    """``--check --jit``: the static JIT-boundary audit
    (analysis/jitaudit.py) over the installed package, plus the
    declared compile budgets — the same pass ``tools/nnsjit.py`` runs,
    surfaced through the launcher's front door."""
    import os as _os

    out = out or sys.stderr
    from .analysis.jitaudit import audit_paths
    from .analysis import compileledger

    pkg = _os.path.dirname(_os.path.abspath(__file__))
    findings = audit_paths([pkg], root=_os.path.dirname(pkg))
    for f in findings:
        print(f"check: jit: {f}", file=out)
    try:
        # importing the engine registers its @compile_budget sites
        from .llm import engine as _engine  # noqa: F401
    except Exception:
        pass
    for site, n in sorted(compileledger.budgets().items()):
        print(f"check: jit: budget {site} = {n} executables", file=out)
    if findings:
        print(f"check: jit: FAIL ({len(findings)} finding(s))", file=out)
        return 1
    print("check: jit: OK", file=out)
    return 0


def check_fleet(path: str, out=None) -> int:
    """``--check`` on a fleet config JSON: static validation without
    spawning anything (analysis/verify.py verify_fleet_config)."""
    out = out or sys.stderr
    from .analysis.verify import verify_fleet_config

    findings = verify_fleet_config(path)
    for f in findings:
        print(f"check: {f}", file=out)
    errors = [f for f in findings if f.severity == "error"]
    if errors:
        print(f"check: FAIL ({len(errors)} error(s))", file=out)
        return 1
    print("check: OK", file=out)
    return 0


def _print_buffer(buf) -> None:
    desc = buf.extra.get("label")
    if desc is None:
        desc = ", ".join(str(getattr(t, "shape", "?")) for t in buf.tensors)
    print(f"pts={buf.pts} {desc}")


if __name__ == "__main__":
    raise SystemExit(main())
