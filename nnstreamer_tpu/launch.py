"""Command-line pipeline launcher (gst-launch-1.0 role).

Usage::

    python -m nnstreamer_tpu.launch "videotestsrc num-buffers=10 ! \
        video/x-raw,format=RGB,width=224,height=224 ! tensor_converter ! \
        tensor_filter framework=xla model=mobilenet_v2 ! \
        tensor_decoder mode=image_labeling ! tensor_sink name=out" \
        [--timeout SECONDS] [--print-sink NAME]

The reference's entire user surface is gst-launch strings; this gives the
TPU framework the same front door.
"""

from __future__ import annotations

import argparse
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="nns-launch",
                                 description="Run a pipeline description")
    ap.add_argument("pipeline", help="pipeline launch string")
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("--print-sink", default=None,
                    help="tensor_sink name whose outputs to print")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    from . import parse_launch

    t0 = time.time()
    try:
        p = parse_launch(args.pipeline)
        if args.print_sink:
            sink = p.get(args.print_sink)
            sink.connect("new-data", _print_buffer)
        p.run(timeout=args.timeout)
    except Exception as exc:  # noqa: BLE001
        print(f"ERROR: {exc}", file=sys.stderr)
        return 1
    if not args.quiet:
        print(f"pipeline finished in {time.time() - t0:.2f}s",
              file=sys.stderr)
    return 0


def _print_buffer(buf) -> None:
    desc = buf.extra.get("label")
    if desc is None:
        desc = ", ".join(str(getattr(t, "shape", "?")) for t in buf.tensors)
    print(f"pts={buf.pts} {desc}")


if __name__ == "__main__":
    raise SystemExit(main())
