"""Horizontal serving fleet: router + managed worker pool + autoscaler.

ROADMAP item 3 composed from the pieces earlier PRs left waiting:

- :mod:`fleet.ring` — seeded consistent-hash placement (model identity
  → stable worker replica set; membership changes move ~1/N of keys);
- :mod:`fleet.router` — one client endpoint fanning connections across
  :class:`~nnstreamer_tpu.query.server.QueryServer` workers over PR 1
  :class:`~nnstreamer_tpu.query.client.FailoverConnection` backend
  legs (hot ``dest-hosts`` updates = storm-free rebalance; T_SHED/QoS
  pass through untouched);
- :mod:`fleet.pool` — spawns ``launch.py`` workers federating into a
  PR 13 collector, restarts crashes with backoff, scales down via the
  PR 7 SIGTERM drain (route-away first);
- :mod:`fleet.autoscaler` — PR 13 sustained signals closed into a
  control loop with cooldowns and hysteresis;
- :mod:`fleet.config` — the JSON config document +
  ``launch.py --check fleet.json`` static validation.

Gated end to end by ``tools/soak.py --fleet`` (multi-process soak:
worker kill mid-run with zero client errors, autoscale up on sustained
load, drain on idle — SOAK_fleet artifacts).
"""

from .autoscaler import Autoscaler, default_autoscaler_signals
from .config import AutoscalerConfig, FleetConfig, load_fleet_config
from .pool import (FleetLoop, ManagedWorker, WorkerPool, free_port,
                   launch_spawn_fn)
from .ring import ConsistentHashRing
from .router import TensorQueryRouter

__all__ = [
    "Autoscaler", "AutoscalerConfig", "ConsistentHashRing",
    "FleetConfig", "FleetLoop", "ManagedWorker", "TensorQueryRouter",
    "WorkerPool", "default_autoscaler_signals", "free_port",
    "launch_spawn_fn", "load_fleet_config",
]
