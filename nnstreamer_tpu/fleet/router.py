"""``tensor_query_router``: one endpoint in front of a worker fleet.

Clients speak the ordinary query protocol (query/protocol.py) to ONE
address; the router terminates each client connection and forwards its
frames to a :class:`~nnstreamer_tpu.query.server.QueryServer` worker
process picked by consistent hash (fleet/ring.py) over the client's
negotiated *model identity* (a ``model=<name>`` token in the T_HELLO
payload; clients that declare none get a per-connection spread key, so
anonymous traffic balances while model-tagged traffic concentrates —
PR 9's per-model buckets stay dense on few workers).

The backend leg of every client is a PR 1
:class:`~nnstreamer_tpu.query.client.FailoverConnection` whose
``dest-hosts`` list is the key's ring candidate set in preference
order.  That one choice buys the whole resilience story for free:

- a worker killed mid-query is a transport failure → the failover path
  retries the frame on the next candidate inside the same request
  budget — the client sees a slightly slower reply, never an error;
- a draining worker answers ``T_SHED`` → the failover path rotates to
  a healthy candidate immediately (shed-is-liveness, PR 7) and only
  when EVERY candidate sheds does the shed pass through to the client,
  retry-after intact — T_SHED/QoS semantics are end-to-end, the router
  adds no policy of its own;
- membership changes (pool spawn/drain/crash) call
  :meth:`FailoverConnection.set_endpoints` on the live clients whose
  candidate set changed — the hot-update path keeps the active backend
  socket when it is still a candidate, so a membership change moves
  the minimal key range with zero reconnect storm.

QoS passes through untouched: the client's ``qos=`` declaration is
re-announced on the backend leg, so the WORKER's admission control
(query/overload.py) stays the only shed decider.
"""

from __future__ import annotations

import socket
import threading
from typing import Dict, List, Optional, Tuple

from ..analysis.sanitizer import make_lock
from ..obs.clock import wall_us
from ..obs.metrics import REGISTRY, Gauge
from ..obs.span import TraceContext
from ..query.client import FailoverConnection
from ..query.overload import ShedError, qos_of_class
from ..query.protocol import (Message, T_BYE, T_DATA, T_HELLO, T_METRICS,
                              T_PING, T_PONG, T_REPLY, T_SHED, T_TRACE,
                              decode_tensors, parse_hello_tokens,
                              recv_msg, send_msg, send_tensors,
                              shutdown_close)
from ..query.resilience import CircuitOpenError, RetryPolicy
from ..tensor.buffer import TensorBuffer, default_pool
from .ring import ConsistentHashRing


class _Worker:
    __slots__ = ("key", "endpoint", "draining", "gauges")

    def __init__(self, key: str, endpoint: Tuple[str, int]) -> None:
        self.key = key
        self.endpoint = endpoint
        self.draining = False
        self.gauges: list = []


class _Routed:
    """One client connection's routing state."""

    __slots__ = ("cid", "conn", "slock", "fc", "key", "model", "qos")

    def __init__(self, cid: int, conn: socket.socket, slock) -> None:
        self.cid = cid
        self.conn = conn
        self.slock = slock
        self.fc: Optional[FailoverConnection] = None
        self.key = ""
        self.model = ""
        self.qos: Optional[str] = None


class TensorQueryRouter:
    """Front-end router: accept clients, forward per-frame to the
    consistent-hash-chosen worker, answer with the worker's reply.

    Membership is driven from outside (fleet/pool.py callbacks or
    direct :meth:`add_worker` / :meth:`mark_draining` /
    :meth:`remove_worker` calls); the router owns only placement and
    per-client forwarding state.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 replicas: int = 2, timeout: float = 10.0,
                 max_retries: int = 3,
                 breaker_failures: int = 5,
                 breaker_cooldown: float = 10.0,
                 ring_seed: str = "nns-fleet",
                 ring_vnodes: int = 64,
                 collector=None) -> None:
        self.host = host
        self.replicas = max(0, int(replicas))
        self.timeout = float(timeout)
        self.max_retries = int(max_retries)
        self.breaker_failures = int(breaker_failures)
        self.breaker_cooldown = float(breaker_cooldown)
        self.ring = ConsistentHashRing(vnodes=ring_vnodes, seed=ring_seed)
        #: telemetry collector (obs/federation.py): workers pushing
        #: T_METRICS through the router's endpoint merge here, exactly
        #: like the QueryServer piggyback.  Unattached: pushes drop.
        self.collector = collector
        self._workers: Dict[str, _Worker] = {}
        self._clients: Dict[int, _Routed] = {}
        self._next_cid = 1
        self._lock = make_lock("fleet.router")
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(64)
        labels = {"port": str(self.port)}
        self._gauges = [
            REGISTRY.register(Gauge("nns_fleet_role",
                                    {**labels, "role": "router"},
                                    fn=lambda: 1.0)),
            REGISTRY.register(Gauge("nns_fleet_router_clients",
                                    dict(labels),
                                    fn=lambda: len(self._clients))),
            REGISTRY.register(Gauge("nns_fleet_workers", dict(labels),
                                    fn=lambda: len(self._workers))),
        ]
        self._m_accepted = REGISTRY.counter(
            "nns_fleet_accepted_total", **labels)
        self._m_rebalanced = REGISTRY.counter(
            "nns_fleet_rebalanced_total", **labels)
        self._m_forwarded = REGISTRY.counter(
            "nns_fleet_forwarded_total", **labels)
        self._m_sheds = REGISTRY.counter(
            "nns_fleet_router_sheds_total", **labels)
        self._m_errors = REGISTRY.counter(
            "nns_fleet_router_errors_total", **labels)
        #: unregistered at close(): each router instance labels its
        #: series with its ephemeral port, so abandoned counters would
        #: grow the registry once per router ever built in the process
        #: (the bench gate builds one per measurement attempt)
        self._counters = [self._m_accepted, self._m_rebalanced,
                          self._m_forwarded, self._m_sheds,
                          self._m_errors]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="fleet-router")
        self._accept_thread.start()

    # -- membership ----------------------------------------------------------
    @staticmethod
    def worker_key(host: str, port: int) -> str:
        return f"{host}:{port}"

    def add_worker(self, host: str, port: int) -> str:
        """Join a worker; live clients whose candidate set now includes
        it pick it up via the hot endpoint update (minimal movement:
        only keys on the new member's arcs change owners)."""
        key = self.worker_key(host, port)
        with self._lock:
            if key in self._workers:
                w = self._workers[key]
                if w.draining:     # resurrected (crash-restart reusing
                    w.draining = False   # the port): back in rotation
                    self.ring.add(key)
                    self._rebalance_locked()
                return key
            w = _Worker(key, (host, int(port)))
            w.gauges = [
                REGISTRY.register(Gauge(
                    "nns_fleet_routed_connections",
                    {"port": str(self.port), "worker": key},
                    fn=lambda k=key: self._routed_count(k))),
                REGISTRY.register(Gauge(
                    "nns_fleet_worker_draining",
                    {"port": str(self.port), "worker": key},
                    fn=lambda k=key: 1.0 if (
                        k in self._workers
                        and self._workers[k].draining) else 0.0)),
            ]
            self._workers[key] = w
            self.ring.add(key)
            self._rebalance_locked()
        return key

    def mark_draining(self, key: str) -> None:
        """Scale-down step 1 (BEFORE the worker gets SIGTERM): leave
        the ring so no new connection routes here, and move live
        clients off via the failover hot update — by the time the
        worker starts shedding, the router has already routed away."""
        with self._lock:
            w = self._workers.get(key)
            if w is None or w.draining:
                return
            w.draining = True
            self.ring.remove(key)
            self._rebalance_locked()

    def remove_worker(self, key: str) -> None:
        with self._lock:
            w = self._workers.pop(key, None)
            if w is None:
                return
            self.ring.remove(key)
            for g in w.gauges:
                REGISTRY.unregister(g)
            self._rebalance_locked()

    def workers(self) -> List[Dict[str, object]]:
        """Membership snapshot (dashboard / soak verdict rows)."""
        with self._lock:
            return [{"worker": w.key, "draining": w.draining,
                     "routed": self._routed_count(w.key)}
                    for w in self._workers.values()]

    def _routed_count(self, key: str) -> int:
        # lock-free scrape read over the clients' _active_key mirrors
        # (the same deliberate choice as FailoverConnection.degraded():
        # a torn read costs one off-by-one sample, not a scrape stalled
        # behind a seconds-long backend dial)
        return sum(1 for rc in list(self._clients.values())
                   if rc.fc is not None and rc.fc._active_key == key)

    # -- placement -----------------------------------------------------------
    def _candidates_locked(self, key: str) -> List[Tuple[str, int]]:
        n = self.replicas or len(self.ring)
        cands = self.ring.lookup_n(key, max(1, n))
        eps = [self._workers[k].endpoint for k in cands
               if k in self._workers and not self._workers[k].draining]
        if not eps:
            # every ring candidate gone mid-change: any live worker
            # beats refusing (the ring re-converges on the next
            # membership event)
            eps = [w.endpoint for _k, w in sorted(self._workers.items())
                   if not w.draining]
        return eps

    def _rebalance_locked(self) -> None:
        for rc in self._clients.values():
            if rc.fc is None:
                continue
            eps = self._candidates_locked(rc.key)
            if eps and list(rc.fc.endpoints) != eps:
                rc.fc.set_endpoints(eps)
                self._m_rebalanced.inc()

    def _bind_backend(self, rc: _Routed) -> None:
        """Create the client's backend failover leg (ring candidates in
        preference order).  ``shed_passthrough``: with no alternate to
        absorb a shed the router must FORWARD it immediately — sleeping
        out the retry-after here would turn an explicit, fast shed into
        opaque added latency inside the client's budget."""
        with self._lock:
            eps = self._candidates_locked(rc.key)
        if not eps:
            raise ConnectionError("no workers in the fleet")
        rc.fc = FailoverConnection(
            eps, timeout=self.timeout, max_retries=self.max_retries,
            retry=RetryPolicy(max_attempts=max(1, self.max_retries),
                              base_delay=0.05, max_delay=0.5),
            breaker_failures=self.breaker_failures,
            breaker_cooldown=self.breaker_cooldown,
            name=f"router-{rc.cid}", qos=rc.qos,
            shed_passthrough=True)
        rc.fc.connect()

    # -- wire ----------------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            with self._lock:
                cid = self._next_cid
                self._next_cid += 1
                rc = _Routed(cid, conn, make_lock("query.send"))
                self._clients[cid] = rc
            self._m_accepted.inc()
            threading.Thread(target=self._client_loop, args=(rc,),
                             daemon=True,
                             name=f"fleet-route-{cid}").start()

    def _client_loop(self, rc: _Routed) -> None:
        pool = default_pool()
        conn = rc.conn
        try:
            while not self._stop.is_set():
                try:
                    msg = recv_msg(conn, pool=pool)
                except TimeoutError:
                    continue
                except ValueError:   # bad magic / CRC: drop the client
                    break
                if msg is None or msg.type == T_BYE:
                    break
                if msg.type == T_HELLO:
                    self._on_hello(rc, msg)
                elif msg.type == T_PING:
                    # answered locally: liveness of the ENDPOINT is the
                    # router's to prove — heartbeats must not stall
                    # behind a backend dial
                    with rc.slock:
                        send_msg(conn, Message(T_PONG, client_id=rc.cid,
                                               seq=msg.seq,
                                               epoch_us=wall_us(),
                                               payload=msg.payload))
                elif msg.type == T_METRICS:
                    collector = self.collector
                    if collector is not None:
                        collector.ingest(bytes(msg.payload or b""))
                elif msg.type == T_DATA:
                    if not self._on_data(rc, msg):
                        break
        except OSError:
            pass
        finally:
            with self._lock:
                self._clients.pop(rc.cid, None)
            if rc.fc is not None:
                rc.fc.close(send_bye=False)
            shutdown_close(conn)

    def _on_hello(self, rc: _Routed, msg: Message) -> None:
        tokens = parse_hello_tokens(msg.payload)
        qos = qos_of_class(tokens.get("qos"))
        if qos is not None:
            rc.qos = qos
        model = tokens.get("model", rc.model)
        # model identity keys the ring; anonymous connections spread by
        # connection id (consistent placement, no accidental pile-up of
        # every untagged client on one worker)
        rekey = model != rc.model and rc.fc is not None
        rc.model = model
        rc.key = model or f"conn:{rc.cid}"
        caps = ""
        if rc.fc is None:
            try:
                self._bind_backend(rc)
            except (ConnectionError, CircuitOpenError, OSError):
                rc.fc = None   # lazy: first DATA retries the dial
        else:
            if qos is not None:
                rc.fc.set_qos(qos)
            if rekey:
                # re-HELLO with a DIFFERENT model: the backend leg must
                # follow the new key's candidate set now, not at the
                # next unrelated membership event — otherwise this
                # stream keeps diluting the old model's buckets
                with self._lock:
                    eps = self._candidates_locked(rc.key)
                if eps and list(rc.fc.endpoints) != eps:
                    rc.fc.set_endpoints(eps)
                    self._m_rebalanced.inc()
        if rc.fc is not None:
            # the worker's caps answer lands async on the backend
            # reader — wait briefly so the client's handshake carries
            # the real serving caps, not an empty racing read
            caps = rc.fc.wait_server_caps(
                min(2.0, self.timeout)) or ""
        with rc.slock:
            send_msg(rc.conn, Message(T_HELLO, client_id=rc.cid,
                                      payload=caps.encode()))

    def _on_data(self, rc: _Routed, msg: Message) -> bool:
        """Forward one frame; False drops the client connection (the
        honest signal when no backend can be reached — a synthetic shed
        would disguise a dead fleet as a protecting one)."""
        seq = msg.seq
        ctx = TraceContext(msg.trace_id, msg.span_id, msg.origin_us)
        if rc.fc is None:
            rc.key = rc.key or f"conn:{rc.cid}"
            try:
                self._bind_backend(rc)
            except (ConnectionError, CircuitOpenError, OSError):
                self._m_errors.inc()
                return False
        buf = TensorBuffer(tensors=decode_tensors(msg.payload),
                           pts=msg.pts, lease=msg.lease)
        if msg.trace_id:
            buf.extra["nns_trace"] = ctx
        try:
            out = rc.fc.query(buf)
        except ShedError as exc:
            # T_SHED passthrough: every candidate shed (fleet-wide
            # overload or drain) — forward the worker's own verdict,
            # retry-after intact
            self._m_sheds.inc()
            with rc.slock:
                send_msg(rc.conn, Message(
                    T_SHED, client_id=rc.cid, seq=seq,
                    epoch_us=wall_us(),
                    payload=str(int(exc.retry_after_s * 1000)).encode()))
            return True
        except (CircuitOpenError, ConnectionError, TimeoutError,
                OSError):
            self._m_errors.inc()
            return False
        if out is None:
            self._m_errors.inc()
            return False
        self._m_forwarded.inc()
        trace_batches = (rc.fc.drain_remote_traces()
                         if msg.trace_id else ())
        with rc.slock:
            send_tensors(rc.conn, T_REPLY, out, client_id=rc.cid,
                         seq=seq, pts=out.pts or 0, epoch_us=wall_us(),
                         trace_id=ctx.trace_id, span_id=ctx.span_id,
                         origin_us=ctx.origin_us)
            for raw, _off, _key in trace_batches:
                # worker span piggyback rides through: the client's
                # tracer merges the serving process under its timeline
                # exactly as if it had dialed the worker directly
                send_msg(rc.conn, Message(T_TRACE, client_id=rc.cid,
                                          trace_id=ctx.trace_id,
                                          epoch_us=wall_us(),
                                          payload=raw))
        return True

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        self._stop.set()
        shutdown_close(self._sock)
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
            workers = list(self._workers.values())
            self._workers.clear()
            for g in self._gauges:
                REGISTRY.unregister(g)
            self._gauges = []
            for c in self._counters:
                REGISTRY.unregister(c)
            self._counters = []
            for w in workers:
                for g in w.gauges:
                    REGISTRY.unregister(g)
        for rc in clients:
            if rc.fc is not None:
                rc.fc.close(send_bye=False)
            shutdown_close(rc.conn)
