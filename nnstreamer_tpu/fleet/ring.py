"""Seeded consistent-hash ring: the fleet router's placement function.

Why consistent hashing (and not round-robin or least-connections alone):
the PR 9 cross-stream batcher gets its win from per-model buckets being
DENSE — frames of one model coalescing into full tiles on one device.
A router that sprays a model's connections uniformly across N workers
splits that model's arrival stream N ways and every worker's bucket
runs at 1/N fill.  Hashing the *model identity* onto a ring instead
concentrates each model's connections on a small, stable replica set of
workers, and — the property this structure exists for — a membership
change (worker spawned, drained, crashed) moves only the keys whose arc
the change touches: ~1/N of the key space, never a full reshuffle that
would cold-start every bucket in the fleet at once.

Determinism is part of the contract: the ring hashes with keyed
``blake2b`` (not Python's per-process-salted ``hash()``), so every
process that builds a ring from the same member set — the router, a
standby router, a test asserting placement — computes the SAME
placement, regardless of member insertion order.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, Iterable, List, Optional, Tuple

#: virtual nodes per member: enough that per-member arc variance stays
#: small (the "moves <= ~1/N" property test bounds the observed
#: movement at 2/N with this default), few enough that membership
#: changes stay O(vnodes log ring)
DEFAULT_VNODES = 64


class ConsistentHashRing:
    """Hash ring over string members with virtual nodes.

    Not thread-safe by itself — the router serializes membership
    changes under its own lock and ``lookup`` runs on an immutable
    snapshot (``_points`` is rebuilt, never mutated in place, so a
    racing reader sees either the old or the new list, both valid).
    """

    def __init__(self, members: Iterable[str] = (),
                 vnodes: int = DEFAULT_VNODES,
                 seed: str = "nns-fleet") -> None:
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = int(vnodes)
        # blake2b 'key' keeps the ring deterministic across processes
        # AND lets two independent fleets (distinct seeds) disagree on
        # placement so a misdirected client cannot collide by accident
        self._seed = str(seed).encode("utf-8")[:64]
        self._members: Dict[str, List[int]] = {}
        #: sorted (position, member) pairs — rebuilt on change
        self._points: List[Tuple[int, str]] = []
        for m in members:
            self.add(m)

    # -- hashing -------------------------------------------------------------
    def _hash(self, data: str) -> int:
        digest = hashlib.blake2b(data.encode("utf-8"), digest_size=8,
                                 key=self._seed).digest()
        return int.from_bytes(digest, "big")

    # -- membership ----------------------------------------------------------
    @property
    def members(self) -> List[str]:
        return sorted(self._members)

    def __len__(self) -> int:
        return len(self._members)

    def __contains__(self, member: str) -> bool:
        return member in self._members

    def add(self, member: str) -> bool:
        """Add ``member``; False when already present."""
        member = str(member)
        if member in self._members:
            return False
        self._members[member] = [
            self._hash(f"{member}#{i}") for i in range(self.vnodes)]
        self._rebuild()
        return True

    def remove(self, member: str) -> bool:
        if self._members.pop(str(member), None) is None:
            return False
        self._rebuild()
        return True

    def _rebuild(self) -> None:
        points = [(pos, m) for m, positions in self._members.items()
                  for pos in positions]
        points.sort()
        self._points = points

    # -- lookup --------------------------------------------------------------
    def lookup(self, key: str) -> Optional[str]:
        """Member owning ``key`` (first point clockwise), or None on an
        empty ring."""
        points = self._points
        if not points:
            return None
        idx = bisect.bisect_right(points, (self._hash(key), ""))
        return points[idx % len(points)][1]

    def lookup_n(self, key: str, n: int) -> List[str]:
        """First ``n`` DISTINCT members clockwise from ``key`` — the
        key's replica/candidate set, in stable preference order.  Fewer
        than ``n`` members returns them all."""
        points = self._points
        if not points or n < 1:
            return []
        idx = bisect.bisect_right(points, (self._hash(key), ""))
        out: List[str] = []
        for off in range(len(points)):
            member = points[(idx + off) % len(points)][1]
            if member not in out:
                out.append(member)
                if len(out) >= n:
                    break
        return out

    def assignment(self, keys: Iterable[str]) -> Dict[str, Optional[str]]:
        """Bulk ``{key: owner}`` map (the property tests' surface)."""
        return {k: self.lookup(k) for k in keys}
