"""Signal-driven autoscaler: the closed loop from telemetry to
membership.

PR 13 built the sensing half — :class:`SustainedSignal` over a
:class:`TimeSeriesRing` running on the federation collector, "the hook
the future fleet autoscaler consumes".  This module is that consumer:

- **scale-up signals** (any one firing spawns a worker): sustained
  cross-stream bucket occupancy (the device is seeing full tiles
  fleet-wide and still can't keep up), sustained queue depth above
  watermark (backlog is structural), and an optional fleet-wide
  admitted-rate watermark (capacity planning by request volume);
- **scale-down signal**: fleet admitted rate at-or-under an idle bar,
  sustained ``direction="below"`` — held much longer than the up
  signals, because giving capacity back is the decision to make
  slowly.

Every arming decision lives in the SIGNALS (PR 6 philosophy: threshold
x min-hold x disarm hysteresis — a blip can never flap the fleet); the
autoscaler adds the *actuation* discipline on top: spawn/drain
cooldowns, a post-spawn guard (the dip while a new worker warms up must
not read as idleness), and the pool's min/max clamps.  A FIRED signal
that stays fired keeps requesting capacity once per cooldown — the loop
converges to max under truly sustained load instead of stopping at one
step.

Decisions are evaluated on an injectable clock (``tick(now)``), so the
tier-1 tests pin spawn-on-sustained-occupancy and drain-on-idle with
synthetic ring captures and zero wall-clock dependence.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional

from ..analysis.sanitizer import make_lock
from ..obs.clock import mono_ns
from ..obs.timeseries import SIGNAL_FIRED, SustainedSignal, TimeSeriesRing
from ..utils.log import logger
from .config import AutoscalerConfig


def _mono_s() -> float:
    return mono_ns() / 1e9


def default_autoscaler_signals(ring: TimeSeriesRing,
                               cfg: AutoscalerConfig,
                               queue_depth: int = 256
                               ) -> Dict[str, List[SustainedSignal]]:
    """The standard signal set, registered on ``ring`` and returned as
    ``{"up": [...], "down": [...]}`` for :class:`Autoscaler`.  Any
    threshold of 0 disables that signal (a fleet without cross-stream
    batching has no occupancy gauge to watch)."""
    up: List[SustainedSignal] = []
    if cfg.occupancy_high > 0:
        up.append(ring.add_signal(SustainedSignal(
            "fleet_occupancy", "nns_xbatch_occupancy",
            threshold=cfg.occupancy_high, min_hold_s=cfg.hold_s,
            kind="gauge", agg="max", window_s=10.0)))
    if cfg.queue_high_frac > 0:
        up.append(ring.add_signal(SustainedSignal(
            "fleet_queue", "nns_query_server_queue_depth",
            threshold=max(1.0, cfg.queue_high_frac * queue_depth),
            min_hold_s=cfg.hold_s, kind="gauge", agg="max",
            window_s=10.0)))
    if cfg.rate_high_rps > 0:
        up.append(ring.add_signal(SustainedSignal(
            "fleet_load", "nns_query_server_admitted_total",
            threshold=cfg.rate_high_rps, min_hold_s=cfg.hold_s,
            kind="rate", window_s=5.0)))
    down = [ring.add_signal(SustainedSignal(
        "fleet_idle", "nns_query_server_admitted_total",
        threshold=cfg.rate_low_rps, min_hold_s=cfg.idle_hold_s,
        kind="rate", window_s=5.0, direction="below",
        disarm_above=max(cfg.rate_low_rps * 2.0, 1.0)))]
    return {"up": up, "down": down}


class Autoscaler:
    """Actuates a :class:`~nnstreamer_tpu.fleet.pool.WorkerPool` from
    sustained-signal states.

    Drive it two ways (both used in production, both injectable in
    tests): :meth:`attach` subscribes to the ring's
    :class:`~nnstreamer_tpu.obs.timeseries.SignalBus` so a ``fired``
    transition acts immediately, and :meth:`tick` (the FleetLoop path)
    re-checks still-fired signals each pass so sustained load keeps
    stepping toward ``max_workers`` once per cooldown.
    """

    def __init__(self, pool, up_signals: List[SustainedSignal],
                 down_signals: List[SustainedSignal],
                 cfg: Optional[AutoscalerConfig] = None,
                 clock=_mono_s) -> None:
        self.pool = pool
        self.cfg = cfg or AutoscalerConfig()
        if self.cfg.spawn_cooldown_s < 0 or self.cfg.drain_cooldown_s < 0:
            raise ValueError("autoscaler cooldowns must be >= 0")
        self.up_signals = list(up_signals)
        self.down_signals = list(down_signals)
        self.clock = clock
        self._lock = make_lock("fleet.autoscaler")
        self._no_spawn_until = 0.0
        self._no_drain_until = 0.0      # drain cooldown
        self._guard_until = 0.0         # post-spawn guard (separate so
        #                                 the decision log names which
        #                                 bound actually blocked)
        self.spawns = 0
        self.drains = 0
        #: bounded decision log (soak verdict / test surface)
        self.decisions: "deque[Dict[str, Any]]" = deque(maxlen=128)
        self._unsubscribe = None

    # -- wiring --------------------------------------------------------------
    def attach(self, ring: TimeSeriesRing) -> "Autoscaler":
        """Subscribe to the ring's signal bus: ``fired`` transitions
        actuate without waiting for the next maintenance tick."""
        self._unsubscribe = ring.bus.subscribe(self._on_event)
        return self

    def detach(self) -> None:
        if self._unsubscribe is not None:
            self._unsubscribe()
            self._unsubscribe = None

    def _on_event(self, event: Dict[str, Any]) -> None:
        if event.get("state") != "fired":
            return
        name = event.get("signal")
        if any(s.name == name for s in self.up_signals):
            self.maybe_spawn(reason=name)
        elif any(s.name == name for s in self.down_signals):
            self.maybe_drain(reason=name)

    def tick(self, now: Optional[float] = None) -> None:
        """Re-actuate on still-FIRED signals (latched sustained load
        keeps requesting capacity once per cooldown)."""
        for s in self.up_signals:
            if s.state == SIGNAL_FIRED:
                self.maybe_spawn(now, reason=s.name)
                break
        for s in self.down_signals:
            if s.state == SIGNAL_FIRED:
                self.maybe_drain(now, reason=s.name)
                break

    # -- actuation -----------------------------------------------------------
    def _decide(self, action: str, outcome: str, now: float,
                reason: str, **extra) -> None:
        row = {"t": round(now, 3), "action": action,
               "outcome": outcome, "reason": reason, **extra}
        self.decisions.append(row)
        if outcome not in ("cooldown", "guard"):
            logger.info("fleet autoscaler: %s (%s) -> %s",
                        action, reason, outcome)

    def maybe_spawn(self, now: Optional[float] = None,
                    reason: str = "") -> bool:
        if now is None:
            now = self.clock()
        with self._lock:
            if now < self._no_spawn_until:
                self._decide("spawn", "cooldown", now, reason)
                return False
            wid = self.pool.scale_up(now)
            if wid is None:
                self._decide("spawn", "at-max", now, reason,
                             target=self.pool.target)
                return False
            self._no_spawn_until = now + self.cfg.spawn_cooldown_s
            # the new worker's warm-up dip must not read as idleness
            self._guard_until = max(
                self._guard_until, now + self.cfg.post_spawn_guard_s)
            self.spawns += 1
            self._decide("spawn", "spawned", now, reason, wid=wid,
                         target=self.pool.target)
            return True

    def maybe_drain(self, now: Optional[float] = None,
                    reason: str = "") -> bool:
        if now is None:
            now = self.clock()
        with self._lock:
            if now < self._no_drain_until or now < self._guard_until:
                self._decide("drain",
                             "guard" if now < self._guard_until
                             else "cooldown", now, reason)
                return False
            wid = self.pool.scale_down(now)
            if wid is None:
                self._decide("drain", "at-min", now, reason,
                             target=self.pool.target)
                return False
            self._no_drain_until = now + self.cfg.drain_cooldown_s
            self.drains += 1
            self._decide("drain", "drained", now, reason, wid=wid,
                         target=self.pool.target)
            return True

    def report(self) -> Dict[str, Any]:
        return {"spawns": self.spawns, "drains": self.drains,
                "target": self.pool.target,
                "signals": {"up": [s.report() for s in self.up_signals],
                            "down": [s.report()
                                     for s in self.down_signals]},
                "decisions": list(self.decisions)}
