"""Managed worker pool: spawn, watch, restart, drain ``launch.py``
serving processes.

Every worker is an ordinary ``python -m nnstreamer_tpu.launch`` process
(the PR 7 lifecycle applies unchanged: SIGTERM = graceful drain, exit 0)
pushing its metrics registry into the fleet's federation collector
(``--push-metrics``, PR 13).  That one wire gives the pool its whole
health model for free:

- **readiness** — a worker is serving once its origin appears in the
  collector (the publisher only starts after ``play()`` succeeded);
- **liveness** — federation staleness: an origin silent past
  ``stale_kill_s`` is a wedged process (the publisher heartbeats empty
  deltas, so silence is dead-not-idle) and is killed + respawned;
- **crashes** — ``proc.poll()`` + exponential restart backoff with a
  streak reset on the first healthy readiness, so a crash-looping
  worker config cannot hot-spin the host.

Membership callbacks (``on_up`` / ``on_draining`` / ``on_down``) drive
the router: ``on_draining`` fires BEFORE the SIGTERM goes out, so the
router has already routed away by the time the worker starts shedding —
scale-down order is route-away → drain → reap, never the reverse.

Everything is injectable (``spawn_fn``, ``clock``, ``origin_age_fn``)
so the tier-1 tests drive the whole state machine with fake processes
and an injected clock — no wall-clock flakiness.
"""

from __future__ import annotations

import os
import socket as _socket
import subprocess
import sys
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..analysis.sanitizer import make_lock
from ..obs.clock import mono_ns
from ..obs.timeseries import DeadlineLoop
from ..utils.log import logger

#: worker lifecycle states
W_STARTING, W_SERVING, W_DRAINING, W_DEAD = ("starting", "serving",
                                             "draining", "dead")


def _mono_s() -> float:
    return mono_ns() / 1e9


def free_port(host: str = "127.0.0.1") -> int:
    s = _socket.socket()
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


def launch_spawn_fn(launch_template: str,
                    collector_port: Optional[int] = None,
                    push_interval_s: float = 0.5,
                    drain_grace_s: float = 10.0,
                    soak_s: float = 3600.0,
                    log_dir: Optional[str] = None,
                    env_extra: Optional[Dict[str, str]] = None
                    ) -> Callable[[str, int], Any]:
    """Standard real-process spawner: ``launch_template.format(port=)``
    as a ``launch.py --soak`` worker, federating into the collector and
    flagged ``NNS_FLEET_ROLE=worker`` (the dashboard's role column)."""

    def _spawn(host: str, port: int):
        line = launch_template.format(port=port, host=host)
        cmd = [sys.executable, "-m", "nnstreamer_tpu.launch", line,
               "--soak", str(soak_s), "--quiet",
               "--drain-grace", str(drain_grace_s)]
        if collector_port:
            cmd += ["--push-metrics", f"127.0.0.1:{collector_port}",
                    "--push-interval", str(push_interval_s)]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["NNS_FLEET_ROLE"] = "worker"
        env.update(env_extra or {})
        stdout = subprocess.DEVNULL
        log = None
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            log = open(os.path.join(log_dir, f"worker-{port}.log"),
                       "w", encoding="utf-8")
            stdout = log
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        try:
            return subprocess.Popen(cmd, stdout=stdout, stderr=stdout,
                                    env=env, cwd=root)
        finally:
            if log is not None:
                # the child holds its own dup of the fd; keeping the
                # parent's open would leak one fd per spawn — a
                # crash-looping worker config would walk the pool
                # process into EMFILE and kill its ability to respawn
                log.close()

    return _spawn


class ManagedWorker:
    """One worker's pool-side record."""

    __slots__ = ("wid", "host", "port", "proc", "state", "spawned_at",
                 "ready_at", "drain_started", "exit_code",
                 "origin_seen")

    def __init__(self, wid: int, host: str, port: int, proc: Any,
                 now: float) -> None:
        self.wid = wid
        self.host = host
        self.port = port
        self.proc = proc
        self.state = W_STARTING
        self.spawned_at = now
        self.ready_at: Optional[float] = None
        self.drain_started: Optional[float] = None
        self.exit_code: Optional[int] = None
        #: its federation origin answered at least once (gates the
        #: evicted-origin staleness verdict: never-seen != vanished)
        self.origin_seen = False

    @property
    def key(self) -> str:
        return f"{self.host}:{self.port}"

    def row(self, now: float) -> Dict[str, Any]:
        return {"wid": self.wid, "worker": self.key,
                "state": self.state,
                "pid": getattr(self.proc, "pid", None),
                "uptime_s": round(now - self.spawned_at, 1),
                "exit_code": self.exit_code}


class WorkerPool:
    """Spawn/watch/restart/drain a target number of workers.

    ``target`` is the desired serving count (the autoscaler's knob via
    :meth:`scale_up`/:meth:`scale_down`, clamped to
    ``[min_workers, max_workers]``); :meth:`tick` converges the live
    set toward it — restarting crashes with backoff, reaping drains,
    killing wedged (federation-stale) workers.
    """

    def __init__(self, spawn_fn: Callable[[str, int], Any],
                 min_workers: int = 1, max_workers: int = 4,
                 host: str = "127.0.0.1",
                 collector=None,
                 ready_fn: Optional[Callable[[ManagedWorker], bool]] = None,
                 origin_age_fn: Optional[
                     Callable[[ManagedWorker], Optional[float]]] = None,
                 ready_timeout_s: float = 120.0,
                 restart_backoff_s: float = 1.0,
                 restart_backoff_max_s: float = 30.0,
                 stale_kill_s: float = 20.0,
                 drain_grace_s: float = 10.0,
                 on_up: Optional[Callable[[ManagedWorker], None]] = None,
                 on_draining: Optional[
                     Callable[[ManagedWorker], None]] = None,
                 on_down: Optional[Callable[[ManagedWorker], None]] = None,
                 port_fn: Optional[Callable[[], int]] = None,
                 clock: Callable[[], float] = _mono_s) -> None:
        if min_workers < 1:
            raise ValueError(
                "min_workers must be >= 1 (fleet-zero-workers): a pool "
                "allowed to reach zero serves nothing behind a live "
                "router")
        if max_workers < min_workers:
            raise ValueError(
                f"min_workers={min_workers} > max_workers={max_workers} "
                "(fleet-minmax)")
        self.spawn_fn = spawn_fn
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.host = host
        self.collector = collector
        self.ready_fn = ready_fn
        self.origin_age_fn = origin_age_fn
        self.ready_timeout_s = float(ready_timeout_s)
        self.restart_backoff_s = float(restart_backoff_s)
        self.restart_backoff_max_s = float(restart_backoff_max_s)
        self.stale_kill_s = float(stale_kill_s)
        self.drain_grace_s = float(drain_grace_s)
        self.on_up = on_up
        self.on_draining = on_draining
        self.on_down = on_down
        self.port_fn = port_fn or (lambda: free_port(host))
        self.clock = clock
        self.target = self.min_workers
        self._workers: Dict[int, ManagedWorker] = {}
        self._next_wid = 1
        self._crash_streak = 0
        self._next_spawn_at = 0.0
        self._lock = make_lock("fleet.pool")
        #: bounded event log (soak verdict / test surface)
        self.events: "deque[Dict[str, Any]]" = deque(maxlen=256)

    # -- introspection -------------------------------------------------------
    def workers(self) -> List[Dict[str, Any]]:
        now = self.clock()
        with self._lock:
            return [w.row(now) for w in self._workers.values()]

    def serving_count(self) -> int:
        with self._lock:
            return sum(1 for w in self._workers.values()
                       if w.state == W_SERVING)

    def alive_count(self) -> int:
        with self._lock:
            return sum(1 for w in self._workers.values()
                       if w.state in (W_STARTING, W_SERVING))

    def _log(self, event: str, w: Optional[ManagedWorker] = None,
             **extra) -> None:
        row = {"t": round(self.clock(), 3), "event": event, **extra}
        if w is not None:
            row.update({"wid": w.wid, "worker": w.key})
        self.events.append(row)
        logger.info("fleet pool: %s %s", event,
                    w.key if w is not None else extra)

    # -- scaling knob --------------------------------------------------------
    def scale_up(self, now: Optional[float] = None) -> Optional[int]:
        """Raise the target and spawn immediately; None at max, inside
        the crash/spawn-failure backoff, or when the spawn itself
        fails.  A failed spawn reverts the target: leaving it raised
        would let the caller's next attempt ratchet it again (the
        autoscaler treats None as not-actuated and skips its cooldown,
        so transient spawn failures would walk target straight to
        max)."""
        if now is None:
            now = self.clock()
        with self._lock:
            if self.target >= self.max_workers \
                    or now < self._next_spawn_at:
                return None
            self.target += 1
            w = self._spawn_locked(now)
            if w is None:
                self.target -= 1
        return w.wid if w is not None else None

    def scale_down(self, now: Optional[float] = None) -> Optional[int]:
        """Lower the target and drain the newest serving worker (route
        away first, SIGTERM second); None at min."""
        if now is None:
            now = self.clock()
        with self._lock:
            serving = [w for w in self._workers.values()
                       if w.state == W_SERVING]
            if self.target <= self.min_workers or len(serving) <= \
                    self.min_workers:
                return None
            self.target -= 1
            victim = max(serving, key=lambda w: w.spawned_at)
            self._drain_locked(victim, now)
        return victim.wid

    def _drain_locked(self, w: ManagedWorker, now: float) -> None:
        w.state = W_DRAINING
        w.drain_started = now
        self._log("draining", w)
        # route-away BEFORE the SIGTERM: by the time the worker starts
        # shedding, the router must already prefer its peers
        if self.on_draining is not None:
            self.on_draining(w)
        try:
            import signal as _signal

            w.proc.send_signal(_signal.SIGTERM)
        except (OSError, ValueError):
            pass

    # -- spawning ------------------------------------------------------------
    def _spawn_locked(self, now: float) -> Optional[ManagedWorker]:
        port = self.port_fn()
        try:
            proc = self.spawn_fn(self.host, port)
        except OSError as exc:
            self._log("spawn-failed", error=repr(exc))
            self._crash_streak += 1
            self._next_spawn_at = now + self._backoff()
            return None
        w = ManagedWorker(self._next_wid, self.host, port, proc, now)
        self._next_wid += 1
        self._workers[w.wid] = w
        self._log("spawned", w)
        return w

    def _backoff(self) -> float:
        return min(self.restart_backoff_max_s,
                   self.restart_backoff_s
                   * (2 ** max(0, self._crash_streak - 1)))

    def start(self) -> None:
        """Spawn the initial target synchronously (readiness converges
        via tick)."""
        now = self.clock()
        with self._lock:
            while self.alive_count_locked() < self.target:
                if self._spawn_locked(now) is None:
                    break

    def alive_count_locked(self) -> int:
        return sum(1 for w in self._workers.values()
                   if w.state in (W_STARTING, W_SERVING))

    # -- health --------------------------------------------------------------
    def _origin_age(self, w: ManagedWorker) -> Optional[float]:
        """Seconds since the worker's origin last pushed (federation
        staleness), None when it never appeared (or was evicted).
        Marks ``origin_seen`` on every observation, so a later None is
        distinguishable as vanished-after-seen."""
        age = None
        if self.origin_age_fn is not None:
            age = self.origin_age_fn(w)
        elif self.collector is not None:
            pid = getattr(w.proc, "pid", None)
            for row in self.collector.origins():
                if row.get("pid") == pid \
                        and row.get("health") != "local":
                    age = row.get("age_s")
                    break
        if age is not None:
            w.origin_seen = True
        return age

    def _is_ready(self, w: ManagedWorker) -> bool:
        if self.ready_fn is not None:
            return bool(self.ready_fn(w))
        # federation readiness: the publisher only starts after play()
        # succeeded, so the origin's first push IS the serving signal
        return self._origin_age(w) is not None

    # -- the maintenance tick ------------------------------------------------
    def tick(self, now: Optional[float] = None) -> None:
        """One maintenance pass (injectable clock; production drives it
        from a :class:`FleetLoop`)."""
        if now is None:
            now = self.clock()
        with self._lock:
            for w in list(self._workers.values()):
                if w.state in (W_STARTING, W_SERVING):
                    rc = w.proc.poll()
                    if rc is not None:
                        self._on_crash_locked(w, now, rc)
                        continue
                if w.state == W_STARTING:
                    if self._is_ready(w):
                        w.state = W_SERVING
                        w.ready_at = now
                        self._crash_streak = 0
                        self._log("serving", w)
                        if self.on_up is not None:
                            self.on_up(w)
                    elif now - w.spawned_at > self.ready_timeout_s:
                        self._log("ready-timeout", w)
                        self._kill(w)
                        self._on_crash_locked(w, now, None)
                elif w.state == W_SERVING:
                    age = self._origin_age(w)
                    evicted = age is None and w.origin_seen
                    if evicted or (age is not None
                                   and age > self.stale_kill_s):
                        # wedged: alive but silent past the heartbeat
                        # horizon — its gauges are lies and its clients
                        # are stalling; replace it.  A VANISHED origin
                        # counts too: the collector evicts origins at
                        # its own staleness horizon (often shorter than
                        # stale_kill_s), after which the age reads None
                        # forever — eviction of a once-ready origin IS
                        # the staleness verdict, not absence of one
                        self._log("stale-kill", w,
                                  age_s=(round(age, 1)
                                         if age is not None
                                         else "evicted"))
                        self._kill(w)
                        self._on_crash_locked(w, now, None)
                elif w.state == W_DRAINING:
                    rc = w.proc.poll()
                    if rc is not None:
                        self._reap_locked(w, now, rc)
                    elif now - w.drain_started > self.drain_grace_s + 5.0:
                        self._log("drain-overdue", w)
                        self._kill(w)
                        self._reap_locked(w, now, None)
            # converge toward target: one respawn per tick, gated by
            # the crash backoff so a bad config cannot hot-loop
            if self.alive_count_locked() < self.target \
                    and now >= self._next_spawn_at:
                self._spawn_locked(now)

    def _on_crash_locked(self, w: ManagedWorker, now: float,
                         rc: Optional[int]) -> None:
        w.state = W_DEAD
        w.exit_code = rc
        del self._workers[w.wid]
        self._crash_streak += 1
        self._next_spawn_at = now + self._backoff()
        self._log("crashed", w, exit_code=rc,
                  backoff_s=round(self._backoff(), 2))
        if self.on_down is not None:
            self.on_down(w)

    def _reap_locked(self, w: ManagedWorker, now: float,
                     rc: Optional[int]) -> None:
        w.state = W_DEAD
        w.exit_code = rc
        del self._workers[w.wid]
        self._log("reaped", w, exit_code=rc)
        if self.on_down is not None:
            self.on_down(w)

    @staticmethod
    def _kill(w: ManagedWorker) -> None:
        try:
            w.proc.kill()
        except (OSError, ValueError):
            pass
        try:
            w.proc.wait(timeout=10)
        except Exception:   # noqa: BLE001 — already-reaped fakes
            pass

    # -- teardown ------------------------------------------------------------
    def stop(self, drain: bool = True, grace_s: Optional[float] = None
             ) -> None:
        """Drain (``SIGTERM`` + grace) or kill every worker and wait
        for exits.  ``drain=False`` kills immediately — workers run
        ``--soak`` loops that never exit on their own, so waiting out
        a grace with no signal sent would just stall teardown."""
        grace = self.drain_grace_s if grace_s is None else grace_s
        with self._lock:
            workers = list(self._workers.values())
            self._workers.clear()
        for w in workers:
            if w.proc.poll() is not None:
                continue
            if drain:
                try:
                    import signal as _signal

                    w.proc.send_signal(_signal.SIGTERM)
                except (OSError, ValueError):
                    pass
            else:
                self._kill(w)
        for w in workers:
            try:
                w.proc.wait(timeout=(grace + 5.0) if drain else 10.0)
            except Exception:   # noqa: BLE001 — hard stop after grace
                self._kill(w)
            if self.on_down is not None:
                self.on_down(w)


class FleetLoop(DeadlineLoop):
    """Fleet maintenance on the shared absolute-deadline loop
    (obs/timeseries.py :class:`DeadlineLoop`): ``pool.tick`` +
    ``autoscaler.tick`` + anything else the fleet owner registers (a
    raising tick is logged and survived — a dead loop would stop crash
    restarts)."""

    def __init__(self, fns, interval_s: float = 0.5) -> None:
        super().__init__(fns, interval_s, name="fleet-maint")
