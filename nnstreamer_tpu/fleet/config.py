"""Fleet configuration: one JSON document describing router + pool +
autoscaler, with static validation (the ``launch.py --check`` hook).

The fleet tier has exactly the failure mode the PR 4 verifier exists to
prevent for pipelines: a config that parses, starts, and then
misbehaves structurally (a router fronting zero workers sheds every
request forever; an autoscaler with ``min > max`` can never converge; a
drain grace shorter than the worker's bucket fill window cuts resident
cross-stream buckets mid-collect on every scale-down).  Those are
graph-shaped errors, so they get the same treatment: named findings
BEFORE anything spawns — ``python -m nnstreamer_tpu.launch --check
fleet.json`` (analysis/verify.py routes ``.json`` arguments here).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Tuple

#: (severity, rule, message) — the shape analysis/verify.py wraps into
#: its Finding rows
ConfigFinding = Tuple[str, str, str]


@dataclasses.dataclass
class AutoscalerConfig:
    """Closed-loop scaling policy knobs (fleet/autoscaler.py)."""

    #: sustained bucket-occupancy threshold that spawns (frames resident
    #: in the cross-stream bucket, fleet-max over workers; 0 disables)
    occupancy_high: float = 6.0
    #: queue-depth fraction of the bound that spawns (0 disables)
    queue_high_frac: float = 0.75
    #: fleet-wide admitted requests/s that spawns (0 disables)
    rate_high_rps: float = 0.0
    #: fleet-wide admitted requests/s at-or-under which the fleet is
    #: idle and a worker drains (<= comparisons: 0 is a valid idle bar)
    rate_low_rps: float = 0.5
    #: seconds a condition must hold before it fires (PR 13 arming)
    hold_s: float = 5.0
    #: idle must hold longer than load: scaling down is the cheap
    #: decision to get wrong slowly and the expensive one to flap
    idle_hold_s: float = 15.0
    #: cooldowns between actions (hysteresis in time, not just value)
    spawn_cooldown_s: float = 20.0
    drain_cooldown_s: float = 30.0
    #: no drain may follow a spawn within this guard (flap killer: the
    #: spawn's own capacity dip must not read as idleness)
    post_spawn_guard_s: float = 30.0


@dataclasses.dataclass
class FleetConfig:
    """The whole fleet document.  ``worker_launch`` is a launch-string
    template with a ``{port}`` placeholder — every worker is an
    ordinary ``launch.py`` serving process."""

    worker_launch: str = ""
    min_workers: int = 1
    max_workers: int = 4
    router_host: str = "127.0.0.1"
    router_port: int = 0
    #: ring replica set size per model key (0 = spread over all workers)
    replicas: int = 2
    #: SIGTERM drain budget handed to workers (launch.py --drain-grace)
    drain_grace_s: float = 10.0
    #: the worker's cross-stream bucket fill window, when batching
    #: (informs the drain-grace check; 0 = per-frame workers)
    worker_batch_timeout_ms: float = 0.0
    restart_backoff_s: float = 1.0
    restart_backoff_max_s: float = 30.0
    #: federation staleness horizon before a silent worker is presumed
    #: wedged and restarted
    stale_kill_s: float = 20.0
    autoscaler: AutoscalerConfig = dataclasses.field(
        default_factory=AutoscalerConfig)

    @classmethod
    def from_dict(cls, raw: Dict[str, Any]) -> "FleetConfig":
        raw = dict(raw)
        asc = raw.pop("autoscaler", None) or {}
        known = {f.name for f in dataclasses.fields(cls)} - {"autoscaler"}
        unknown = set(raw) - known
        if unknown:
            raise ValueError(
                f"unknown fleet config keys: {sorted(unknown)}")
        asc_known = {f.name for f in dataclasses.fields(AutoscalerConfig)}
        asc_unknown = set(asc) - asc_known
        if asc_unknown:
            raise ValueError(
                f"unknown autoscaler config keys: {sorted(asc_unknown)}")
        return cls(autoscaler=AutoscalerConfig(**asc), **raw)

    @classmethod
    def load(cls, path: str) -> "FleetConfig":
        with open(path, encoding="utf-8") as fh:
            return cls.from_dict(json.load(fh))

    # -- static validation ---------------------------------------------------
    def validate(self) -> List[ConfigFinding]:
        """Named findings, errors first — the ``--check`` surface.  The
        same rules gate ``WorkerPool``/``Autoscaler`` construction, so
        a config that passes ``--check`` cannot fail at start for a
        structural reason."""
        out: List[ConfigFinding] = []
        asc = self.autoscaler
        if self.min_workers < 1:
            out.append((
                "error", "fleet-zero-workers",
                f"min_workers={self.min_workers}: a router fronting "
                "zero workers answers every request with a shed — the "
                "fleet serves nothing while looking alive"))
        if self.max_workers < self.min_workers:
            out.append((
                "error", "fleet-minmax",
                f"autoscaler bounds inverted: min_workers="
                f"{self.min_workers} > max_workers={self.max_workers} "
                "— no worker count satisfies both, so every tick wants "
                "to scale in both directions"))
        if not str(self.worker_launch).strip():
            out.append((
                "error", "fleet-no-launch",
                "worker_launch is empty: the pool has no pipeline to "
                "spawn"))
        elif "{port}" not in str(self.worker_launch):
            out.append((
                "error", "fleet-no-launch",
                "worker_launch has no {port} placeholder: every worker "
                "would bind the same port and all but the first would "
                "crash-loop"))
        if self.worker_batch_timeout_ms > 0 and \
                self.drain_grace_s * 1000.0 <= self.worker_batch_timeout_ms:
            out.append((
                "error", "fleet-drain-grace",
                f"drain_grace_s={self.drain_grace_s:g}s is not longer "
                f"than the worker bucket fill window "
                f"({self.worker_batch_timeout_ms:g} ms): a draining "
                "worker's resident cross-stream bucket could not flush "
                "before the grace cuts it, dropping admitted frames on "
                "every scale-down"))
        if self.replicas < 0:
            out.append((
                "error", "fleet-replicas",
                f"replicas={self.replicas} (want 0 = spread over all "
                "workers, or a positive replica-set size)"))
        if asc.spawn_cooldown_s < 0 or asc.drain_cooldown_s < 0:
            # parity with Autoscaler.__init__'s guard: validate() must
            # reject everything construction would crash on, or a
            # --check-passing config could still fail at start
            out.append((
                "error", "fleet-cooldown",
                f"negative autoscaler cooldown (spawn="
                f"{asc.spawn_cooldown_s:g}, drain="
                f"{asc.drain_cooldown_s:g}): cooldowns must be >= 0"))
        if asc.spawn_cooldown_s == 0:
            out.append((
                "warning", "fleet-cooldown",
                "spawn_cooldown_s=0: a still-FIRED load signal "
                "re-actuates every maintenance tick, so the fleet "
                "jumps to max_workers in seconds under any sustained "
                "load — the cooldown IS the step pacing"))
        if asc.idle_hold_s < asc.hold_s:
            out.append((
                "warning", "fleet-idle-hold",
                f"idle_hold_s={asc.idle_hold_s:g} < hold_s="
                f"{asc.hold_s:g}: the fleet gives capacity back faster "
                "than it grants it, which amplifies load oscillation"))
        if self.replicas and self.replicas > self.max_workers:
            out.append((
                "info", "fleet-replicas",
                f"replicas={self.replicas} exceeds max_workers="
                f"{self.max_workers}: every model spreads over the "
                "whole fleet (equivalent to replicas=0)"))
        return out

    def raise_on_errors(self) -> None:
        errors = [m for sev, _r, m in self.validate() if sev == "error"]
        if errors:
            raise ValueError("invalid fleet config: " + "; ".join(errors))


def load_fleet_config(path_or_dict) -> FleetConfig:
    if isinstance(path_or_dict, FleetConfig):
        return path_or_dict
    if isinstance(path_or_dict, dict):
        return FleetConfig.from_dict(path_or_dict)
    return FleetConfig.load(str(path_or_dict))
