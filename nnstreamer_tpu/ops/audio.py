"""TF-compatible audio feature ops: AudioSpectrogram and Mfcc.

The reference's speech-command golden (tests/nnstreamer_filter_tensorflow:
conv_actions_frozen.pb on yes.wav) runs DecodeWav → AudioSpectrogram →
Mfcc inside the TF graph.  These are faithful jax implementations of the
TF kernels (tensorflow/core/kernels/spectrogram.cc,
mfcc_mel_filterbank.cc, mfcc_dct.cc) so the whole feature front-end jits
into the same XLA executable as the conv net:

- spectrogram: periodic Hann window, FFT length = next pow2(window),
  frame step = stride, |FFT|² (magnitude_squared) over the first
  fft/2+1 bins;
- mel filterbank: TF's linear-interpolation weights over FFT bins mapped
  to mel (1127·ln(1+f/700)) between lower/upper limits, applied to the
  MAGNITUDE (sqrt of the squared spectrogram) — precomputed as one
  (channels, bins) matrix so it runs as a single MXU matmul;
- log floor 1e-12, then TF's DCT-II (scale sqrt(2/N), no orthonormal
  special case for k=0).

The filterbank matrix depends on the sample rate; it is built host-side
(numpy) for a STATIC rate — fine for real pipelines, where a stream's
rate is fixed (DecodeWav's desired_samples pins it in the graphs that use
these ops).
"""

from __future__ import annotations

import numpy as np


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def audio_spectrogram(audio, window_size: int, stride: int,
                      magnitude_squared: bool):
    """TF AudioSpectrogram: (samples, channels) f32 →
    (channels, frames, fft//2+1)."""
    import jax.numpy as jnp

    samples = audio.shape[0]
    fft_len = _next_pow2(window_size)
    n_frames = 1 + (samples - window_size) // stride
    if n_frames < 1:
        raise ValueError(
            f"audio_spectrogram: {samples} samples < window {window_size}")
    window = (0.5 - 0.5 * np.cos(
        2.0 * np.pi * np.arange(window_size) / window_size)).astype(
            np.float32)
    idx = (np.arange(n_frames)[:, None] * stride
           + np.arange(window_size)[None, :])
    frames = jnp.transpose(audio, (1, 0))[:, idx]   # (ch, frames, win)
    spec = jnp.fft.rfft(frames * window, n=fft_len)
    mag2 = (spec.real * spec.real + spec.imag * spec.imag)
    return mag2 if magnitude_squared else jnp.sqrt(mag2)


def mel_filterbank_matrix(sample_rate: float, input_length: int,
                          channel_count: int, lower_limit: float,
                          upper_limit: float) -> np.ndarray:
    """TF MfccMelFilterbank weights as a dense (channels, bins) matrix
    (mfcc_mel_filterbank.cc Initialize/Compute, including its band
    mapping and interpolation conventions)."""
    def mel(f):
        return 1127.0 * np.log1p(np.asarray(f, np.float64) / 700.0)

    mel_lo = mel(lower_limit)
    mel_hi = mel(upper_limit)
    mel_span = mel_hi - mel_lo
    mel_spacing = mel_span / (channel_count + 1)
    center = mel_lo + mel_spacing * np.arange(1, channel_count + 2)

    hz_per_sbin = 0.5 * sample_rate / (input_length - 1)
    start_index = int(1.5 + lower_limit / hz_per_sbin)
    end_index = int(upper_limit / hz_per_sbin)

    band_mapper = np.full(input_length, -2, np.int64)
    channel = 0
    for i in range(input_length):
        melf = mel(i * hz_per_sbin)
        if start_index <= i <= end_index:
            while (channel < channel_count
                   and center[channel] < melf):
                channel += 1
            band_mapper[i] = channel - 1

    weights = np.zeros(input_length, np.float64)
    for i in range(input_length):
        ch = band_mapper[i]
        if start_index <= i <= end_index:
            melf = mel(i * hz_per_sbin)
            if ch >= 0:
                weights[i] = ((center[ch + 1] - melf)
                              / (center[ch + 1] - center[ch]))
            else:
                weights[i] = (center[0] - melf) / (center[0] - mel_lo)

    mat = np.zeros((channel_count, input_length), np.float64)
    for i in range(input_length):
        ch = band_mapper[i]
        if start_index <= i <= end_index:
            if ch >= 0:
                mat[ch, i] += weights[i]
            if ch + 1 < channel_count:
                mat[ch + 1, i] += 1.0 - weights[i]
    return mat.astype(np.float32)


def dct_matrix(input_length: int, coefficient_count: int) -> np.ndarray:
    """TF MfccDct cosine table (mfcc_dct.cc): DCT-II scaled sqrt(2/N)."""
    fnorm = np.sqrt(2.0 / input_length)
    arg = np.pi / input_length
    n = np.arange(input_length)
    k = np.arange(coefficient_count)[:, None]
    return (fnorm * np.cos(k * arg * (n + 0.5))).astype(np.float32)


def mfcc(spectrogram_sq, sample_rate: float, channel_count: int = 40,
         lower_limit: float = 20.0, upper_limit: float = 4000.0,
         dct_count: int = 13):
    """TF Mfcc: squared-magnitude spectrogram (ch, frames, bins) →
    (ch, frames, dct_count)."""
    import jax.numpy as jnp

    bins = spectrogram_sq.shape[-1]
    fb = mel_filterbank_matrix(sample_rate, bins, channel_count,
                               lower_limit, upper_limit)
    dct = dct_matrix(channel_count, dct_count)
    mag = jnp.sqrt(spectrogram_sq)
    energies = jnp.einsum("cfb,kb->cfk", mag, jnp.asarray(fb))
    logged = jnp.log(jnp.maximum(energies, 1e-12))
    return jnp.einsum("cfk,dk->cfd", logged, jnp.asarray(dct))
