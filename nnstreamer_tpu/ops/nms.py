"""Device-side non-maximum suppression (static shapes, XLA-friendly).

The reference decodes SSD boxes and runs greedy NMS on the host CPU
(tensordec-boundingbox.c nms + the per-scheme decode).  TPU-first, the
whole detection tail — prior decode, thresholding, top-K cap, greedy
per-class NMS — belongs INSIDE the serving executable: only the ≤K
surviving boxes ever cross device→host (~2.4 KB instead of the full
anchor grid), and the O(K·N + K²) suppression math runs on the chip
next to the model instead of in Python.

Everything is static-shape: ``top_k`` caps candidates to K
(DETECTION_MAX), pairwise IoU is a (K, K) matrix, and the greedy scan
is a ``lax.fori_loop`` whose carry is the keep mask — the same greedy
per-class semantics as ``decoders.boundingbox.nms`` (score-descending,
suppress IoU > thresh against already-kept boxes of the same class).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def pairwise_iou(boxes: jnp.ndarray) -> jnp.ndarray:
    """(K, 4) yxyx corners -> (K, K) IoU matrix (0 where union is 0)."""
    ymin, xmin, ymax, xmax = (boxes[:, i] for i in range(4))
    area = (ymax - ymin) * (xmax - xmin)
    iy = (jnp.minimum(ymax[:, None], ymax[None, :])
          - jnp.maximum(ymin[:, None], ymin[None, :]))
    ix = (jnp.minimum(xmax[:, None], xmax[None, :])
          - jnp.maximum(xmin[:, None], xmin[None, :]))
    inter = jnp.maximum(iy, 0.0) * jnp.maximum(ix, 0.0)
    union = area[:, None] + area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def device_nms(boxes: jnp.ndarray, scores: jnp.ndarray,
               classes: jnp.ndarray, k: int = 100,
               iou_thresh: float = 0.5, score_thresh: float = 0.0):
    """Greedy per-class NMS over the top-``k`` candidates.

    Args:
      boxes: (N, 4) yxyx corners (already decoded to image space).
      scores: (N,) — entries below ``score_thresh`` are dropped.
      classes: (N,) int class ids.

    Returns ``(boxes (k,4) f32, classes (k,) i32, scores (k,) f32,
    num (1,) i32)``: score-descending; suppressed/invalid slots carry
    class -1 / score 0, and ``num`` counts the survivors — the same
    output contract as the reference's ssd-postprocess tensors, so the
    host just materializes ``num`` objects.
    """
    n = scores.shape[0]
    k = min(k, n)
    sc = jnp.where(scores >= score_thresh, scores.astype(jnp.float32),
                   -jnp.inf)
    top, idx = lax.top_k(sc, k)
    b = boxes[idx].astype(jnp.float32)
    c = classes[idx].astype(jnp.int32)
    valid = jnp.isfinite(top)
    top = jnp.where(valid, top, 0.0)
    same_cls = c[:, None] == c[None, :]
    conflict = (pairwise_iou(b) > iou_thresh) & same_cls
    order = jnp.arange(k)

    def body(i, keep):
        sup = jnp.any(conflict[i] & keep & (order < i))
        return keep.at[i].set(keep[i] & ~sup)

    keep = lax.fori_loop(0, k, body, valid)
    out_c = jnp.where(keep, c, -1)
    out_s = jnp.where(keep, top, 0.0)
    return (b, out_c, out_s,
            jnp.sum(keep.astype(jnp.int32)).reshape(1))
