"""TPU ops: pallas kernels + jitted primitives for stream hot paths."""

from .classify import top1, topk_indices
from .preprocess import normalize_frame, normalize_frame_reference

__all__ = ["normalize_frame", "normalize_frame_reference", "top1",
           "topk_indices"]
