"""TPU ops: pallas kernels + jitted primitives for stream hot paths."""

from .preprocess import normalize_frame, normalize_frame_reference

__all__ = ["normalize_frame", "normalize_frame_reference"]
