"""Classifier post-processing reductions as pure jax ops.

The argmax-style decoders (image_labeling and friends) reduce a score
vector to one index; done on host they force a full d2h fetch of the
logits (1001 floats for MobileNet) per frame.  Expressed here as pure
jnp functions they serve BOTH device paths that keep the logits
resident:

- the decoder reduction pushdown (``Decoder.device_reduce_spec``):
  the reduction composes into the upstream filter's jitted forward via
  ``set_postprocess`` and only the (1,) int32 index crosses to host;
- whole-segment XLA lowering (``Decoder.lower_decode``, fuse=xla):
  the reduction is traced into the segment's single fused computation.

Kept op-shaped (tensor in, tensor out, no config/buffer types) so the
same kernels slot into future decoders — top-k detection heads, CTC
collapse — without touching the decoder ABI.
"""

from __future__ import annotations


def top1(scores):
    """Flattened argmax as a ``(1,)`` int32 tensor — the image_labeling
    reduction.  Pure jnp; traceable under jit/vmap (a vmapped segment
    reduces every bucket row independently)."""
    import jax.numpy as jnp

    return jnp.argmax(scores.reshape(-1)).astype(jnp.int32).reshape(1)


def topk_indices(scores, k: int):
    """Top-k flattened indices, descending, as ``(k,)`` int32 — the
    multi-label generalization (k is static under jit)."""
    import jax.numpy as jnp

    flat = scores.reshape(-1)
    _, idx = __import__("jax").lax.top_k(flat, k)
    return idx.astype(jnp.int32)
