"""Fused frame preprocessing as a Pallas TPU kernel.

The converter→filter seam's per-frame math (uint8 media → scaled/shifted
model dtype; the role the reference gives ORC SIMD in tensor_transform's
``typecast + arithmetic`` chains, gsttensor_transform.c:463-533) expressed
as a single VMEM-resident Pallas kernel: one pass, no intermediate f32
buffer in HBM.

XLA already fuses `x.astype(bf16) * a + b` well, so this kernel is mostly
a template for heavier fused stages (quantized preprocessing, layout
swizzles); the XLA backend uses it when ``use_pallas:1`` is set.  On CPU
(tests) the kernel runs in interpret mode and is validated against the
jnp reference implementation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_LANES = 128
_SUBLANES = 8
_BLOCK = _LANES * _SUBLANES


def normalize_frame_reference(frame, scale: float = 1.0 / 127.5,
                              shift: float = -1.0,
                              dtype=jnp.bfloat16):
    """jnp reference: y = frame * scale + shift, cast to dtype."""
    return (frame.astype(jnp.float32) * scale + shift).astype(dtype)


@functools.partial(jax.jit, static_argnames=("scale", "shift", "dtype"))
def normalize_frame(frame, scale: float = 1.0 / 127.5, shift: float = -1.0,
                    dtype=jnp.bfloat16):
    """Pallas kernel: flatten → pad to (8,128) tiles → fused scale/shift/
    cast in VMEM → original shape."""
    from jax.experimental import pallas as pl

    shape = frame.shape
    n = frame.size
    pad = (-n) % _BLOCK
    flat = frame.reshape(-1)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), frame.dtype)])
    tiled = flat.reshape(-1, _LANES)  # (rows, 128), rows % 8 == 0

    def kernel(in_ref, out_ref):
        x = in_ref[:].astype(jnp.float32)
        out_ref[:] = (x * scale + shift).astype(out_ref.dtype)

    from .flash_attention import flash_is_default

    interpret = not flash_is_default()
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(tiled.shape, dtype),
        interpret=interpret,
    )(tiled)
    out = out.reshape(-1)
    if pad:
        out = out[:n]
    return out.reshape(shape)
