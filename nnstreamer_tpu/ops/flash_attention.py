"""Pallas flash attention: the TPU kernel for exact local attention.

The framework's long-context strategies (parallel/ring_attention.py,
parallel/ulysses.py) reduce global attention to per-device LOCAL attention
over full or blockwise sequences.  This module provides that local core as
a hand-written Pallas TPU kernel (per /opt/skills/guides/pallas_guide.md):

- **streaming softmax**: grid dimension 2 walks K/V in ``block_k`` tiles;
  running max / sum / accumulator live in VMEM scratch that persists
  across the (sequential) innermost grid dimension — VMEM holds
  O(block_q·d + block_q·block_k + block_k·d), never O(T²) scores and
  never the full K/V;
- **MXU-shaped**: both matmuls (Q·Kᵀ and P·V) run as ``dot_general`` with
  f32 accumulation on bf16/f32 inputs; tiles default to 128 to match the
  MXU systolic array;
- **differentiable**: a ``jax.custom_vjp`` pairs the flash forward with an
  exact recompute backward (standard attention gradients in jnp) so
  training steps (train_step.py's ``value_and_grad``) work — backward
  materializes one (T_q, T_kv) score matrix, the usual
  recompute-checkpoint trade.

``interpret=True`` runs the same kernel on CPU (tests validate it against
the naive oracle); on non-TPU platforms callers should prefer the jnp
reference path for speed (`flash_attention` is correct everywhere but the
interpreter is slow).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = float("-inf")


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, max_ref, sum_ref, *,
            n_k_blocks: int, causal: bool, q_offset: int, k_offset: int,
            scale: float, kv_len: int = 0):
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    j = pl.program_id(2)
    q = q_ref[0].astype(jnp.float32)           # (bq, d)
    block_q, d = q.shape

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        max_ref[...] = jnp.full_like(max_ref, _NEG_INF)
        sum_ref[...] = jnp.zeros_like(sum_ref)

    k_blk = k_ref[0].astype(jnp.float32)       # (bk, d)
    v_blk = v_ref[0].astype(jnp.float32)
    block_k = k_blk.shape[0]

    def _accumulate():
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if kv_len:
            # K/V were zero-padded up to a block multiple: mask the
            # padded tail (local positions >= the real length)
            k_local = j * block_k + jax.lax.iota(jnp.int32, block_k)
            s = jnp.where(k_local[None, :] >= kv_len, _NEG_INF, s)
        if causal:
            q_idx = (q_offset + iq * block_q
                     + jax.lax.iota(jnp.int32, block_q))
            k_idx = (k_offset + j * block_k
                     + jax.lax.iota(jnp.int32, block_k))
            s = jnp.where(k_idx[None, :] > q_idx[:, None], _NEG_INF, s)
        row_max = max_ref[:, 0]
        row_sum = sum_ref[:, 0]
        blk_max = jnp.max(s, axis=-1)
        new_max = jnp.maximum(row_max, blk_max)
        safe_max = jnp.where(jnp.isfinite(new_max), new_max, 0.0)
        p = jnp.exp(s - safe_max[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        corr = jnp.exp(jnp.where(jnp.isfinite(row_max),
                                 row_max - safe_max, _NEG_INF))
        corr = jnp.where(jnp.isfinite(corr), corr, 0.0)
        acc_ref[...] = acc_ref[...] * corr[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        max_ref[:, 0] = new_max
        sum_ref[:, 0] = row_sum * corr + jnp.sum(p, axis=-1)

    if causal:
        # causal block skip: a K block strictly in THIS q-block's future
        # is all-masked — skip both matmuls (the standard flash
        # optimization; ~half the inner-grid work for self-attention)
        live = (k_offset + j * block_k
                <= q_offset + (iq + 1) * block_q - 1)
        pl.when(live)(_accumulate)
    else:
        _accumulate()

    @pl.when(j == n_k_blocks - 1)
    def _finalize():
        denom = jnp.maximum(sum_ref[:, 0], 1e-20)
        o_ref[0] = (acc_ref[...] / denom[:, None]).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int,
                   q_offset: int, k_offset: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t_q, h, d = q.shape
    t_kv = k.shape[0]
    # tile choice: never shrink below the 8-row sublane granule — a T that
    # doesn't divide the tile is PADDED up to a block multiple instead
    # (an odd/prime T used to collapse blocks to 1-row tiles: a severe
    # MXU perf cliff and a Mosaic shape the tests never exercised)
    block_q = min(block_q, _round_up(t_q, 8))
    block_k = min(block_k, _round_up(t_kv, 8))
    t_q_pad = _round_up(t_q, block_q)
    t_kv_pad = _round_up(t_kv, block_k)
    if t_q_pad != t_q:
        q = jnp.pad(q, ((0, t_q_pad - t_q), (0, 0), (0, 0)))
    if t_kv_pad != t_kv:
        k = jnp.pad(k, ((0, t_kv_pad - t_kv), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, t_kv_pad - t_kv), (0, 0), (0, 0)))
    n_k_blocks = t_kv_pad // block_k

    qh = jnp.transpose(q, (1, 0, 2))   # (H, Tq, D)
    kh = jnp.transpose(k, (1, 0, 2))
    vh = jnp.transpose(v, (1, 0, 2))
    scale = 1.0 / float(d) ** 0.5

    kern = functools.partial(_kernel, n_k_blocks=n_k_blocks, causal=causal,
                             q_offset=q_offset, k_offset=k_offset,
                             scale=scale,
                             kv_len=t_kv if t_kv_pad != t_kv else 0)
    out = pl.pallas_call(
        kern,
        grid=(h, t_q_pad // block_q, n_k_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda hh, qq, kk: (hh, qq, 0)),
            pl.BlockSpec((1, block_k, d), lambda hh, qq, kk: (hh, kk, 0)),
            pl.BlockSpec((1, block_k, d), lambda hh, qq, kk: (hh, kk, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda hh, qq, kk: (hh, qq, 0)),
        out_shape=jax.ShapeDtypeStruct((h, t_q_pad, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qh, kh, vh)
    return jnp.transpose(out, (1, 0, 2))[:t_q]


def _naive_grads(q, k, v, do, causal, q_offset, k_offset):
    """Exact attention gradients by recompute (one (Tq,Tkv) score matrix
    per head — the standard flash-backward checkpoint trade)."""
    t_q, h, d = q.shape
    t_kv = k.shape[0]
    scale = 1.0 / float(d) ** 0.5
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    s = jnp.einsum("qhd,khd->hqk", qf, kf) * scale
    if causal:
        q_idx = q_offset + jnp.arange(t_q)
        k_idx = k_offset + jnp.arange(t_kv)
        s = jnp.where(k_idx[None, None, :] > q_idx[None, :, None],
                      _NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.isnan(p), 0.0, p)        # fully-masked rows
    dv = jnp.einsum("hqk,qhd->khd", p, dof)
    dp = jnp.einsum("qhd,khd->hqk", dof, vf)
    ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
    dq = jnp.einsum("hqk,khd->qhd", ds, kf) * scale
    dk = jnp.einsum("hqk,qhd->khd", ds, qf) * scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, block_q, block_k, q_offset, k_offset,
           interpret):
    return _flash_forward(q, k, v, causal, block_q, block_k, q_offset,
                          k_offset, interpret)


def _flash_fwd(q, k, v, causal, block_q, block_k, q_offset, k_offset,
               interpret):
    out = _flash_forward(q, k, v, causal, block_q, block_k, q_offset,
                         k_offset, interpret)
    return out, (q, k, v)


def _flash_bwd(causal, block_q, block_k, q_offset, k_offset, interpret,
               res, do):
    q, k, v = res
    return _naive_grads(q, k, v, do, causal, q_offset, k_offset)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = False, block_q: int = 128,
                    block_k: int = 128, q_offset: int = 0,
                    k_offset: int = 0,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Exact attention via the Pallas streaming-softmax kernel.

    Args:
      q: (T_q, H, D); k, v: (T_kv, H, D) — same layout as
        :func:`parallel.ring_attention.local_attention`.
      causal: mask ``k_pos > q_pos`` using global positions
        ``q_offset + i`` / ``k_offset + j`` (offsets let blockwise callers
        keep global causality).
      interpret: force the Pallas interpreter (CPU); default: interpret
        off on TPU, on elsewhere.

    Differentiable (custom VJP: flash forward, exact recompute backward).
    Sequence lengths that don't divide the tile are zero-padded up to a
    block multiple (padded K positions masked, padded Q rows sliced off)
    — tiles never shrink below the 8-row sublane granule.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash(q, k, v, causal, block_q, block_k, q_offset, k_offset,
                  interpret)
