"""Pallas flash attention: the TPU kernel for exact local attention.

The framework's long-context strategies (parallel/ring_attention.py,
parallel/ulysses.py) reduce global attention to per-device LOCAL attention
over full or blockwise sequences.  This module provides that local core as
a hand-written Pallas TPU kernel (per /opt/skills/guides/pallas_guide.md):

- **streaming softmax**: grid dimension 2 walks K/V in ``block_k`` tiles;
  running max / sum / accumulator live in VMEM scratch that persists
  across the (sequential) innermost grid dimension — VMEM holds
  O(block_q·d + block_q·block_k + block_k·d), never O(T²) scores and
  never the full K/V;
- **MXU-shaped**: both matmuls (Q·Kᵀ and P·V) run as ``dot_general`` with
  f32 accumulation on bf16/f32 inputs; tile defaults are 128 (MXU
  systolic shape) for short sequences and the MEASURED best shape from
  ``tools/flash_tpu_bench.py --tune`` (utils/tuned.py FLASH_TILES) for
  long ones;
- **differentiable, flash both ways**: a ``jax.custom_vjp`` pairs the
  flash forward with STREAMING Pallas backward kernels
  (FlashAttention-2 structure): the forward saves only O and the
  per-row logsumexp; dq and dk/dv kernels recompute one (bq, bk)
  probability tile at a time in VMEM — no (T_q, T_kv) matrix ever
  lands in HBM in either direction, so trainable sequence length is
  bounded by O(T·d), not O(T²).

``interpret=True`` runs the same kernel on CPU (tests validate it against
the naive oracle); on non-TPU platforms callers should prefer the jnp
reference path for speed (`flash_attention` is correct everywhere but the
interpreter is slow).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = float("-inf")


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def flash_is_default() -> bool:
    """Whether callers with ``flash=None`` should pick the Mosaic kernel:
    keys off the ACTUAL placement, not just the process default — a
    ``jax.default_device(cpu)`` pin on a TPU host must not select it."""
    dev = getattr(jax.config, "jax_default_device", None)
    if isinstance(dev, str):               # e.g. JAX_DEFAULT_DEVICE=cpu
        platform = dev.split(":")[0]
    else:
        platform = (getattr(dev, "platform", None)
                    or jax.default_backend())
    return platform == "tpu"


#: Fallback sequence-length crossover for kernel-vs-naive selection
#: when the measured record (utils/tuned.py FLASH_MIN_T, rewritten by
#: tools/flash_tpu_bench.py --apply-crossover from green proof
#: captures) is unavailable.  The kernel's unconditional upside is
#: memory: naive materializes the (T, T) score matrix per head
#: (O(T^2) HBM — 2 GiB/head bf16 at 32k, OOM territory), the kernel
#: streams it through VMEM at O(T*d).  Above the crossover the kernel
#: is both the faster and the only-feasible choice; below it, which is
#: faster is a per-chip measurement, not theory.  Override with
#: NNS_TPU_FLASH_MIN_T.
FLASH_MIN_T_DEFAULT = 16384


def _env_min_t():
    """NNS_TPU_FLASH_MIN_T operator override as an int, or None (absent
    or malformed; malformed warns once per call site)."""
    import os

    raw = os.environ.get("NNS_TPU_FLASH_MIN_T")
    if raw:
        try:
            return int(raw)
        except ValueError:
            import warnings

            warnings.warn(f"NNS_TPU_FLASH_MIN_T={raw!r} is not an int; "
                          f"ignoring the override")
    return None


def flash_min_t() -> int:
    env = _env_min_t()
    if env is not None:
        return env
    try:
        from ..utils.tuned import FLASH_MIN_T
        return int(FLASH_MIN_T)
    except Exception:
        return FLASH_MIN_T_DEFAULT


def flash_win_table():
    """Measured ((T, wins), ...) rows (utils/tuned.py FLASH_WIN_TABLE,
    rewritten by flash_tpu_bench --apply-crossover), or () when no
    capture has been applied."""
    try:
        from ..utils.tuned import FLASH_WIN_TABLE
        return tuple(FLASH_WIN_TABLE)
    except Exception:
        return ()


#: Sequence length where naive attention's O(T²) score matrix enters
#: OOM territory regardless of speed (≈2 GiB/head bf16 at 32k — see
#: FLASH_MIN_T_DEFAULT): beyond it the kernel is the only feasible
#: choice, so a measured LOSS at a shorter length stops extrapolating
#: and the threshold gate (memory regime) takes over.
MEM_REGIME_MIN_T = 32768


def _table_verdict(table, t: int):
    """Kernel-vs-naive verdict for length ``t`` from the measured win
    table, or None when the table has no say (empty; ``t`` below its
    first row, where the threshold gate decides; or ``t`` past the
    memory-regime bound, where naive's O(T²) scores stop being feasible
    and the threshold gate's memory fallback takes over).  Within the
    span: an exact hit returns that row; between two measured lengths
    the kernel is selected only when BOTH neighbors won — hardware data
    is non-monotonic in T, and an unmeasured interior length must not
    inherit a win across a loss.  Just ABOVE the span the carry is
    ASYMMETRIC, both directions conservative: a trailing LOSS extends
    (a 0.795x loss measured at 16384 keeps 16385..32767 on the naive
    path instead of falling through to a threshold that would route
    them to the kernel — ADVICE r5) until the memory-regime bound where
    naive stops being feasible; a trailing WIN does not extend (wins
    are non-monotonic in T, so past the evidence the threshold gate
    decides, as ever)."""
    rows = sorted((int(T), bool(w)) for T, w in table)
    if not rows or t < rows[0][0]:
        return None
    if t > rows[-1][0]:
        if not rows[-1][1] and t < MEM_REGIME_MIN_T:
            return False         # measured trailing loss carries
        return None              # threshold / memory gate decides
    below = above = None
    for T, w in rows:
        if T <= t:
            below = (T, w)
        if T >= t and above is None:
            above = (T, w)
    if below[0] == t:
        return below[1]
    return below[1] and above[1]


def flash_wins(t: int) -> bool:
    """Length-gated kernel selection for ``flash=None`` callers doing
    FULL local attention (vit@197, lm@2k): pick the Pallas kernel only
    where measurement says it beats (or memory-obsoletes) naive XLA
    attention.  Layered: the NNS_TPU_FLASH_MIN_T operator override is a
    plain threshold; otherwise the measured per-length win table
    (FLASH_WIN_TABLE) decides inside its span — the r5 hardware data is
    non-monotonic (win@2k/8k, loss@16k), which a threshold cannot
    express — and the FLASH_MIN_T threshold decides outside it.
    Blockwise callers (ring attention) keep selecting the kernel
    directly: their per-block lse-merge and O(T*d) footprint are the
    point, not raw single-block speed."""
    if not flash_is_default():
        return False
    env = _env_min_t()
    if env is not None:
        return t >= env
    verdict = _table_verdict(flash_win_table(), t)
    if verdict is not None:
        return verdict
    return t >= flash_min_t()


def _kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, max_ref,
            sum_ref, *, n_k_blocks: int, causal: bool, q_offset: int,
            k_offset: int, scale: float, kv_len: int = 0):
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    j = pl.program_id(2)
    q = q_ref[0].astype(jnp.float32)           # (bq, d)
    block_q, d = q.shape

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        max_ref[...] = jnp.full_like(max_ref, _NEG_INF)
        sum_ref[...] = jnp.zeros_like(sum_ref)

    k_blk = k_ref[0].astype(jnp.float32)       # (bk, d)
    v_blk = v_ref[0].astype(jnp.float32)
    block_k = k_blk.shape[0]

    def _accumulate():
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if kv_len:
            # K/V were zero-padded up to a block multiple: mask the
            # padded tail (local positions >= the real length)
            k_local = j * block_k + jax.lax.iota(jnp.int32, block_k)
            s = jnp.where(k_local[None, :] >= kv_len, _NEG_INF, s)
        if causal:
            q_idx = (q_offset + iq * block_q
                     + jax.lax.iota(jnp.int32, block_q))
            k_idx = (k_offset + j * block_k
                     + jax.lax.iota(jnp.int32, block_k))
            s = jnp.where(k_idx[None, :] > q_idx[:, None], _NEG_INF, s)
        row_max = max_ref[:, 0]
        row_sum = sum_ref[:, 0]
        blk_max = jnp.max(s, axis=-1)
        new_max = jnp.maximum(row_max, blk_max)
        safe_max = jnp.where(jnp.isfinite(new_max), new_max, 0.0)
        p = jnp.exp(s - safe_max[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        corr = jnp.exp(jnp.where(jnp.isfinite(row_max),
                                 row_max - safe_max, _NEG_INF))
        corr = jnp.where(jnp.isfinite(corr), corr, 0.0)
        acc_ref[...] = acc_ref[...] * corr[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        max_ref[:, 0] = new_max
        sum_ref[:, 0] = row_sum * corr + jnp.sum(p, axis=-1)

    if causal:
        # causal block skip: a K block strictly in THIS q-block's future
        # is all-masked — skip both matmuls (the standard flash
        # optimization; ~half the inner-grid work for self-attention)
        live = (k_offset + j * block_k
                <= q_offset + (iq + 1) * block_q - 1)
        pl.when(live)(_accumulate)
    else:
        _accumulate()

    @pl.when(j == n_k_blocks - 1)
    def _finalize():
        row_sum = sum_ref[:, 0]
        denom = jnp.maximum(row_sum, 1e-20)
        o_ref[0] = (acc_ref[...] / denom[:, None]).astype(o_ref.dtype)
        # logsumexp per row — the only forward residual the streaming
        # backward needs (fully-masked rows: -inf)
        lse_ref[0] = jnp.where(
            row_sum > 0, max_ref[:, 0] + jnp.log(denom),
            _NEG_INF)[:, None]


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int,
                   q_offset: int, k_offset: int, interpret: bool,
                   return_lse: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t_q, h, d = q.shape
    t_kv = k.shape[0]
    # tile choice: never shrink below the 8-row sublane granule — a T that
    # doesn't divide the tile is PADDED up to a block multiple instead
    # (an odd/prime T used to collapse blocks to 1-row tiles: a severe
    # MXU perf cliff and a Mosaic shape the tests never exercised)
    block_q = min(block_q, _round_up(t_q, 8))
    block_k = min(block_k, _round_up(t_kv, 8))
    t_q_pad = _round_up(t_q, block_q)
    t_kv_pad = _round_up(t_kv, block_k)
    if t_q_pad != t_q:
        q = jnp.pad(q, ((0, t_q_pad - t_q), (0, 0), (0, 0)))
    if t_kv_pad != t_kv:
        k = jnp.pad(k, ((0, t_kv_pad - t_kv), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, t_kv_pad - t_kv), (0, 0), (0, 0)))
    n_k_blocks = t_kv_pad // block_k

    qh = jnp.transpose(q, (1, 0, 2))   # (H, Tq, D)
    kh = jnp.transpose(k, (1, 0, 2))
    vh = jnp.transpose(v, (1, 0, 2))
    scale = 1.0 / float(d) ** 0.5

    kern = functools.partial(_kernel, n_k_blocks=n_k_blocks, causal=causal,
                             q_offset=q_offset, k_offset=k_offset,
                             scale=scale,
                             kv_len=t_kv if t_kv_pad != t_kv else 0)
    out, lse = pl.pallas_call(
        kern,
        grid=(h, t_q_pad // block_q, n_k_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda hh, qq, kk: (hh, qq, 0)),
            pl.BlockSpec((1, block_k, d), lambda hh, qq, kk: (hh, kk, 0)),
            pl.BlockSpec((1, block_k, d), lambda hh, qq, kk: (hh, kk, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda hh, qq, kk: (hh, qq, 0)),
            pl.BlockSpec((1, block_q, 1), lambda hh, qq, kk: (hh, qq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((h, t_q_pad, d), q.dtype),
            jax.ShapeDtypeStruct((h, t_q_pad, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qh, kh, vh)
    out = jnp.transpose(out, (1, 0, 2))[:t_q]
    if return_lse:
        return out, lse[:, :t_q, 0]            # (H, Tq)
    return out


def _recompute_p(q, k, lse, j, iq, block_q, block_k, causal, q_offset,
                 k_offset, scale, kv_len):
    """Shared backward recompute of one (bq, bk) probability tile from
    the saved logsumexp: p = exp(s − lse).  Masked positions and
    fully-masked rows (lse = −inf) come out exactly 0."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    block_q_, block_k_ = s.shape
    if kv_len:
        k_local = j * block_k + jax.lax.iota(jnp.int32, block_k_)
        s = jnp.where(k_local[None, :] >= kv_len, _NEG_INF, s)
    if causal:
        q_idx = q_offset + iq * block_q + jax.lax.iota(jnp.int32, block_q_)
        k_idx = k_offset + j * block_k + jax.lax.iota(jnp.int32, block_k_)
        s = jnp.where(k_idx[None, :] > q_idx[:, None], _NEG_INF, s)
    p = jnp.exp(s - lse[:, None])
    return s, jnp.where(jnp.isfinite(lse)[:, None] & jnp.isfinite(s),
                        p, 0.0)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, acc_ref, *, n_k_blocks: int, causal: bool,
                   q_offset: int, k_offset: int, scale: float,
                   kv_len: int = 0):
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    j = pl.program_id(2)
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _accumulate():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        _, p = _recompute_p(q, k, lse_ref[0][:, 0], j, iq, block_q,
                            block_k, causal, q_offset, k_offset, scale,
                            kv_len)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        acc_ref[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        live = (k_offset + j * block_k
                <= q_offset + (iq + 1) * block_q - 1)
        pl.when(live)(_accumulate)
    else:
        _accumulate()

    @pl.when(j == n_k_blocks - 1)
    def _finalize():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, n_q_blocks: int,
                    causal: bool, q_offset: int, k_offset: int,
                    scale: float, kv_len: int = 0):
    from jax.experimental import pallas as pl

    jk = pl.program_id(1)          # K block (outer)
    iq = pl.program_id(2)          # Q block (inner, sequential)
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]

    @pl.when(iq == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _accumulate():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        _, p = _recompute_p(q, k, lse_ref[0][:, 0], jk, iq, block_q,
                            block_k, causal, q_offset, k_offset, scale,
                            kv_len)
        dv_acc[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # a Q block entirely in THIS k-block's past is all-masked
        live = (q_offset + (iq + 1) * block_q - 1
                >= k_offset + jk * block_k)
        pl.when(live)(_accumulate)
    else:
        _accumulate()

    @pl.when(iq == n_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, do, lse, delta, causal, block_q, block_k,
                    q_offset, k_offset, interpret):
    """Streaming flash backward: dq, dk, dv without ever materializing a
    (Tq, Tkv) matrix in HBM — VMEM holds one (bq, bk) tile recomputed
    from the saved logsumexp (FlashAttention-2 backward structure)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t_q, h, d = q.shape
    t_kv = k.shape[0]
    block_q = min(block_q, _round_up(t_q, 8))
    block_k = min(block_k, _round_up(t_kv, 8))
    t_q_pad = _round_up(t_q, block_q)
    t_kv_pad = _round_up(t_kv, block_k)
    if t_q_pad != t_q:
        pad = ((0, t_q_pad - t_q), (0, 0), (0, 0))
        q = jnp.pad(q, pad)
        do = jnp.pad(do, pad)
        # padded Q rows: lse = -inf makes their p tiles exactly 0, so
        # they contribute nothing to dk/dv
        lse = jnp.pad(lse, ((0, 0), (0, t_q_pad - t_q)),
                      constant_values=_NEG_INF)
        delta = jnp.pad(delta, ((0, 0), (0, t_q_pad - t_q)))
    if t_kv_pad != t_kv:
        pad = ((0, t_kv_pad - t_kv), (0, 0), (0, 0))
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    n_q_blocks = t_q_pad // block_q
    n_k_blocks = t_kv_pad // block_k
    scale = 1.0 / float(d) ** 0.5
    kv_len = t_kv if t_kv_pad != t_kv else 0

    qh = jnp.transpose(q, (1, 0, 2))
    kh = jnp.transpose(k, (1, 0, 2))
    vh = jnp.transpose(v, (1, 0, 2))
    doh = jnp.transpose(do, (1, 0, 2))
    lseh = lse[..., None]                      # (H, Tq, 1)
    deltah = delta[..., None]

    common = dict(causal=causal, q_offset=q_offset, k_offset=k_offset,
                  scale=scale, kv_len=kv_len)
    q_spec = pl.BlockSpec((1, block_q, d), lambda hh, a, b: (hh, a, 0))
    q1_spec = pl.BlockSpec((1, block_q, 1), lambda hh, a, b: (hh, a, 0))
    k_spec = pl.BlockSpec((1, block_k, d), lambda hh, a, b: (hh, b, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, n_k_blocks=n_k_blocks, **common),
        grid=(h, n_q_blocks, n_k_blocks),
        in_specs=[q_spec, k_spec, k_spec, q_spec, q1_spec, q1_spec],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda hh, a, b: (hh, a, 0)),
        out_shape=jax.ShapeDtypeStruct((h, t_q_pad, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qh, kh, vh, doh, lseh, deltah)

    # dkv grid: K blocks outer, Q blocks inner (sequential accumulation)
    qi_spec = pl.BlockSpec((1, block_q, d), lambda hh, a, b: (hh, b, 0))
    qi1_spec = pl.BlockSpec((1, block_q, 1), lambda hh, a, b: (hh, b, 0))
    ki_spec = pl.BlockSpec((1, block_k, d), lambda hh, a, b: (hh, a, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, n_q_blocks=n_q_blocks,
                          **common),
        grid=(h, n_k_blocks, n_q_blocks),
        in_specs=[qi_spec, ki_spec, ki_spec, qi_spec, qi1_spec, qi1_spec],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda hh, a, b: (hh, a, 0)),
            pl.BlockSpec((1, block_k, d), lambda hh, a, b: (hh, a, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((h, t_kv_pad, d), jnp.float32),
            jax.ShapeDtypeStruct((h, t_kv_pad, d), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(qh, kh, vh, doh, lseh, deltah)

    dq = jnp.transpose(dq, (1, 0, 2))[:t_q].astype(q.dtype)
    dk = jnp.transpose(dk, (1, 0, 2))[:t_kv].astype(k.dtype)
    dv = jnp.transpose(dv, (1, 0, 2))[:t_kv].astype(v.dtype)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, block_q, block_k, q_offset, k_offset,
           interpret):
    return _flash_forward(q, k, v, causal, block_q, block_k, q_offset,
                          k_offset, interpret)


def _flash_fwd(q, k, v, causal, block_q, block_k, q_offset, k_offset,
               interpret):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, q_offset,
                              k_offset, interpret, return_lse=True)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, q_offset, k_offset, interpret,
               res, do):
    q, k, v, out, lse = res
    # D_i = dO_i · O_i, the softmax-backward row correction
    delta = jnp.transpose(jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1))
    return _flash_backward(q, k, v, do, lse, delta, causal, block_q,
                           block_k, q_offset, k_offset, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_lse(q, k, v, causal, block_q, block_k, q_offset, k_offset,
               interpret):
    """(out, lse) variant for blockwise callers (ring attention) that
    combine blocks through the logsumexp."""
    return _flash_forward(q, k, v, causal, block_q, block_k, q_offset,
                          k_offset, interpret, return_lse=True)


def _flash_lse_fwd(q, k, v, causal, block_q, block_k, q_offset, k_offset,
                   interpret):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, q_offset,
                              k_offset, interpret, return_lse=True)
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_bwd(causal, block_q, block_k, q_offset, k_offset, interpret,
                   res, cots):
    q, k, v, out, lse = res
    do, dlse = cots
    # lse_i = logsumexp(s_i·) has dlse/ds_ij = p_ij, so its cotangent
    # folds into the delta term: ds = p·(dp − (delta − ḡ_lse))
    delta = jnp.transpose(jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1))
    delta = delta - dlse.astype(jnp.float32)
    return _flash_backward(q, k, v, do, lse, delta, causal, block_q,
                           block_k, q_offset, k_offset, interpret)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def _default_tiles(t_q: int, t_kv: int, interpret: bool):
    """Tile defaults, measured-data first (the 16k grid-overhead lesson:
    at (128, 128) a causal 16k forward is h·128·128 ≈ 131k Mosaic grid
    steps ≈ 50 ms of pure dispatch — the measured 0.795× loss — while
    both matmuls cost ~3 ms; the cure is fewer, larger tiles, but a tile
    that never passed the on-chip gradcheck must not become the
    custom_vjp default, so larger tiles ship only via measured records).
    Precedence: the per-length FLASH_TILES_BY_T record (largest measured
    length ≤ the sequence, when both lengths cover its tile), then the
    legacy single FLASH_TILES record, then the 128x128 MXU-shaped
    default (a tiny input must not pad up to a giant tuned tile — and
    the interpreter has no tuned data)."""
    if not interpret:
        from ..utils.tuned import FLASH_TILES, FLASH_TILES_BY_T

        t = max(t_q, t_kv)
        for rec_t, bq, bk in sorted(FLASH_TILES_BY_T, reverse=True):
            if t >= rec_t and t_q >= bq and t_kv >= bk:
                return int(bq), int(bk)
        bq, bk = FLASH_TILES
        if t_q >= bq and t_kv >= bk:
            return int(bq), int(bk)
    return 128, 128


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = False, block_q: Optional[int] = None,
                    block_k: Optional[int] = None, q_offset: int = 0,
                    k_offset: int = 0,
                    interpret: Optional[bool] = None,
                    return_lse: bool = False):
    """Exact attention via the Pallas streaming-softmax kernel.

    Args:
      q: (T_q, H, D); k, v: (T_kv, H, D) — same layout as
        :func:`parallel.ring_attention.local_attention`.
      causal: mask ``k_pos > q_pos`` using global positions
        ``q_offset + i`` / ``k_offset + j`` (offsets let blockwise callers
        keep global causality).
      interpret: force the Pallas interpreter (CPU); default: interpret
        off on TPU, on elsewhere.

    Differentiable (custom VJP: flash forward, streaming flash backward).
    Sequence lengths that don't divide the tile are zero-padded up to a
    block multiple (padded K positions masked, padded Q rows sliced off)
    — tiles never shrink below the 8-row sublane granule.

    ``return_lse``: also return the per-row logsumexp (H, T_q) — the
    residual blockwise callers (ring attention) need to merge block
    outputs; both outputs stay differentiable (the lse cotangent folds
    into the backward's delta term).
    """
    if interpret is None:
        interpret = not flash_is_default()
    if block_q is None or block_k is None:
        dbq, dbk = _default_tiles(q.shape[0], k.shape[0], interpret)
        block_q = dbq if block_q is None else block_q
        block_k = dbk if block_k is None else block_k
    if return_lse:
        return _flash_lse(q, k, v, causal, block_q, block_k, q_offset,
                          k_offset, interpret)
    return _flash(q, k, v, causal, block_q, block_k, q_offset, k_offset,
                  interpret)
