"""DeepLabV3 semantic segmentation — benchmark config 3.

Parity with the reference fixture ``deeplabv3_257_mv_gpu.tflite`` consumed by
the ``image_segment`` decoder (reference:
ext/nnstreamer/tensor_decoder/tensordec-imagesegment.c, tflite-deeplab mode:
output is per-pixel class scores (21 × W × H), decoder takes argmax).

TPU-first: MobileNetV2 backbone + ASPP-lite head, bf16, bilinear upsample
inside the jitted graph.
"""

from __future__ import annotations

from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..tensor.info import TensorInfo, TensorsInfo
from ..tensor.types import TensorType
from .mobilenet_v2 import _ConvBN, _InvertedResidual, _INVERTED_RESIDUAL_CFG
from .registry import Model, host_init, register_model

NUM_SEG_CLASSES = 21  # PASCAL VOC, same as the tflite fixture


class _DeepLabV3(nn.Module):
    num_classes: int = NUM_SEG_CLASSES
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        size = x.shape[0]
        # Backbone at output-stride 16 (stop before the last stride-2 stage).
        x = _ConvBN(32, (3, 3), strides=2, dtype=self.dtype)(x[None])
        for t, ch, n, s in _INVERTED_RESIDUAL_CFG[:5]:
            for i in range(n):
                x = _InvertedResidual(ch, s if i == 0 else 1, t,
                                      dtype=self.dtype)(x)
        # ASPP-lite: 1x1 conv + global pooling branch.
        a = _ConvBN(256, (1, 1), dtype=self.dtype)(x)
        g = jnp.mean(x, axis=(1, 2), keepdims=True)
        g = _ConvBN(256, (1, 1), dtype=self.dtype)(g)
        g = jnp.broadcast_to(g, a.shape)
        y = _ConvBN(256, (1, 1), dtype=self.dtype)(
            jnp.concatenate([a, g], axis=-1))
        y = nn.Conv(self.num_classes, (1, 1), dtype=self.dtype)(y)
        y = jax.image.resize(y.astype(jnp.float32),
                             (1, size, size, self.num_classes), "bilinear")
        return y[0]


def build_deeplab_v3(custom_props: Dict[str, str]) -> Model:
    seed = int(custom_props.get("seed", 0))
    size = int(custom_props.get("input_size", 257))
    dtype = jnp.dtype(custom_props.get("dtype", "bfloat16"))
    module = _DeepLabV3(dtype=dtype)
    variables = host_init(lambda: module.init(
        jax.random.PRNGKey(seed), jnp.zeros((size, size, 3), dtype)))

    def forward(variables, frame):
        x = frame.astype(dtype) * (1.0 / 127.5) - 1.0
        return (module.apply(variables, x),)

    in_info = TensorsInfo([TensorInfo(TensorType.UINT8, (3, size, size))])
    out_info = TensorsInfo(
        [TensorInfo(TensorType.FLOAT32, (NUM_SEG_CLASSES, size, size))])
    return Model(name="deeplab_v3", forward=forward, params=variables,
                 in_info=in_info, out_info=out_info)


register_model("deeplab_v3")(build_deeplab_v3)
