"""StreamFormer LM serving: full-sequence forward + KV-cache decoding.

The training side lives in parallel/train_step.py (sharded over
dp/sp/tp/ep).  This module is the single-device SERVING path for the same
parameter tree: a full-sequence forward for pipeline use (registry model
``streamformer_lm`` → ``tensor_filter framework=xla``), and an
incremental decode step with a static-shape KV cache for token streaming
— `lax`-friendly (fixed ``max_seq`` cache, position index, one
``dynamic_update_slice`` per layer), so the whole generate loop is ONE
compiled ``lax.scan``.

Consistency contract (tested): decoding token-by-token through the cache
reproduces the full-sequence forward's logits at every position, and the
full forward matches the training forward (shard_map on a 1-device mesh)
— params trained with make_train_step serve unchanged.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.train_step import (StreamFormerConfig, _ln,
                                   init_params)


def _moe_dense(y, lyr, cfg: StreamFormerConfig):
    """Top-1 routed MoE for serving: per-token expert selection with a
    dense einsum over ALL experts masked to the chosen one (E is small;
    no capacity cap at serving — every token runs its expert)."""
    gate = jnp.einsum("...d,de->...e", y.astype(jnp.float32),
                      lyr["gate"].astype(jnp.float32))
    probs = jax.nn.softmax(gate, axis=-1)
    choice = jnp.argmax(probs, axis=-1)                      # (...,)
    onehot = jax.nn.one_hot(choice, cfg.experts, dtype=y.dtype)
    scale = jnp.take_along_axis(probs, choice[..., None],
                                axis=-1)[..., 0].astype(y.dtype)
    h = jax.nn.gelu(jnp.einsum("...d,edf->...ef", y,
                               lyr["we1"].astype(y.dtype)))
    out = jnp.einsum("...ef,efd->...ed", h, lyr["we2"].astype(y.dtype))
    picked = jnp.einsum("...ed,...e->...d", out, onehot)
    return picked * scale[..., None]


def forward_logits(params: Dict[str, Any], tokens: jnp.ndarray,
                   cfg: StreamFormerConfig,
                   flash: "bool | None" = None) -> jnp.ndarray:
    """Full-sequence forward: tokens (T,) int32 → logits (T, vocab).
    Same math as the training forward (single device, causal).

    ``flash``: run attention as the Pallas streaming-softmax kernel
    (ops/flash_attention.py) — the long-prompt prefill path never
    materializes (T, T) scores.  Default: length-gated on TPU
    (flash_wins): each prefill length takes whichever path the
    measured win table / crossover records say is faster there (the
    r5 capture routes 2k prefills to the kernel at 1.365×)."""
    t = tokens.shape[0]
    if flash is None:
        from ..ops.flash_attention import flash_wins

        flash = flash_wins(t)
    pos = jnp.arange(t)
    x = (params["embed"][tokens] + params["pos"][pos]).astype(cfg.dtype)
    for lyr in params["layers"]:
        y = _ln(x.astype(jnp.float32), lyr["ln1"]).astype(cfg.dtype)
        qkv = jnp.einsum("td,dchn->tchn", y, lyr["wqkv"].astype(cfg.dtype))
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        if flash:
            from ..ops.flash_attention import flash_attention

            attn = flash_attention(q, k, v, causal=True)
        else:
            from ..parallel.ring_attention import local_attention

            attn = local_attention(q, k, v, causal=True)
        o = jnp.einsum("qhd,hdn->qn", attn.astype(cfg.dtype),
                       lyr["wo"].astype(cfg.dtype))
        x = x + o
        y = _ln(x.astype(jnp.float32), lyr["ln2"]).astype(cfg.dtype)
        m = jnp.einsum("td,df->tf", y, lyr["w1"].astype(cfg.dtype))
        m = jnp.einsum("tf,fd->td", jax.nn.gelu(m),
                       lyr["w2"].astype(cfg.dtype))
        x = x + m + _moe_dense(y, lyr, cfg)
    x = _ln(x.astype(jnp.float32), params["ln_f"])
    return jnp.einsum("td,dv->tv", x, params["head"])


def config_from_custom(custom: Dict[str, Any],
                       default_seq: int = 64) -> StreamFormerConfig:
    """The ``custom=`` sizing grammar, shared by the registry builder and
    the LLM serving tier (``nnstreamer_tpu/llm/``) — the same
    parameterization discipline ``models/mlp.py`` established, so a soak
    server sizes a realistically heavy decoder from the launch line
    alone::

        custom=layers:8,width:512,heads:8,head_dim:64,max_seq:1024

    Keys: ``vocab`` ``dim``/``width`` (aliases) ``heads`` ``head_dim``
    ``mlp`` ``layers`` ``experts`` ``max_seq`` ``dtype`` (``seq`` — the
    registry filter's window length — and ``seed`` stay with their
    callers).  ``max_seq`` defaults to ``max(seq, 64)`` for the
    full-sequence filter's historical sizing; the decode tier sets it
    explicitly (its KV-cache memory is ``slots x layers x max_seq x
    heads x head_dim x 2``, the bound the cache pool enforces)."""
    if "dim" in custom and "width" in custom \
            and str(custom["dim"]) != str(custom["width"]):
        raise ValueError("streamformer_lm: custom dim and width are "
                         "aliases; give one")
    # ``seq`` is the full-sequence FILTER's window length; the decode
    # tier never sets it (its sequence axis is the cache), so the
    # window-fits-cache validation only applies when a caller names it
    seq = int(custom["seq"]) if "seq" in custom else int(default_seq)
    cfg = StreamFormerConfig(
        vocab=int(custom.get("vocab", 256)),
        dim=int(custom.get("dim", custom.get("width", 128))),
        heads=int(custom.get("heads", 8)),
        head_dim=int(custom.get("head_dim", 16)),
        mlp=int(custom.get("mlp", 512)),
        layers=int(custom.get("layers", 2)),
        experts=int(custom.get("experts", 2)),
        max_seq=int(custom.get("max_seq", max(seq, 64))),
        dtype=jnp.dtype(custom.get("dtype", "bfloat16")))
    if min(cfg.vocab, cfg.dim, cfg.heads, cfg.head_dim, cfg.mlp,
           cfg.layers, cfg.experts, cfg.max_seq) < 1:
        raise ValueError(
            "streamformer_lm: vocab/dim/heads/head_dim/mlp/layers/"
            "experts/max_seq must all be >= 1")
    if "seq" in custom and cfg.max_seq < seq:
        raise ValueError(
            f"streamformer_lm: max_seq={cfg.max_seq} < seq={seq}: the "
            "KV cache could not hold one full input window")
    return cfg


def init_cache(cfg: StreamFormerConfig) -> Dict[str, jnp.ndarray]:
    """Static-shape KV cache: (layers, max_seq, heads, head_dim)."""
    L = cfg.layers
    shape = (L, cfg.max_seq, cfg.heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype),
            "pos": jnp.zeros((), jnp.int32)}


def prefill_kv(params: Dict[str, Any], tokens: jnp.ndarray,
               cfg: StreamFormerConfig, flash: "bool | None" = None
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Full-prompt prefill for the KV-cache serving tier: one
    full-sequence forward (the :func:`forward_logits` math, length-gated
    onto the Pallas flash kernel so long prompts never materialize
    (T, T) scores) that ALSO returns every layer's keys/values —
    ``tokens (T,) int32 → (logits (T, vocab) f32, k (L, T, H, Dh),
    v (L, T, H, Dh))`` in ``cfg.dtype``.

    A prompt prefilled here and continued through
    :func:`decode_step` / :func:`decode_step_pooled` produces the same
    logits as scanning :func:`decode_step` over the whole prompt — at
    full-sequence GEMM arithmetic intensity instead of T GEMV steps
    (the consistency contract tests/test_llm.py pins)."""
    t = tokens.shape[0]
    if flash is None:
        from ..ops.flash_attention import flash_wins

        flash = flash_wins(t)
    pos = jnp.arange(t)
    x = (params["embed"][tokens] + params["pos"][pos]).astype(cfg.dtype)
    ks, vs = [], []
    for lyr in params["layers"]:
        y = _ln(x.astype(jnp.float32), lyr["ln1"]).astype(cfg.dtype)
        qkv = jnp.einsum("td,dchn->tchn", y, lyr["wqkv"].astype(cfg.dtype))
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        ks.append(k)
        vs.append(v)
        if flash:
            from ..ops.flash_attention import flash_attention

            attn = flash_attention(q, k, v, causal=True)
        else:
            from ..parallel.ring_attention import local_attention

            attn = local_attention(q, k, v, causal=True)
        o = jnp.einsum("qhd,hdn->qn", attn.astype(cfg.dtype),
                       lyr["wo"].astype(cfg.dtype))
        x = x + o
        y = _ln(x.astype(jnp.float32), lyr["ln2"]).astype(cfg.dtype)
        m = jnp.einsum("td,df->tf", y, lyr["w1"].astype(cfg.dtype))
        m = jnp.einsum("tf,fd->td", jax.nn.gelu(m),
                       lyr["w2"].astype(cfg.dtype))
        x = x + m + _moe_dense(y, lyr, cfg)
    x = _ln(x.astype(jnp.float32), params["ln_f"])
    logits = jnp.einsum("td,dv->tv", x, params["head"])
    return logits, jnp.stack(ks), jnp.stack(vs)


def decode_step_pooled(params: Dict[str, Any], k_pool: jnp.ndarray,
                       v_pool: jnp.ndarray, tokens: jnp.ndarray,
                       pos: jnp.ndarray, slots: jnp.ndarray,
                       cfg: StreamFormerConfig
                       ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One continuous-batching decode step over a SLOT-POOLED cache:
    ``B`` resident sequences — each at its own position, each owning one
    cache slot — advance together through one batched invoke.

    - ``k_pool``/``v_pool``: ``(S, L, max_seq, H, Dh)`` — the whole
      session pool's cache, ``S`` static slots (the llm/ tier's bounded
      memory: nothing here ever allocates per-sequence);
    - ``tokens``/``pos``/``slots``: ``(B,) int32`` — this step's token,
      position and cache-slot id per lane.  Padding lanes (partial
      buckets) point at a caller-reserved scratch slot, so their
      scatter writes can never touch a live session;
    - returns ``(logits (B, vocab) f32, k_pool', v_pool')``.

    Same math as :func:`decode_step` (scatter the new K/V at
    ``(slot, layer, pos)``, attend the single query against the slot's
    prefix, positions beyond ``pos`` masked) — lane *i* of this step
    equals a solo :func:`decode_step` on slot *i*'s cache, which is the
    correctness spine the batched serving tier rests on.  The batched
    shape is the point: B GEMV-shaped single-token steps become one
    GEMM-shaped step (the PR 9 padded-bucket economics, applied to the
    decode loop), and ONE executable per padded B serves every fill."""
    x = (params["embed"][tokens] + params["pos"][pos]).astype(cfg.dtype)
    valid = jnp.arange(cfg.max_seq)[None, :] <= pos[:, None]   # (B, T)
    scale = 1.0 / jnp.sqrt(jnp.asarray(cfg.head_dim, jnp.float32))
    for li, lyr in enumerate(params["layers"]):
        y = _ln(x.astype(jnp.float32), lyr["ln1"]).astype(cfg.dtype)
        qkv = jnp.einsum("bd,dchn->bchn", y,
                         lyr["wqkv"].astype(cfg.dtype))
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]          # (B, H, Dh)
        li_ix = jnp.full_like(slots, li)
        k_pool = k_pool.at[slots, li_ix, pos].set(k)
        v_pool = v_pool.at[slots, li_ix, pos].set(v)
        kcur = k_pool[slots, li_ix]                 # (B, max_seq, H, Dh)
        vcur = v_pool[slots, li_ix]
        s = jnp.einsum("bhd,bthd->bht", q.astype(jnp.float32),
                       kcur.astype(jnp.float32)) * scale
        s = jnp.where(valid[:, None, :], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        attn = jnp.einsum("bht,bthd->bhd", p,
                          vcur.astype(jnp.float32))
        o = jnp.einsum("bhd,hdn->bn", attn.astype(cfg.dtype),
                       lyr["wo"].astype(cfg.dtype))
        x = x + o
        y = _ln(x.astype(jnp.float32), lyr["ln2"]).astype(cfg.dtype)
        m = jnp.einsum("bd,df->bf", y, lyr["w1"].astype(cfg.dtype))
        m = jnp.einsum("bf,fd->bd", jax.nn.gelu(m),
                       lyr["w2"].astype(cfg.dtype))
        x = x + m + _moe_dense(y, lyr, cfg)
    x = _ln(x.astype(jnp.float32), params["ln_f"])
    return (jnp.einsum("bd,dv->bv", x, params["head"]),
            k_pool, v_pool)


def decode_step_paged(params: Dict[str, Any], k_pages: jnp.ndarray,
                      v_pages: jnp.ndarray, tokens: jnp.ndarray,
                      pos: jnp.ndarray, tables: jnp.ndarray,
                      cfg: StreamFormerConfig, page_size: int
                      ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One continuous-batching decode step over a BLOCK-PAGED cache:
    the vLLM/PagedAttention layout, where a session's cache is a chain
    of fixed-size pages named by a block table instead of one dense
    ``max_seq`` lane.

    - ``k_pages``/``v_pages``: ``(P, L, page_size, H, Dh)`` — ONE fixed
      arena shared by every session; a page belongs to whichever block
      table names it.  The last page is the caller's scratch page;
    - ``tokens``/``pos``: ``(B,) int32`` per lane, as in
      :func:`decode_step_pooled`;
    - ``tables``: ``(B, W) int32`` — each lane's block table, pages in
      sequence order (page ``j`` holds positions ``[j*page_size,
      (j+1)*page_size)``).  ``W`` must satisfy ``W*page_size > max(pos)``
      (the caller pow2-quantizes it so the executable set stays
      bounded); entries past a lane's allocated pages — and every entry
      of a padding lane — point at the scratch page;
    - returns ``(logits (B, vocab) f32, k_pages', v_pages')``.

    Per layer: scatter-append the new K/V into the TAIL page
    (``tables[b, pos//page_size]`` at offset ``pos % page_size``),
    gather the lane's pages back as one ``(W*page_size,)`` run and
    attend with the same causal-prefix mask as the dense step — lane
    *i* equals a solo :func:`decode_step` on the same history, the
    correctness spine the paged pool rests on.  The arena is donated by
    the engine exactly like the dense pool (the in-place-update
    discipline: without donation the WHOLE arena copies per step)."""
    ps = int(page_size)
    b, w = tables.shape
    span = w * ps
    x = (params["embed"][tokens] + params["pos"][pos]).astype(cfg.dtype)
    valid = jnp.arange(span)[None, :] <= pos[:, None]      # (B, W*ps)
    scale = 1.0 / jnp.sqrt(jnp.asarray(cfg.head_dim, jnp.float32))
    # tail-page coordinates for this step's scatter-append
    wpage = jnp.take_along_axis(tables, (pos // ps)[:, None],
                                axis=1)[:, 0]              # (B,)
    woff = pos % ps
    for li, lyr in enumerate(params["layers"]):
        y = _ln(x.astype(jnp.float32), lyr["ln1"]).astype(cfg.dtype)
        qkv = jnp.einsum("bd,dchn->bchn", y,
                         lyr["wqkv"].astype(cfg.dtype))
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]          # (B, H, Dh)
        li_ix = jnp.full_like(wpage, li)
        k_pages = k_pages.at[wpage, li_ix, woff].set(k)
        v_pages = v_pages.at[wpage, li_ix, woff].set(v)
        kcur = k_pages[tables, li].reshape(
            b, span, cfg.heads, cfg.head_dim)              # page gather
        vcur = v_pages[tables, li].reshape(
            b, span, cfg.heads, cfg.head_dim)
        s = jnp.einsum("bhd,bthd->bht", q.astype(jnp.float32),
                       kcur.astype(jnp.float32)) * scale
        s = jnp.where(valid[:, None, :], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        attn = jnp.einsum("bht,bthd->bhd", p,
                          vcur.astype(jnp.float32))
        o = jnp.einsum("bhd,hdn->bn", attn.astype(cfg.dtype),
                       lyr["wo"].astype(cfg.dtype))
        x = x + o
        y = _ln(x.astype(jnp.float32), lyr["ln2"]).astype(cfg.dtype)
        m = jnp.einsum("bd,df->bf", y, lyr["w1"].astype(cfg.dtype))
        m = jnp.einsum("bf,fd->bd", jax.nn.gelu(m),
                       lyr["w2"].astype(cfg.dtype))
        x = x + m + _moe_dense(y, lyr, cfg)
    x = _ln(x.astype(jnp.float32), params["ln_f"])
    return (jnp.einsum("bd,dv->bv", x, params["head"]),
            k_pages, v_pages)


def prefill_chunk_paged(params: Dict[str, Any], k_pages: jnp.ndarray,
                        v_pages: jnp.ndarray, tokens: jnp.ndarray,
                        table: jnp.ndarray, start: jnp.ndarray,
                        true_len: jnp.ndarray, cfg: StreamFormerConfig,
                        page_size: int, scratch: int
                        ) -> Tuple[jnp.ndarray, jnp.ndarray,
                                   jnp.ndarray]:
    """One bounded prefill CHUNK for a paged session: process ``C``
    prompt tokens starting at absolute position ``start``, writing
    their K/V into the session's pages and attending over everything
    the pages already hold (a cached/shared prefix, earlier chunks) plus
    the chunk itself — causally, so chaining chunks reproduces the
    full-prompt prefill's math.

    - ``tokens (C,) int32``: the chunk, zero-padded past ``true_len``;
    - ``table (W,) int32``: the session's block table, scratch-padded;
      ``W*page_size >= start + C`` (caller-quantized);
    - ``start ()`` / ``true_len ()`` int32: chunk origin and real
      length — traced operands, so ONE ``(C, W)`` executable serves
      every chunk of every prompt at every prefix-hit offset;
    - ``scratch``: the arena's scratch page id (static) — padding
      queries' writes land there;
    - returns ``(last_logits (vocab,), k_pages', v_pages')`` where
      ``last_logits`` is position ``start + true_len - 1``'s row — the
      final chunk's caller argmaxes it into the session's first token.

    This one function is BOTH levers pages buy: chunked prefill (the
    engine interleaves these between decode steps so a long prompt
    cannot stall resident streams) and prefix-cache suffix completion
    (a prefix hit starts the chunk walk at the shared-page boundary
    instead of position 0)."""
    ps = int(page_size)
    c = tokens.shape[0]
    w = table.shape[0]
    span = w * ps
    qpos = start + jnp.arange(c)                           # (C,) absolute
    qvalid = jnp.arange(c) < true_len
    x = (params["embed"][tokens] + params["pos"][qpos]).astype(cfg.dtype)
    # key position t is visible to chunk query i iff t <= start + i
    kvalid = jnp.arange(span)[None, :] <= qpos[:, None]    # (C, W*ps)
    wpage = jnp.where(qvalid, table[qpos // ps], scratch)  # (C,)
    woff = qpos % ps
    scale = 1.0 / jnp.sqrt(jnp.asarray(cfg.head_dim, jnp.float32))
    for li, lyr in enumerate(params["layers"]):
        y = _ln(x.astype(jnp.float32), lyr["ln1"]).astype(cfg.dtype)
        qkv = jnp.einsum("td,dchn->tchn", y,
                         lyr["wqkv"].astype(cfg.dtype))
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]          # (C, H, Dh)
        li_ix = jnp.full_like(wpage, li)
        k_pages = k_pages.at[wpage, li_ix, woff].set(k)
        v_pages = v_pages.at[wpage, li_ix, woff].set(v)
        kcur = k_pages[table, li].reshape(
            span, cfg.heads, cfg.head_dim)
        vcur = v_pages[table, li].reshape(
            span, cfg.heads, cfg.head_dim)
        s = jnp.einsum("chd,thd->cht", q.astype(jnp.float32),
                       kcur.astype(jnp.float32)) * scale
        s = jnp.where(kvalid[:, None, :], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        attn = jnp.einsum("cht,thd->chd", p,
                          vcur.astype(jnp.float32))
        o = jnp.einsum("chd,hdn->cn", attn.astype(cfg.dtype),
                       lyr["wo"].astype(cfg.dtype))
        x = x + o
        y = _ln(x.astype(jnp.float32), lyr["ln2"]).astype(cfg.dtype)
        m = jnp.einsum("td,df->tf", y, lyr["w1"].astype(cfg.dtype))
        m = jnp.einsum("tf,fd->td", jax.nn.gelu(m),
                       lyr["w2"].astype(cfg.dtype))
        x = x + m + _moe_dense(y, lyr, cfg)
    x = _ln(x.astype(jnp.float32), params["ln_f"])
    logits = jnp.einsum("td,dv->tv", x, params["head"])
    last = jax.lax.dynamic_index_in_dim(logits, true_len - 1, axis=0,
                                        keepdims=False)
    return last, k_pages, v_pages


def decode_step(params: Dict[str, Any], cache: Dict[str, jnp.ndarray],
                token: jnp.ndarray, cfg: StreamFormerConfig
                ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """One incremental step: token () int32 → (logits (vocab,), cache').

    Attention runs the single query against the cache prefix; positions
    beyond ``cache['pos']`` are masked, so the cache array's static
    ``max_seq`` shape never leaks into the math."""
    pos = cache["pos"]
    x = (params["embed"][token] + params["pos"][pos]).astype(cfg.dtype)
    new_k = cache["k"]
    new_v = cache["v"]
    valid = jnp.arange(cfg.max_seq) <= pos                 # causal prefix
    for li, lyr in enumerate(params["layers"]):
        y = _ln(x.astype(jnp.float32), lyr["ln1"]).astype(cfg.dtype)
        qkv = jnp.einsum("d,dchn->chn", y, lyr["wqkv"].astype(cfg.dtype))
        q, k, v = qkv[0], qkv[1], qkv[2]                   # (H, Dh)
        new_k = jax.lax.dynamic_update_slice(
            new_k, k[None, None], (li, pos, 0, 0))
        new_v = jax.lax.dynamic_update_slice(
            new_v, v[None, None], (li, pos, 0, 0))
        scale = 1.0 / jnp.sqrt(jnp.asarray(cfg.head_dim, jnp.float32))
        s = jnp.einsum("hd,thd->ht", q.astype(jnp.float32),
                       new_k[li].astype(jnp.float32)) * scale
        s = jnp.where(valid[None, :], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        attn = jnp.einsum("ht,thd->hd", p,
                          new_v[li].astype(jnp.float32))
        o = jnp.einsum("hd,hdn->n", attn.astype(cfg.dtype),
                       lyr["wo"].astype(cfg.dtype))
        x = x + o
        y = _ln(x.astype(jnp.float32), lyr["ln2"]).astype(cfg.dtype)
        m = jnp.einsum("d,df->f", y, lyr["w1"].astype(cfg.dtype))
        m = jnp.einsum("f,fd->d", jax.nn.gelu(m),
                       lyr["w2"].astype(cfg.dtype))
        x = x + m + _moe_dense(y, lyr, cfg)
    x = _ln(x.astype(jnp.float32), params["ln_f"])
    logits = jnp.einsum("d,dv->v", x, params["head"])
    return logits, {"k": new_k, "v": new_v, "pos": pos + 1}


#: compiled generate programs keyed by (cfg fields, lengths, temperature)
_RUN_CACHE: Dict[tuple, Any] = {}


def _compiled_run(cfg: StreamFormerConfig, n_prompt: int, n_tokens: int,
                  temperature: float):
    key = (tuple(sorted(vars(cfg).items(), key=lambda kv: kv[0],
                        )).__repr__(), n_prompt, n_tokens, temperature)
    fn = _RUN_CACHE.get(key)
    if fn is not None:
        return fn

    @jax.jit
    def run(params, prompt_toks, rng_key):
        cache = init_cache(cfg)

        def prefill(carry, tok):
            cache = carry
            logits, cache = decode_step(params, cache, tok, cfg)
            return cache, logits

        cache, logits_seq = jax.lax.scan(prefill, cache, prompt_toks)
        last_logits = logits_seq[-1]

        def step(carry, _):
            cache, logits, rng_key = carry
            if temperature > 0:
                rng_key, sub = jax.random.split(rng_key)
                tok = jax.random.categorical(sub, logits / temperature)
            else:
                tok = jnp.argmax(logits)
            tok = tok.astype(jnp.int32)
            new_logits, cache = decode_step(params, cache, tok, cfg)
            return (cache, new_logits, rng_key), tok

        _, toks = jax.lax.scan(step, (cache, last_logits, rng_key),
                               None, length=n_tokens)
        return toks

    _RUN_CACHE[key] = run
    return run


def generate(params: Dict[str, Any], cfg: StreamFormerConfig,
             prompt: np.ndarray, n_tokens: int,
             temperature: float = 0.0, seed: int = 0) -> np.ndarray:
    """Greedy (temperature 0) or sampled continuation, fully device-side
    (prefill scan + decode scan); compiled programs are cached per
    (config, lengths, temperature) so repeat calls skip XLA."""
    prompt_arr = jnp.asarray(prompt, jnp.int32)
    total = prompt_arr.shape[0] + n_tokens
    if total > cfg.max_seq:
        raise ValueError(
            f"prompt ({prompt_arr.shape[0]}) + n_tokens ({n_tokens}) = "
            f"{total} exceeds max_seq={cfg.max_seq}: the KV cache would "
            "clamp positions and silently corrupt the continuation")
    run = _compiled_run(cfg, prompt_arr.shape[0], n_tokens, temperature)
    return np.asarray(run(params, prompt_arr, jax.random.PRNGKey(seed)))


def _build_registry_model(custom_props):
    """``framework=xla model=streamformer_lm``: full-sequence next-token
    logits as a pipeline filter — tokens in (T,) int32, logits out
    (T, vocab) float32."""
    from .registry import Model, host_init
    from ..tensor.info import TensorInfo, TensorsInfo
    from ..tensor.types import TensorType

    seed = int(custom_props.get("seed", 0))
    seq = int(custom_props.get("seq", 64))
    # one sizing grammar for every streamformer_lm consumer (registry
    # filter here, the llm/ decode tier, soak servers): layers/width/
    # heads/head_dim/max_seq all launch-line parameterizable
    cfg = config_from_custom(custom_props)
    params = host_init(lambda: init_params(cfg, seed))

    def forward(params, tokens):
        return (forward_logits(params, tokens, cfg).astype(jnp.float32),)

    in_info = TensorsInfo([TensorInfo(TensorType.INT32, (seq,))])
    out_info = TensorsInfo([TensorInfo(TensorType.FLOAT32,
                                       (cfg.vocab, seq))])
    return Model(name="streamformer_lm", forward=forward, params=params,
                 in_info=in_info, out_info=out_info)


def _register():
    from .registry import register_model

    register_model("streamformer_lm")(_build_registry_model)


_register()
