"""Pure-matmul MLP — the batching-efficiency probe model.

The cross-stream batching dispatcher (query/server.py) exists to turn
per-frame GEMV-shaped serving into full-tile GEMM-shaped serving.  This
model makes that effect directly measurable on any host: its FLOPs are
entirely dense matmuls, so the per-row cost of a batched invoke drops
exactly as much as the platform's GEMM beats its GEMV (MXU tiles on
TPU, BLAS kernels on the CPU test hosts) — no conv/batch-norm noise in
the measurement.  ``tools/soak.py --xbatch`` serves it behind per-frame
and batching query servers and commits the ratio.

Sizing is configurable through custom props so benches can pick an
arithmetic intensity that suits the host::

    tensor_filter framework=xla model=mlp custom=width:1024,depth:4

- input: ``(in_dim,)`` float32 (default 64 — small on the wire, so the
  loopback transport never becomes the bottleneck being measured);
- ``depth`` hidden layers of ``width``×``width`` matmuls with a relu
  (the FLOP body);
- output: ``(out_dim,)`` float32 logits (default 16).

Weights are deterministic random (``seed`` custom prop).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from ..tensor.info import TensorInfo, TensorsInfo
from ..tensor.types import TensorType
from .registry import Model, host_init, register_model


def build_mlp(custom: Dict[str, str]) -> Model:
    in_dim = int(custom.get("in_dim", 64))
    width = int(custom.get("width", 1024))
    depth = int(custom.get("depth", 4))
    out_dim = int(custom.get("out_dim", 16))
    seed = int(custom.get("seed", 0))
    if min(in_dim, width, depth, out_dim) < 1:
        raise ValueError("mlp: in_dim/width/depth/out_dim must be >= 1")

    def _init():
        key = jax.random.PRNGKey(seed)
        dims = [in_dim] + [width] * depth + [out_dim]
        layers = []
        for i, (a, b) in enumerate(zip(dims, dims[1:])):
            key, wk = jax.random.split(key)
            layers.append({
                "w": jax.random.normal(wk, (a, b), jnp.float32)
                * (1.0 / jnp.sqrt(a)),
                "b": jnp.zeros((b,), jnp.float32)})
        return {"layers": layers}

    params = host_init(_init)

    def forward(p, x):
        # unbatched frame contract: x is (in_dim,).  Row-vector matmuls
        # keep the batched executable (vmap over axis 0) a plain GEMM.
        h = x
        layers = p["layers"]
        for layer in layers[:-1]:
            h = jax.nn.relu(h @ layer["w"] + layer["b"])
        out = h @ layers[-1]["w"] + layers[-1]["b"]
        return (out,)

    in_info = TensorsInfo([TensorInfo(TensorType.FLOAT32, (in_dim,))])
    out_info = TensorsInfo([TensorInfo(TensorType.FLOAT32, (out_dim,))])
    return Model(name="mlp", forward=forward, params=params,
                 in_info=in_info, out_info=out_info)


register_model("mlp")(build_mlp)
