"""Model registry: named, jittable models the XLA filter backend serves.

The reference loads vendor model files (.tflite/.pb/.pt …) through per-SDK
subplugins (SURVEY.md §2.4).  TPU-native, a "model" is a pure JAX function +
params compiled by XLA; the registry replaces file-extension dispatch with
named model specs (file paths to orbax checkpoints also resolve here).

Sizing is a ``custom=`` grammar, not code: ``mlp``
(``custom=width:2048,depth:32``, models/mlp.py) and ``streamformer_lm``
(``custom=layers:8,width:512,max_seq:1024``,
models/streamformer_lm.config_from_custom — shared with the
``tensor_llm`` serving tier) both size from the launch line, so soak
and bench servers pick a realistically heavy model without edits.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..tensor.info import TensorsInfo


@dataclasses.dataclass
class Model:
    """A ready-to-serve model.

    ``forward(params, *inputs) -> tuple(outputs)`` must be jittable, operate
    on *unbatched* numpy-shaped arrays (one stream frame), and keep its
    FLOPs in MXU-friendly form (bf16 matmuls/convs).  ``in_info``/``out_info``
    use reference dim order (innermost first).
    """

    name: str
    forward: Callable[..., Tuple[Any, ...]]
    params: Any
    in_info: TensorsInfo
    out_info: TensorsInfo
    #: optional training step factory (loss, optimizer) for trainer parity
    make_train_step: Optional[Callable[..., Any]] = None


#: name -> build(custom_props: dict) -> Model
_MODELS: Dict[str, Callable[[Dict[str, str]], Model]] = {}


def register_model(name: str):
    def deco(build: Callable[[Dict[str, str]], Model]):
        _MODELS[name] = build
        return build
    return deco


def host_init(fn: Callable[[], Any]) -> Any:
    """Run a model-building computation (flax ``module.init`` etc.) on the
    host CPU device.

    Eager init on the default accelerator dispatches each of the model's
    hundreds of parameter/batch-norm ops separately, each paying its own
    tiny XLA compile plus a device round trip — on a tunneled TPU that is
    minutes of wall clock before the serving graph's single real compile
    even starts.  Params are moved to the serving device exactly once, at
    backend open (filter/backends/xla.py device_put), so nothing is lost by
    initializing on host.
    """
    import jax

    try:
        cpu = jax.devices("cpu")[0]
    except RuntimeError:  # cpu platform masked out (e.g. JAX_PLATFORMS=tpu)
        return fn()
    with jax.default_device(cpu):
        return fn()


def save_checkpoint(model: Model, path: str) -> None:
    """Persist model params as an orbax checkpoint (the framework's model
    artifact format — the role of the reference's .tflite/.pb model files)."""
    import os

    import orbax.checkpoint as ocp

    ckpt = ocp.StandardCheckpointer()
    ckpt.save(os.path.abspath(path), model.params)
    ckpt.wait_until_finished()


def restore_params(template, path: str):
    """Restore params matching ``template``'s structure from orbax."""
    import os

    import orbax.checkpoint as ocp

    ckpt = ocp.StandardCheckpointer()
    return ckpt.restore(os.path.abspath(path), target=template)


def graft_params(dst, src):
    """Copy every ``src`` leaf into ``dst`` where the tree path AND shape
    match; returns ``(grafted, n_copied)``.

    The transfer-learning helper behind real-trunk validation: the zoo's
    SSD/posenet heads share the MobileNetV2 trunk by flax auto-naming
    (ConvBN_0, InvertedResidual_0.., incl. batch_stats), so grafting the
    real ImageNet weights under an untrained head takes one call —
    head layers differ in shape and keep their fresh init."""
    n = 0
    out = {}
    for k, v in dst.items():
        if k in src and isinstance(v, dict) and isinstance(src[k], dict):
            out[k], m = graft_params(v, src[k])
            n += m
        elif (k in src and hasattr(v, "shape")
                and getattr(src[k], "shape", None) == v.shape):
            out[k] = src[k]
            n += 1
        else:
            out[k] = v
    return out, n


def _ensure_loaded() -> None:
    from . import (mlp, mobilenet_v2, ssd, deeplab_v3,  # noqa: F401
                   posenet, streamformer_lm, vit)  # noqa: F401


def get_model(name: str, custom_props: Optional[Dict[str, str]] = None) -> Model:
    _ensure_loaded()
    if name not in _MODELS:
        raise KeyError(f"unknown model {name!r}; known: {sorted(_MODELS)}")
    return _MODELS[name](custom_props or {})


def has_model(name: str) -> bool:
    try:
        _ensure_loaded()
    except Exception:  # pragma: no cover - import errors surface later
        return False
    return name in _MODELS


def list_models() -> List[str]:
    _ensure_loaded()
    return sorted(_MODELS)
