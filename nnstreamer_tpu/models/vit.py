"""Vision Transformer classifier — the attention-based vision family.

The reference's model zoo is conv-only (mobilenet/ssd/deeplab/posenet
fixtures under tests/test_models/models/); a TPU-native framework's
flagship compute is the MXU matmul, and a ViT is the model family whose
FLOPs are *pure* matmul — patch embedding, QKV projections, attention,
MLP.  This model ties the framework's marquee Pallas flash-attention
kernel (ops/flash_attention.py) into the vision streaming path: encoder
attention runs the streaming-softmax kernel on TPU and the naive jnp
oracle elsewhere, selected exactly like the LM path
(models/streamformer_lm.py forward_logits).

TPU-first choices:
- bfloat16 compute throughout, f32 params and f32 logits out;
- MXU-aligned defaults (ViT-S/16: dim 384 = 3 sublanes x 128 lanes,
  6 heads x 64 head-dim), patchify as a stride-16 conv;
- token count 197 (196 patches + CLS) exercises the kernel's
  pad-to-block path on every frame — odd lengths are the norm here;
- the whole uint8-frame -> logits path is one jitted graph, vmap-safe
  (the micro-batched streaming engine vmaps ``forward``; pallas_call
  lifts the batch axis into its grid).

Served through the registry backend::

    tensor_filter framework=registry model=vit custom=depth:12,dim:384

Weights are deterministic random (``seed`` prop); pretrained restore
goes through orbax via the ``checkpoint`` custom property, same as
every registry model.
"""

from __future__ import annotations

from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..tensor.info import TensorInfo, TensorsInfo
from ..tensor.types import TensorType
from .registry import Model, host_init, register_model


class _Attention(nn.Module):
    heads: int
    dtype: Any = jnp.bfloat16
    flash: bool | None = None

    @nn.compact
    def __call__(self, x):
        """x: (T, dim) one frame's token sequence (unbatched)."""
        t, dim = x.shape
        head_dim = dim // self.heads
        qkv = nn.Dense(3 * dim, dtype=self.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv.reshape(t, 3, self.heads, head_dim)
                            .swapaxes(0, 1), 3, axis=0)
        q, k, v = q[0], k[0], v[0]          # (T, H, D) kernel layout
        flash = self.flash
        if flash is None:
            # length-gated: at ViT's T≈197 naive XLA attention measured
            # FASTER than the kernel on hardware (see flash_wins)
            from ..ops.flash_attention import flash_wins

            flash = flash_wins(t)
        if flash:
            from ..ops.flash_attention import flash_attention

            attn = flash_attention(q, k, v, causal=False)
        else:
            from ..parallel.ring_attention import local_attention

            attn = local_attention(q, k, v, causal=False)
        out = attn.astype(self.dtype).reshape(t, dim)
        return nn.Dense(dim, dtype=self.dtype, name="proj")(out)


class _Block(nn.Module):
    heads: int
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    flash: bool | None = None

    @nn.compact
    def __call__(self, x):
        dim = x.shape[-1]
        y = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + _Attention(self.heads, self.dtype, self.flash)(y)
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = nn.Dense(self.mlp_ratio * dim, dtype=self.dtype)(y)
        y = nn.gelu(y)
        y = nn.Dense(dim, dtype=self.dtype)(y)
        return x + y


class ViT(nn.Module):
    """ViT-S/16 by default; every knob is a custom prop."""

    num_classes: int = 1000
    patch: int = 16
    dim: int = 384
    depth: int = 12
    heads: int = 6
    dtype: Any = jnp.bfloat16
    flash: bool | None = None

    @nn.compact
    def __call__(self, x):
        """x: bf16 (H, W, 3) in [-1, 1], one frame."""
        h, w, _ = x.shape
        x = nn.Conv(self.dim, (self.patch, self.patch),
                    strides=self.patch, padding="VALID",
                    dtype=self.dtype, name="patch_embed")(x[None])
        n_tok = (h // self.patch) * (w // self.patch)
        x = x.reshape(n_tok, self.dim)
        cls = self.param("cls", nn.initializers.zeros, (1, self.dim))
        x = jnp.concatenate([cls.astype(self.dtype), x], axis=0)
        pos = self.param("pos_embed",
                         nn.initializers.normal(stddev=0.02),
                         (n_tok + 1, self.dim))
        x = x + pos.astype(self.dtype)
        for _ in range(self.depth):
            x = _Block(self.heads, dtype=self.dtype, flash=self.flash)(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        logits = nn.Dense(self.num_classes, dtype=self.dtype,
                          name="head")(x[0])
        return logits.astype(jnp.float32)


def build_vit(custom_props: Dict[str, str]) -> Model:
    seed = int(custom_props.get("seed", 0))
    num_classes = int(custom_props.get("num_classes", 1000))
    size = int(custom_props.get("input_size", 224))
    patch = int(custom_props.get("patch", 16))
    dim = int(custom_props.get("dim", 384))
    depth = int(custom_props.get("depth", 12))
    heads = int(custom_props.get("heads", 6))
    dtype = jnp.dtype(custom_props.get("dtype", "bfloat16"))
    flash: bool | None = None
    if "attn" in custom_props:  # attn:flash / attn:naive overrides
        flash = custom_props["attn"] == "flash"
    module = ViT(num_classes=num_classes, patch=patch, dim=dim,
                 depth=depth, heads=heads, dtype=dtype, flash=flash)
    variables = host_init(lambda: module.init(
        jax.random.PRNGKey(seed), jnp.zeros((size, size, 3), dtype)))

    def forward(variables, frame):
        """frame: uint8 (H, W, 3) — preprocessing fused into the graph."""
        x = frame.astype(dtype) * (1.0 / 127.5) - 1.0
        return (module.apply(variables, x),)

    in_info = TensorsInfo([TensorInfo(TensorType.UINT8, (3, size, size))])
    out_info = TensorsInfo([TensorInfo(TensorType.FLOAT32, (num_classes,))])
    return Model(name="vit", forward=forward, params=variables,
                 in_info=in_info, out_info=out_info)


register_model("vit")(build_vit)
