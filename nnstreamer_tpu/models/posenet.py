"""PoseNet pose estimation — benchmark config 4.

Parity with the reference PoseNet fixture consumed by the ``pose_estimation``
decoder (reference: ext/nnstreamer/tensor_decoder/tensordec-pose.c: outputs
keypoint heatmaps (K × W' × H') and short-range offsets (2K × W' × H'),
decoder finds per-keypoint argmax + offset refinement and draws a skeleton).

TPU-first: MobileNetV2 backbone, two conv heads, one fused graph.
"""

from __future__ import annotations

from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..tensor.info import TensorInfo, TensorsInfo
from ..tensor.types import TensorType
from .mobilenet_v2 import _ConvBN, _InvertedResidual, _INVERTED_RESIDUAL_CFG
from .registry import Model, host_init, register_model

NUM_KEYPOINTS = 17  # COCO


class _PoseNet(nn.Module):
    num_keypoints: int = NUM_KEYPOINTS
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = _ConvBN(32, (3, 3), strides=2, dtype=self.dtype)(x[None])
        for t, ch, n, s in _INVERTED_RESIDUAL_CFG:
            for i in range(n):
                x = _InvertedResidual(ch, s if i == 0 else 1, t,
                                      dtype=self.dtype)(x)
        heat = nn.Conv(self.num_keypoints, (1, 1), dtype=self.dtype)(x)
        offs = nn.Conv(2 * self.num_keypoints, (1, 1), dtype=self.dtype)(x)
        return (jax.nn.sigmoid(heat.astype(jnp.float32))[0],
                offs.astype(jnp.float32)[0])


def build_posenet(custom_props: Dict[str, str]) -> Model:
    seed = int(custom_props.get("seed", 0))
    size = int(custom_props.get("input_size", 257))
    dtype = jnp.dtype(custom_props.get("dtype", "bfloat16"))
    module = _PoseNet(dtype=dtype)
    variables = host_init(lambda: module.init(
        jax.random.PRNGKey(seed), jnp.zeros((size, size, 3), dtype)))
    out_hw = jax.eval_shape(
        lambda v, x: module.apply(v, x), variables,
        jax.ShapeDtypeStruct((size, size, 3), dtype))[0].shape[:2]

    def forward(variables, frame):
        x = frame.astype(dtype) * (1.0 / 127.5) - 1.0
        return module.apply(variables, x)

    h, w = out_hw
    in_info = TensorsInfo([TensorInfo(TensorType.UINT8, (3, size, size))])
    out_info = TensorsInfo([
        TensorInfo(TensorType.FLOAT32, (NUM_KEYPOINTS, w, h)),
        TensorInfo(TensorType.FLOAT32, (2 * NUM_KEYPOINTS, w, h)),
    ])
    return Model(name="posenet", forward=forward, params=variables,
                 in_info=in_info, out_info=out_info)


register_model("posenet")(build_posenet)
