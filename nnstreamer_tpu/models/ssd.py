"""SSD-MobileNetV2 object detection — benchmark config 2.

Capability parity with the reference's SSD fixture consumed by the
``bounding_boxes`` decoder (reference decoder:
ext/nnstreamer/tensor_decoder/tensordec-boundingbox.c, mobilenet-ssd scheme).
Output contract matches the tflite SSD graph: raw box encodings
(4 × NUM_ANCHORS) + per-class scores (NUM_CLASSES × NUM_ANCHORS); decoding
(priors, NMS) happens in the decoder, as in the reference.

TPU-first: one fused XLA graph from uint8 frame to both heads, bf16 convs.
"""

from __future__ import annotations

from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..tensor.info import TensorInfo, TensorsInfo
from ..tensor.types import TensorType
from .mobilenet_v2 import _ConvBN, _InvertedResidual, _INVERTED_RESIDUAL_CFG
from .registry import Model, host_init, register_model

NUM_ANCHORS = 1917
NUM_CLASSES = 91


class _SSDBackboneHeads(nn.Module):
    num_classes: int = NUM_CLASSES
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        # MobileNetV2 feature extractor up to stride-16 and stride-32 maps.
        feats = []
        x = _ConvBN(32, (3, 3), strides=2, dtype=self.dtype)(x)
        for t, ch, n, s in _INVERTED_RESIDUAL_CFG:
            for i in range(n):
                x = _InvertedResidual(ch, s if i == 0 else 1, t,
                                      dtype=self.dtype)(x)
            if ch in (96, 320):
                feats.append(x)
        # Extra SSD feature maps (stride 64/128) for multi-scale anchors.
        y = _ConvBN(256, (1, 1), dtype=self.dtype)(x)
        y = _ConvBN(512, (3, 3), strides=2, dtype=self.dtype)(y)
        feats.append(y)
        z = _ConvBN(128, (1, 1), dtype=self.dtype)(y)
        z = _ConvBN(256, (3, 3), strides=2, dtype=self.dtype)(z)
        feats.append(z)
        # Per-map box + class heads; anchors per cell chosen to total 1917
        # for a 300x300 input (19x19*3 + 10x10*6 + 5x5*6 + 3x3*6 + pad).
        boxes, scores = [], []
        anchors_per_cell = (3, 6, 6, 6)
        for f, a in zip(feats, anchors_per_cell):
            b = nn.Conv(a * 4, (3, 3), padding="SAME", dtype=self.dtype)(f)
            s = nn.Conv(a * self.num_classes, (3, 3), padding="SAME",
                        dtype=self.dtype)(f)
            boxes.append(b.reshape(-1, 4))
            scores.append(s.reshape(-1, self.num_classes))
        boxes = jnp.concatenate(boxes, axis=0)
        scores = jnp.concatenate(scores, axis=0)
        return boxes.astype(jnp.float32), scores.astype(jnp.float32)


def build_ssd_mobilenet_v2(custom_props: Dict[str, str]) -> Model:
    seed = int(custom_props.get("seed", 0))
    size = int(custom_props.get("input_size", 300))
    dtype = jnp.dtype(custom_props.get("dtype", "bfloat16"))
    module = _SSDBackboneHeads(dtype=dtype)
    variables = host_init(lambda: module.init(
        jax.random.PRNGKey(seed), jnp.zeros((size, size, 3), dtype)))
    # Count actual anchors from a traced run (depends on input size).
    n_anchors = jax.eval_shape(
        lambda v, x: module.apply(v, x), variables,
        jax.ShapeDtypeStruct((size, size, 3), dtype))[0].shape[0]

    def forward(variables, frame):
        x = frame.astype(dtype) * (1.0 / 127.5) - 1.0
        boxes, scores = module.apply(variables, x)
        return boxes, jax.nn.sigmoid(scores)

    in_info = TensorsInfo([TensorInfo(TensorType.UINT8, (3, size, size))])
    out_info = TensorsInfo([
        TensorInfo(TensorType.FLOAT32, (4, n_anchors)),
        TensorInfo(TensorType.FLOAT32, (NUM_CLASSES, n_anchors)),
    ])
    return Model(name="ssd_mobilenet_v2", forward=forward, params=variables,
                 in_info=in_info, out_info=out_info)


register_model("ssd_mobilenet_v2")(build_ssd_mobilenet_v2)
