"""MobileNetV2 image classifier — the flagship/benchmark model.

Capability parity with the reference's benchmark fixture
(tests/test_models/models/mobilenet_v2_1.0_224_quant.tflite, used by the
image-labeling pipelines in BASELINE.md), re-implemented TPU-first in Flax:

- bfloat16 compute throughout (MXU-native), float32 params;
- inference-mode BatchNorm folded into running stats;
- uint8 HWC input, preprocessing fused into the jitted graph so the whole
  media→logits path is one XLA executable;
- 1001-way logits (background + 1000 ImageNet classes), matching the tflite
  fixture's output contract consumed by the image_labeling decoder.

Weights are deterministic random (seed via custom prop ``seed``); pretrained
restore goes through orbax when a checkpoint path is supplied via the
``checkpoint`` custom property.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..tensor.info import TensorInfo, TensorsInfo
from ..tensor.types import TensorType
from .registry import Model, host_init, register_model

# (expansion t, out channels c, repeats n, stride s) — standard V2 config
_INVERTED_RESIDUAL_CFG: Sequence[Tuple[int, int, int, int]] = (
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
)


class _ConvBN(nn.Module):
    features: int
    kernel: Tuple[int, int] = (3, 3)
    strides: int = 1
    groups: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.features, self.kernel, strides=self.strides,
                    padding="SAME", feature_group_count=self.groups,
                    use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=True, dtype=self.dtype)(x)
        return jnp.minimum(jax.nn.relu(x), 6.0)  # ReLU6


class _InvertedResidual(nn.Module):
    features: int
    strides: int
    expand: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        inp = x.shape[-1]
        hidden = inp * self.expand
        y = x
        if self.expand != 1:
            y = _ConvBN(hidden, (1, 1), dtype=self.dtype)(y)
        y = _ConvBN(hidden, (3, 3), strides=self.strides, groups=hidden,
                    dtype=self.dtype)(y)
        y = nn.Conv(self.features, (1, 1), use_bias=False, dtype=self.dtype)(y)
        y = nn.BatchNorm(use_running_average=True, dtype=self.dtype)(y)
        if self.strides == 1 and inp == self.features:
            y = y + x
        return y


class MobileNetV2(nn.Module):
    num_classes: int = 1001
    width: float = 1.0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        """x: bf16 NHWC in [-1, 1]."""
        def c(ch):
            return max(8, int(ch * self.width + 4) // 8 * 8)

        x = _ConvBN(c(32), (3, 3), strides=2, dtype=self.dtype)(x)
        for t, ch, n, s in _INVERTED_RESIDUAL_CFG:
            for i in range(n):
                x = _InvertedResidual(c(ch), s if i == 0 else 1, t,
                                      dtype=self.dtype)(x)
        x = _ConvBN(c(1280) if self.width > 1.0 else 1280, (1, 1),
                    dtype=self.dtype)(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


def build_mobilenet_v2(custom_props: Dict[str, str]) -> Model:
    seed = int(custom_props.get("seed", 0))
    num_classes = int(custom_props.get("num_classes", 1001))
    size = int(custom_props.get("input_size", 224))
    # bf16 is MXU-native on TPU; on CPU (tests) f32 avoids emulated-bf16 convs
    dtype = jnp.dtype(custom_props.get("dtype", "bfloat16"))
    module = MobileNetV2(num_classes=num_classes, dtype=dtype)
    variables = host_init(lambda: module.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, size, size, 3), dtype)))

    from ..utils.conf import parse_bool

    use_pallas = parse_bool(custom_props.get("use_pallas", "0"))

    def forward(variables, frame):
        """frame: uint8 (H, W, 3) — preprocessing fused into the graph
        (optionally as a Pallas VMEM kernel, ``use_pallas:1``)."""
        if use_pallas:
            from ..ops.preprocess import normalize_frame

            x = normalize_frame(frame, dtype=dtype)
        else:
            x = frame.astype(dtype) * (1.0 / 127.5) - 1.0
        logits = module.apply(variables, x[None])
        return (logits[0],)

    in_info = TensorsInfo([TensorInfo(TensorType.UINT8, (3, size, size))])
    out_info = TensorsInfo([TensorInfo(TensorType.FLOAT32, (num_classes,))])
    return Model(name="mobilenet_v2", forward=forward, params=variables,
                 in_info=in_info, out_info=out_info)


register_model("mobilenet_v2")(build_mobilenet_v2)
