"""Tensor type system (L1): dtypes, infos, configs, meta, buffers."""

from .types import (TENSOR_RANK_LIMIT, TENSOR_SIZE_EXTRA_LIMIT,
                    TENSOR_SIZE_LIMIT, Dimension, TensorFormat, TensorType,
                    dim_element_count, dim_is_static, dim_padded, dim_parse,
                    dim_to_np_shape, dim_to_string, dims_equal,
                    np_shape_to_dim)
from .info import TensorInfo, TensorsConfig, TensorsInfo
from .meta import (META_HEADER_SIZE, TensorMetaInfo, unwrap_flex, wrap_flex)
from .buffer import (BufferLease, CLOCK_TIME_NONE, SECOND, TensorBuffer,
                     TensorBufferPool, default_pool, frames_to_ns)

__all__ = [
    "TENSOR_RANK_LIMIT", "TENSOR_SIZE_LIMIT", "TENSOR_SIZE_EXTRA_LIMIT",
    "Dimension", "TensorFormat", "TensorType", "TensorInfo", "TensorsInfo",
    "TensorsConfig", "TensorMetaInfo", "TensorBuffer", "META_HEADER_SIZE",
    "CLOCK_TIME_NONE", "SECOND", "dim_parse", "dim_to_string", "dim_padded",
    "dims_equal", "dim_is_static", "dim_element_count", "dim_to_np_shape",
    "np_shape_to_dim", "wrap_flex", "unwrap_flex", "frames_to_ns",
    "BufferLease", "TensorBufferPool", "default_pool",
]
